"""IThreadPool: blocking-work offload for REAL deployments.

Reference: flow/IThreadPool.h + the EIO thread pool behind AsyncFileEIO
(fdbrpc/AsyncFileEIO.actor.h) — the reference never lets a blocking
syscall run on the Net2 loop; work ships to pool threads and ONLY a
completion record crosses back, drained by the main loop. Same shape
here: worker threads pull (fn, args) off a queue, post (future, result)
into a locked completion deque, and a reactor actor running on the flow
scheduler delivers them — futures are touched exclusively on the
scheduler thread, preserving the single-threaded actor model.

Wall-clock deployments only (tools/server --data-dir): the simulator
keeps its deterministic single thread and simulated disks.
"""

from __future__ import annotations

import threading
from collections import deque
from queue import Queue

from .future import Future
from .scheduler import TaskPriority, delay, spawn


class ThreadPool:
    """`run(fn, *args) -> Future` executing fn on a worker thread."""

    def __init__(self, n_threads: int = 4, name: str = "iopool"):
        self.name = name
        self._work: Queue = Queue()
        self._done: deque = deque()
        self._lock = threading.Lock()
        self._closing = False
        self._reactor_task = None
        #: futures handed out by run() and not yet delivered — close()
        #: resolves every one of them with io_error so no actor can
        #: wedge on a pool that has shut down
        self._outstanding: set = set()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(n_threads)]

    def start(self) -> None:
        for t in self._threads:
            t.start()
        self._reactor_task = spawn(self._reactor(),
                                   TaskPriority.READ_SOCKET,
                                   name=f"{self.name}.reactor")

    def close(self) -> None:
        """Shut down; MUST run on the scheduler thread (it resolves
        futures). Every future run() ever handed out that has not been
        delivered — queued, mid-flight on a worker, or sitting in the
        completion queue — resolves with io_error rather than wedging
        its awaiting actor."""
        from .error import error
        self._closing = True
        for _ in self._threads:
            self._work.put(None)
        if self._reactor_task is not None:
            self._reactor_task.cancel()
        with self._lock:
            pending = list(self._outstanding)
            self._outstanding.clear()
            self._done.clear()
        for fut in pending:
            if not fut.is_ready:
                fut.send_error(error("io_error"))

    def run(self, fn, *args) -> Future:
        """Execute `fn(*args)` in the pool; the returned Future resolves
        on the scheduler thread (exceptions arrive as io_error with the
        original in the trace)."""
        fut = Future()
        if self._closing:
            from .error import error
            fut.send_error(error("io_error"))
            return fut
        with self._lock:
            self._outstanding.add(fut)
        self._work.put((fn, args, fut))
        return fut

    # -- worker threads ---------------------------------------------------
    def _worker(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            fn, args, fut = item
            try:
                result = (True, fn(*args))
            except BaseException as e:  # noqa: BLE001 — ships to caller
                result = (False, e)
            with self._lock:
                self._done.append((fut, result))

    # -- scheduler-side delivery -----------------------------------------
    async def _reactor(self) -> None:
        from .error import error
        from .knobs import SERVER_KNOBS
        from .trace import SevWarnAlways, TraceEvent
        while not self._closing:
            while True:
                with self._lock:
                    item = self._done.popleft() if self._done else None
                if item is None:
                    break
                fut, (ok, value) = item
                with self._lock:
                    self._outstanding.discard(fut)
                if fut.is_ready:
                    continue   # close() already errored it
                if ok:
                    fut.send(value)
                else:
                    TraceEvent("ThreadPoolTaskError", self.name,
                               severity=SevWarnAlways).detail(
                        Error=repr(value)).log()
                    fut.send_error(error("io_error"))
            await delay(SERVER_KNOBS.tcp_reactor_poll_delay,
                        TaskPriority.READ_SOCKET)
