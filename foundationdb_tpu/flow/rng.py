"""Deterministic randomness + BUGGIFY fault-injection sites.

Reference: flow/DeterministicRandom.h, flow/IRandom.h (g_random), and the
BUGGIFY macro (flow/genericactors + Knobs randomization). Determinism is the
backbone of the test strategy: the same seed must reproduce the same run.
"""

from __future__ import annotations

import random as _pyrandom
from typing import Optional, Sequence


class DeterministicRandom:
    """Seeded PRNG with the reference's convenience surface (ref: flow/IRandom.h)."""

    def __init__(self, seed: int):
        self.seed = seed
        self._r = _pyrandom.Random(seed)

    def reseed(self, seed: int) -> None:
        """Re-seed in place (the ambient g_random is shared by reference)."""
        self.seed = seed
        self._r = _pyrandom.Random(seed)

    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform in [lo, hi) — half-open like the reference's randomInt."""
        return self._r.randrange(lo, hi)

    def random_choice(self, seq: Sequence):
        return seq[self.random_int(0, len(seq))]

    def random_shuffle(self, seq: list) -> None:
        self._r.shuffle(seq)

    def random_alpha_numeric(self, length: int) -> str:
        chars = "abcdefghijklmnopqrstuvwxyz0123456789"
        return "".join(self.random_choice(chars) for _ in range(length))

    def random_bytes(self, length: int) -> bytes:
        return self._r.randbytes(length)

    def random_unique_id(self) -> str:
        return "%016x%016x" % (self._r.getrandbits(64), self._r.getrandbits(64))

    def random_exp(self, mean: float) -> float:
        return self._r.expovariate(1.0 / mean) if mean > 0 else 0.0

    def coinflip(self) -> bool:
        return self._r.random() < 0.5

    def fork(self) -> "DeterministicRandom":
        """Derive an independent deterministic stream (for per-process RNGs)."""
        return DeterministicRandom(self._r.getrandbits(63))


class Buggifier:
    """Per-site random fault activation (ref: BUGGIFY, flow/Knobs.cpp:37+).

    Each distinct call site (identified by a string) is *activated* once per
    run with probability `activated_p`; an activated site then fires with
    probability `fire_p` on each evaluation.
    """

    def __init__(self, rng: Optional[DeterministicRandom] = None,
                 enabled: bool = False, activated_p: float = 0.25, fire_p: float = 0.25):
        self.rng = rng or DeterministicRandom(0)
        self.enabled = enabled
        self.activated_p = activated_p
        self.fire_p = fire_p
        self._sites: dict[str, bool] = {}

    def __call__(self, site: str) -> bool:
        if not self.enabled:
            return False
        act = self._sites.get(site)
        if act is None:
            act = self.rng.random01() < self.activated_p
            self._sites[site] = act
        return act and self.rng.random01() < self.fire_p


# Ambient instances, reset in place per simulation so that importers holding a
# reference observe the new seed (ref: g_random / g_nondeterministic_random).
g_random = DeterministicRandom(1)
g_buggify = Buggifier()


def set_seed(seed: int, buggify_enabled: bool = False) -> None:
    g_random.reseed(seed)
    g_buggify.rng = g_random.fork()
    g_buggify.enabled = buggify_enabled
    g_buggify._sites.clear()


def buggify(site: str) -> bool:
    return g_buggify(site)


def rng_state() -> tuple:
    """Opaque snapshot of the ambient RNG + BUGGIFY state, for tools
    that call set_seed() inside a process that may already be running
    a seeded simulation (networktest, clusterbench): capture before,
    restore_rng_state() in a finally — or the tool silently desyncs
    the caller's deterministic stream."""
    return (g_random.seed, g_random._r.getstate(), g_buggify.rng,
            g_buggify.enabled, dict(g_buggify._sites))


def restore_rng_state(state: tuple) -> None:
    seed, rstate, brng, benabled, sites = state
    g_random.seed = seed
    g_random._r.setstate(rstate)
    # the displaced fork object was untouched while we ran (set_seed
    # replaced it wholesale), so restoring the reference restores its
    # exact stream position
    g_buggify.rng = brng
    g_buggify.enabled = benabled
    g_buggify._sites.clear()
    g_buggify._sites.update(sites)
