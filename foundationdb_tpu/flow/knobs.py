"""Tunable knobs with simulation randomization.

Reference: flow/Knobs.h/.cpp (93 flow knobs), fdbserver/Knobs.cpp (284 server
knobs). Knobs are plain attributes initialized by ``init(name, default)``,
optionally distorted under BUGGIFY, and overridable by ``--knob_name=value``
style dicts.
"""

from __future__ import annotations

from typing import Callable, Optional

from .rng import g_buggify, g_random


class Knobs:
    def __init__(self):
        self._defaults: dict[str, float | int | str] = {}

    def init(self, name: str, default, buggify_fn: Optional[Callable[[], object]] = None):
        """Register a knob. `buggify_fn` returns a distorted value when the
        site fires under BUGGIFY (ref: `if (randomize && BUGGIFY)` in Knobs.cpp)."""
        value = default
        if buggify_fn is not None and g_buggify(f"knob/{name}"):
            value = buggify_fn()
        self._defaults[name] = default
        setattr(self, name.lower(), value)

    def set(self, name: str, value) -> None:
        setattr(self, name.lower(), value)


def make_server_knobs(randomize: bool = False, into: "Knobs | None" = None) -> Knobs:
    """Server knobs used by this framework (subset of fdbserver/Knobs.cpp,
    numerically identical defaults). Pass `into` to re-initialize an existing
    instance in place (so importers holding a reference see new values)."""
    k = into if into is not None else Knobs()

    def init(name, default, buggify_fn=None):
        k.init(name, default, buggify_fn if randomize else None)

    init("VERSIONS_PER_SECOND", 1_000_000)
    init("MAX_READ_TRANSACTION_LIFE_VERSIONS", 5 * 1_000_000,
         lambda: g_random.random_choice([1_000_000, 100_000, 10_000_000]))
    init("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 5 * 1_000_000,
         lambda: 1_000_000)
    init("MAX_COMMIT_BATCH_INTERVAL", 0.5, lambda: 2.0)
    init("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001)
    init("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 32768, lambda: 1000)
    init("COMMIT_TRANSACTION_BATCH_BYTES_MAX", 8 << 20, lambda: 4096)
    init("RESOLVER_STATE_MEMORY_LIMIT", 1 << 20)
    init("GRV_BATCH_INTERVAL", 0.0005, lambda: 0.01)
    init("DESIRED_TOTAL_BYTES", 150000, lambda: 200)
    init("STORAGE_DURABILITY_LAG", 5.0)
    init("TLOG_SPILL_THRESHOLD", 1500 << 20)
    init("TRANSACTION_SIZE_LIMIT", 10_000_000)
    init("KEY_SIZE_LIMIT", 10_000)
    init("VALUE_SIZE_LIMIT", 100_000)
    init("RESOLVER_REPLY_CACHE_SIZE", 256, lambda: 4)
    init("LOAD_BALANCE_BACKUP_DELAY", 0.005, lambda: 0.0005)
    # DD shard sizing on SAMPLED BYTES and write bandwidth (ref:
    # SHARD_MAX_BYTES / SHARD_MIN_BYTES_PER_KSEC family, Knobs.cpp;
    # storageserver byteSample at storageserver.actor.cpp:310)
    init("DD_SHARD_SPLIT_BYTES", 50_000, lambda: 6_000)
    init("DD_SHARD_MERGE_BYTES", 1_500, lambda: 400)
    init("DD_SHARD_SPLIT_BYTES_PER_KSEC", 2_000_000_000,
         lambda: 4_000_000)
    init("BYTE_SAMPLE_FACTOR", 100, lambda: 10)
    init("DD_BANDWIDTH_TAU", 5.0, lambda: 1.0)
    # -- storage heat plane (server/storage.py read-side metrics;
    # ref: StorageMetrics.actor bytesReadSample + getReadHotRanges +
    # TransactionTagCounter on the storage server). Default OFF: the
    # read hot paths pay one knob read per request and nothing else;
    # BUGGIFY arms it so sim runs exercise the accounting paths (the
    # plane is observe-only — arming never changes commit outcomes)
    init("STORAGE_HEAT_TRACKING", 0, lambda: 1)
    # read-byte sample inclusion factor (mirrors BYTE_SAMPLE_FACTOR on
    # the read side; ref: BYTE_SAMPLING_FACTOR for bytesReadSample)
    init("READ_SAMPLE_FACTOR", 100, lambda: 10)
    # sampled read keys kept per shard (lowest decayed rate evicted)
    init("READ_SAMPLE_MAX_KEYS", 256, lambda: 16)
    # a sub-range is read-hot when its read-bandwidth / sampled-byte
    # density exceeds this multiple of the shard's own density (ref:
    # SHARD_MAX_READ_DENSITY_RATIO behind ReadHotSubRangeRequest)
    init("READ_HOT_RANGE_RATIO", 8.0, lambda: 2.0)
    # byte-balanced buckets the shard's sample is split into for the
    # density scan (ref: the chunk math in getReadHotRanges). Finer
    # buckets name narrower hot ranges; a bucket much wider than the
    # truly-hot keys dilutes their density below the ratio
    init("READ_HOT_SUB_RANGE_CHUNKS", 16, lambda: 4)
    # cluster-wide storage_heat rollup at the CC: decaying top-K table
    # (ConflictHotSpots-style bounds — per-range state stays O(active))
    init("STORAGE_HEAT_HALF_LIFE", 10.0, lambda: 0.5)
    init("STORAGE_HEAT_MAX_ENTRIES", 64, lambda: 4)
    init("STORAGE_HEAT_TOP_K", 10)
    # auto-throttler input preference: with this armed the ratekeeper's
    # TagThrottler also reads per-STORAGE-SERVER tag busyness (one
    # tenant hammering one shard throttles that tenant even when its
    # cluster-wide rate looks modest — ROADMAP item 3's storage-aware
    # steering; enforcement semantics are unchanged, only detection)
    init("TAG_THROTTLE_STORAGE_BUSYNESS", 0, lambda: 1)
    init("DD_MIN_BALANCE_BYTES", 2_000, lambda: 600)
    init("CONF_SYNC_INTERVAL", 2.0, lambda: 0.3)
    init("WATCH_TIMEOUT", 900.0, lambda: 20.0)

    # -- master / recovery (ref: fdbserver/Knobs.cpp recovery family) --
    init("MAX_VERSION_ADVANCE", 5_000_000, lambda: 50_000)
    init("RECOVERY_WAIT_FOR_LOGS_DELAY", 0.5, lambda: 2.0)
    # straggler window for region-takeover lock acquisition (NOT
    # buggified smaller: a too-short window re-admits the data loss
    # the satellite path exists to prevent)
    init("REGION_LOCK_GRACE", 5.0)
    init("RESOLUTION_BALANCING_INTERVAL", 2.0, lambda: 0.3)
    init("RESOLUTION_METRICS_TIMEOUT", 2.0, lambda: 0.2)
    init("RESOLUTION_BALANCING_MIN_WORK", 100, lambda: 5)
    init("OLD_LOG_CLEANUP_INTERVAL", 1.0, lambda: 0.1)
    init("TLOG_LOCK_TIMEOUT", 2.0, lambda: 0.5)

    # -- cluster controller (ref: CC_* / FAILURE_* knobs) --------------
    init("CC_WORKER_POLL_DELAY", 0.05, lambda: 0.5)
    init("FAILURE_DETECTION_INTERVAL", 0.1, lambda: 0.5)
    init("FAILURE_MONITOR_PING_TIMEOUT", 0.5, lambda: 0.05)
    init("LATENCY_PROBE_INTERVAL", 5.0, lambda: 0.5)
    init("METRIC_SAMPLE_INTERVAL", 1.0, lambda: 0.1)
    # -- observability (ref: Trace.cpp suppression + traceCounters) ----
    # events below this severity never materialize (0 keeps everything;
    # sim tests assert on SevDebug-level stitching, so the floor is an
    # operator knob, not a default)
    init("TRACE_SEVERITY_MIN", 0)
    # roll the trace file once it exceeds this many bytes (ref: the
    # reference's 10 MB trace_roll_size / FileTraceLogWriter rolls);
    # 0 disables rolling
    init("TRACE_ROLL_SIZE", 10 << 20, lambda: 4096)
    # cadence of the per-role *Metrics counter rollup TraceEvents
    init("TRACE_COUNTERS_INTERVAL", 1.0, lambda: 0.1)
    # cross-process trace propagation (ISSUE 16): 1 = TCP requests that
    # carry a debug id ride TRACED frames (rpc/tcp.py kinds 3/4) with
    # the sender's process identity, its open parent span id per debug
    # id, and the four NTP-style hop timestamps tracemerge uses to
    # estimate per-process clock offsets; 0 = only kinds 0/1/2 ever
    # leave the process — wire bytes byte-identical to the pre-knob
    # transport (the pinned off posture). Deliberately NOT buggified
    # (same reasoning as INTERVAL_PACKED_FEED: a new buggify site
    # consumes a draw from the shared buggify stream and would shift
    # every later knob's randomization on existing seeds, invalidating
    # the pinned chaos baselines); the armed path is exercised by the
    # soak harness and tests/test_distributed_trace.py instead
    init("TRACE_PROPAGATION", 0)
    # -- longitudinal observability (ISSUE 17): TimeKeeper + metric
    # history + SLO engine. METRIC_HISTORY is the master gate: 0 (the
    # default) spawns NONE of the plane's actors — the cluster is
    # byte-identical to the pre-plane behavior (the pinned off
    # posture). Deliberately NOT buggified (the INTERVAL_PACKED_FEED /
    # TRACE_PROPAGATION discipline: a new buggify site consumes a draw
    # from the shared buggify stream and would shift every later
    # knob's randomization on existing seeds, invalidating the pinned
    # chaos baselines); the armed paths are exercised by the soak
    # harness, smoke --slo, and tests/test_longitudinal.py instead.
    init("METRIC_HISTORY", 0)
    # version<->wallclock map cadence + retention (ref: the reference's
    # fdbserver/TimeKeeper.actor.cpp writing \xff\x02/timeKeeper/ every
    # SYSTEM_MONITOR_FREQUENCY with a bounded day count; sim-scaled)
    init("TIMEKEEPER_INTERVAL", 1.0)
    init("TIMEKEEPER_RETENTION", 120.0)
    # metric-history recorder: sample cadence, samples per persisted
    # chunk row, and the shared retention window the janitor trims BOTH
    # the new \xff\x02/metrics/ series and the legacy tuple-space
    # counter series to (satellite: one bounded-scan janitor)
    init("METRIC_HISTORY_INTERVAL", 1.0)
    init("METRIC_HISTORY_CHUNK", 8)
    init("METRIC_RETENTION_SECONDS", 300.0)
    init("METRIC_JANITOR_INTERVAL", 10.0)
    # SLO engine (server/slo.py): evaluation cadence, p99 ceilings for
    # the commit/GRV probes (milliseconds), the recovery-time bound,
    # the error budget (fraction of requests allowed over the latency
    # band edge), and the multiwindow burn-rate alert shape (a la the
    # SRE-workbook fast/slow windows: page only when BOTH windows burn
    # the budget faster than their rate)
    init("SLO_EVAL_INTERVAL", 1.0)
    init("SLO_COMMIT_P99_MS", 250.0)
    init("SLO_GRV_P99_MS", 250.0)
    init("SLO_RECOVERY_SECONDS", 120.0)
    init("SLO_ERROR_BUDGET", 0.01)
    init("SLO_BURN_FAST_WINDOW", 10.0)
    init("SLO_BURN_SLOW_WINDOW", 60.0)
    init("SLO_BURN_FAST_RATE", 14.0)
    init("SLO_BURN_SLOW_RATE", 3.0)
    # breach-drill latency injection (tools/soak.py --breach-at): extra
    # seconds added to every proxy commit batch while armed, so a soak
    # can prove the burn-rate alert actually fires. 0 = off (one knob
    # read per batch, no delay, no schedule change). Not buggified —
    # chaos storms inject latency through the network plane; this knob
    # exists for the DIRECTED drill whose detection time is asserted.
    init("COMMIT_LATENCY_INJECTION", 0.0)
    # -- latency forensics (ISSUE 18): commit critical-path
    # decomposition + per-process resource telemetry + the flight
    # recorder. CRITICAL_PATH is the master gate: 0 (the default)
    # records nothing, spawns no CC loop, and keeps the commit path
    # byte-identical to the pre-plane behavior (the pinned off
    # posture). Deliberately NOT buggified (the TRACE_PROPAGATION /
    # METRIC_HISTORY discipline: a new buggify site consumes a draw
    # from the shared buggify stream and would shift every later
    # knob's randomization on existing seeds, invalidating the pinned
    # chaos baselines); the armed paths are exercised by smoke --path
    # and tests/test_critical_path.py instead.
    init("CRITICAL_PATH", 0)
    # CC cadence for folding the per-role path recorders into the
    # cluster-wide decaying top-cause table
    init("CRITICAL_PATH_INTERVAL", 2.0)
    # decomposition-invariant bound: |sum(stations) - end_to_end| must
    # stay within this FRACTION of the end-to-end latency (the station
    # timestamps are consecutive flow.now() reads, so the residual is
    # float rounding, not missing time — the bound is pinned by test)
    init("CRITICAL_PATH_TOLERANCE", 0.05)
    # decaying dominant-station table (ConflictHotSpots bounds)
    init("CRITICAL_PATH_HALF_LIFE", 10.0)
    # per-role recorder sample buffer drained by the CC loop
    init("CRITICAL_PATH_SAMPLE_MAX", 512)
    # per-OS-process resource sampling cadence (tools/soak + bench
    # workers; wall-clock domain, so never determinism-sensitive)
    init("PROCESS_METRICS_INTERVAL", 2.0)
    # flight recorder ring capacity (flow/flightrec.py): recent trace
    # events kept in memory per process, independent of file rotation
    init("FLIGHTREC_SIZE", 512)
    # directed fsync-stall injection: extra seconds added inside every
    # TLog durability leg while armed — COMMIT_LATENCY_INJECTION's
    # tlog twin, so a smoke cell can force tlog_fsync to dominate the
    # critical-path table. 0 = off (one knob read per fsync, no delay,
    # no schedule change). Not buggified, same reasoning as the gate.
    init("TLOG_FSYNC_INJECTION", 0.0)
    # conflict hot-spot table (resolver-side attribution aggregation):
    # score half-life seconds, table capacity, rows surfaced in status
    init("HOT_SPOT_HALF_LIFE", 10.0, lambda: 0.5)
    init("HOT_SPOT_MAX_ENTRIES", 64, lambda: 4)
    init("HOT_SPOT_TOP_K", 10)
    # health rollup thresholds (the status `messages` array): conflict
    # fraction of recently-resolved txns that reads as pathological,
    # and how many versions storage may trail the log frontier
    init("HEALTH_CONFLICT_RATE", 0.25)
    init("HEALTH_STORAGE_LAG_VERSIONS", 2_000_000)
    # sampled transaction profiling (ref: the CSI_SAMPLING client knob
    # + TRANSACTION_LOGGING_ENABLE): fraction of transactions whose
    # ClientLogEvent stream persists into \xff\x02/fdbClientInfo/.
    # 0 (the default) compiles the sampler out of the client hot path
    # entirely — never buggified: sampling changes keyspace traffic.
    init("PROFILE_SAMPLE_RATE", 0.0)
    # chunk size for persisted profile records (buggified tiny so sim
    # runs exercise multi-chunk reassembly)
    init("PROFILE_CHUNK_BYTES", 4096, lambda: 64)
    # profile-record retention + janitor cadence (the clientlog layer
    # trims records older than the retention window)
    init("PROFILE_RETENTION_SECONDS", 300.0, lambda: 5.0)
    init("PROFILE_JANITOR_INTERVAL", 10.0, lambda: 0.5)
    # run-loop steps longer than this (wall seconds) emit a SlowTask
    # TraceEvent and enter the slow-task table (ref: Net2's
    # SLOWTASK_PROFILING_LOG_INTERVAL family). 0 disables slow-task
    # sampling — and, with SIM_TASK_STATS also off, the run loop skips
    # the per-step monotonic() pair entirely (busy_seconds then
    # accrues through windowed coarse accounting)
    init("SLOW_TASK_THRESHOLD", 0.05)
    # -- sim-perf attribution plane (ROADMAP item 6: profile the run
    # loop before refactoring it). SIM_TASK_STATS=1 arms per-task-name
    # wall-µs accounting in the scheduler AND per-message-type
    # accounting in the sim network at cluster boot. Default off; the
    # off posture is byte-identical sim behavior (profiling only ever
    # reads the wall clock, never the sim timeline) — never buggified:
    # it would add wall overhead to every randomized CI cell for no
    # coverage (the armed-vs-off equivalence is its own pinned test)
    init("SIM_TASK_STATS", 0)
    # bounded-table caps: task names beyond the cap fold into
    # "(other)"; message types beyond theirs into "(other)"
    init("SIM_TASK_STATS_MAX_NAMES", 256)
    init("SIM_MSG_STATS_MAX_TYPES", 128)
    # rows the status document / exporter / storm reports surface from
    # the task and message tables (the full tables ride tools/simprof)
    init("SIM_TASK_STATS_TOP_K", 10)
    # time 1-in-N kernel dispatches with a block_until_ready fence
    # (first call per shape bucket is always timed: that's the compile);
    # 0 disables the periodic fence entirely so the streamed bench can
    # keep its async pipeline
    init("KERNEL_PROFILE_EVERY", 64, lambda: 1)
    # resolve pipeline: max conflict batches in flight between submit
    # and drain (models/conflict_set.py ResolvePipeline). 1 degenerates
    # to the fully synchronous submit-block-read path; buggified tiny
    # so sim runs stress the backpressure/forced-drain machinery
    init("RESOLVE_PIPELINE_DEPTH", 4, lambda: 1)
    # packed single-buffer interval feed (models/tpu_resolver.py
    # _dispatch): 1 = every interval batch rides ONE H2D transfer;
    # 0 = the legacy ~12-transfer feed (bit-exact parity baseline and
    # operational rollback). Deliberately NOT buggified: a new knob
    # buggify site consumes a draw from the shared buggify stream and
    # would shift every later knob's randomization on existing seeds
    # (invalidating the pinned chaos-storm baselines); the fallback
    # path is exercised by bench.py --dry and the directed parity
    # tests instead, and verdicts are identical by construction
    init("INTERVAL_PACKED_FEED", 1)
    init("DD_POLL_INTERVAL", 2.0, lambda: 0.3)
    init("DD_MOVE_NUDGE_INTERVAL", 0.1, lambda: 0.5)
    # how long a team may stay degraded before DD rebuilds the missing
    # replica. Must exceed SIM_REBOOT_DELAY under EVERY knob combination
    # (default 7.5 > buggified reboot 5.0; buggified 15.0 likewise) so
    # an auto-rebooting worker always wins the race (ref:
    # DDTeamCollection's server-failure rebuild delays)
    init("DD_TEAM_REBUILD_DELAY", 7.5, lambda: 15.0)
    # a live replica this many versions behind the log frontier with NO
    # progress for the rebuild delay is wedged (e.g. it rebooted at a
    # version whose covering log generation already retired) and gets
    # rebuilt like a dead one (ref: the reference removing storage
    # servers that cannot catch up)
    init("DD_REPLICA_STUCK_VERSIONS", 100_000)
    init("STORAGE_RECRUIT_RECOVERY_TIMEOUT", 30.0, lambda: 3.0)
    init("COORDINATOR_FORWARD_TIMEOUT", 2.0, lambda: 0.2)

    # -- coordination / election (ref: POLLING_FREQUENCY etc.) ---------
    init("CANDIDACY_POLL_INTERVAL", 0.05, lambda: 0.3)
    init("COORDINATOR_FORWARD_HOPS_MAX", 8)

    # -- storage (ref: STORAGE_* / FETCH_* knobs) ----------------------
    init("STORAGE_PULL_IDLE_DELAY", 0.2, lambda: 1.0)
    init("STORAGE_PEEK_TIMEOUT", 5.0, lambda: 0.5)
    init("STORAGE_ROLLBACK_DELAY", 0.05, lambda: 0.5)
    init("STORAGE_COMMIT_INTERVAL", 0.05, lambda: 0.5)
    init("WATCH_EXPIRY_SWEEP_INTERVAL", 30.0, lambda: 1.0)

    # -- tlog (ref: TLOG_* knobs) --------------------------------------
    init("TLOG_STALLED_PEEK_DELAY", 1.0, lambda: 0.05)
    init("TLOG_FSYNC_DELAY", 0.0005, lambda: 0.01)
    # BUGGIFY-injected commit reordering window (the durable-path race
    # stressor; 0 disables even the buggify branch)
    init("BUGGIFY_TLOG_COMMIT_DELAY_MAX", 0.01, lambda: 0.1)
    # fetchKeys streaming chunk (ref: FETCH_BLOCK_BYTES — rows here,
    # shard moves stream in bounded chunks)
    init("FETCH_BLOCK_ROWS", 64, lambda: 3)

    # -- proxy / GRV (ref: START_TRANSACTION_* knobs) ------------------
    init("GRV_RATE_POLL_INTERVAL", 0.1, lambda: 1.0)
    init("GRV_CONFIRM_TIMEOUT", 2.0)
    init("GRV_PEER_SUSPECT_DURATION", 1.0, lambda: 0.01)
    init("GRV_BURST_INTERVALS", 10, lambda: 1)
    init("RATEKEEPER_POLL_TIMEOUT", 1.0, lambda: 0.1)

    # -- enforced admission control (server/admission.py +
    # server/tag_throttler.py — ROADMAP item 3). All planes default
    # OFF, the PR 8 posture: the GRV path is byte-identical until an
    # operator (or the --overload smoke) arms them; BUGGIFY arms them
    # randomly so chaos/sim runs exercise the throttled paths.
    # per-priority GRV token buckets at every proxy, refilled from the
    # ratekeeper's budget SPLIT across proxies (ref: transactionRate /
    # proxy count in GetRateInfoReply), with bounded queues
    init("GRV_ADMISSION_CONTROL", 0, lambda: 1)
    # per-priority admission queue depth cap; overflow is rejected with
    # retryable proxy_memory_limit_exceeded (ref: the GRV proxy's
    # queue-memory rejection) rather than silently growing
    init("GRV_QUEUE_MAX", 10_000, lambda: 4)
    # longest a queued GRV may wait before it is shed with the same
    # retryable error — the bound that keeps ADMITTED p99 meaningful
    init("GRV_QUEUE_MAX_WAIT", 2.0, lambda: 0.05)
    # per-tag throttling: proxies watch \xff\x02/throttledTags/ and
    # enforce per-tag buckets IN FRONT of the class buckets; clients
    # honor throttles by delaying locally before their next GRV
    init("TAG_THROTTLING", 0, lambda: 1)
    # ratekeeper-side auto-throttler: busy tags get auto rows written
    # into the same system keyspace manual throttles use
    init("AUTO_TAG_THROTTLING", 0, lambda: 1)
    init("TAG_THROTTLE_POLL_INTERVAL", 0.5, lambda: 0.05)
    init("TAG_THROTTLE_UPDATE_INTERVAL", 0.5, lambda: 0.1)
    # smoothed per-tag started-transaction rate at which the
    # auto-throttler reads a tag as abusive
    init("TAG_THROTTLE_BUSY_RATE", 50.0, lambda: 2.0)
    # auto-throttle target: the busy tag is cut to this fraction of
    # its observed rate (floored at TAG_THROTTLE_MIN_TPS)
    init("TAG_THROTTLE_TARGET_FRACTION", 0.25)
    init("TAG_THROTTLE_MIN_TPS", 1.0)
    init("TAG_THROTTLE_DURATION", 5.0, lambda: 0.5)
    # per-tag parked-request queue bound; overflow rejects with
    # retryable tag_throttled
    init("TAG_THROTTLE_QUEUE_MAX", 256, lambda: 2)
    # cap on the client-side local delay honored per GRV (the server
    # still enforces; the cap only bounds one wait)
    init("CLIENT_TAG_BACKOFF_MAX", 2.0, lambda: 0.1)

    # -- QoS telemetry plane (per-role saturation signals) -------------
    # cluster-controller collection cadence for QosSamples; 0 disables
    # the plane entirely (roles then pay nothing — signals are computed
    # pull-style at sample time, never on the hot paths)
    init("QOS_SAMPLE_INTERVAL", 1.0, lambda: 0.1)
    # time constant for every smoothed QoS signal (flow/smoother.py);
    # live-tunable: smoothers read it per sample
    init("QOS_SMOOTHING_TAU", 1.0)
    # proxy-side per-priority / per-tag transaction accounting
    # (started/committed/conflicted per class + a bounded decaying
    # top-K tag table); 0 compiles it down to one knob read per batch
    init("QOS_TAG_ACCOUNTING", 1)
    # tag-table bounds + decay (ConflictHotSpots-style): busyness score
    # half-life seconds, table capacity, rows surfaced in status
    init("QOS_TAG_HALF_LIFE", 10.0, lambda: 0.5)
    init("QOS_TAG_MAX_ENTRIES", 64, lambda: 4)
    init("QOS_TAG_TOP_K", 10)
    # tags per transaction + tag length caps (ref: the reference's
    # MAX_TAGS_PER_TRANSACTION / MAX_TRANSACTION_TAG_LENGTH)
    init("MAX_TAGS_PER_TRANSACTION", 5)
    init("MAX_TRANSACTION_TAG_LENGTH", 16)

    # -- ratekeeper (ref: Ratekeeper.actor.cpp knobs) ------------------
    init("RK_UPDATE_INTERVAL", 0.1, lambda: 0.02)
    init("RK_MIN_RATE", 10.0)
    init("RK_MAX_RATE", 1e9)
    init("RK_TLOG_BACKLOG_LIMIT", 10_000, lambda: 500)
    # spring-zone queue-byte controller (ref: TARGET_BYTES_PER_STORAGE_
    # SERVER / _TLOG + SPRING_BYTES_* + SMOOTHING_AMOUNT, sim-scaled)
    init("RK_TARGET_STORAGE_QUEUE_BYTES", 4 << 20, lambda: 1 << 14)
    init("RK_SPRING_STORAGE_QUEUE_BYTES", 1 << 20)
    init("RK_TARGET_TLOG_QUEUE_BYTES", 64 << 20, lambda: 1 << 16)
    init("RK_SPRING_TLOG_QUEUE_BYTES", 16 << 20)
    init("RK_BATCH_TARGET_FRACTION", 0.5)
    init("RK_SMOOTHING_SECONDS", 1.0)
    # resolve-pipeline saturation input (PR 4's occupancy/forced-drain
    # counters as a throttle signal): a smoothed forced-drain rate
    # above the target means batches are hitting the depth backpressure
    # faster than the device drains them — spring-zone throttle like
    # the queue-byte inputs (0 disables the input)
    init("RK_PIPELINE_FORCED_DRAIN_LIMIT", 50.0, lambda: 2.0)
    init("RK_PIPELINE_FORCED_DRAIN_SPRING", 25.0)

    # -- region / log router (ref: LOG_ROUTER_* knobs) -----------------
    init("LOG_ROUTER_PEEK_TIMEOUT", 2.0, lambda: 0.2)
    init("LOG_ROUTER_IDLE_DELAY", 0.2, lambda: 1.0)
    init("LOG_ROUTER_RETRY_DELAY", 0.1, lambda: 0.5)
    init("REGION_SETTLE_DELAY", 0.05, lambda: 0.5)

    # -- backup agent (ref: BACKUP_* knobs) ----------------------------
    init("BACKUP_TAIL_IDLE_DELAY", 0.1, lambda: 0.5)
    init("BACKUP_PEEK_TIMEOUT", 2.0, lambda: 0.2)
    init("BACKUP_SOURCE_RETRY_DELAY", 0.2, lambda: 1.0)
    init("BACKUP_NUDGE_INTERVAL", 0.05, lambda: 0.5)
    # the cluster-side driver polling the \xff\x02/backup/ control rows
    # (ref: the backup agent's task poll delay)
    init("BACKUP_DRIVER_POLL_INTERVAL", 0.25, lambda: 0.05)
    init("BACKUP_DRIVER_UPLOAD_INTERVAL", 1.0, lambda: 0.2)

    # -- cluster chaos (ref: sim2.actor.cpp swizzling/clogging/kill
    # workloads; server/chaos.py scenario storms) ----------------------
    # how long a partition_minority scenario keeps the machine sets
    # separated before healing
    init("CHAOS_PARTITION_SECONDS", 4.0, lambda: 8.0)
    # per-link swizzle window: while swizzled, messages draw extra
    # reorder latency and one-way datagrams may duplicate
    init("CHAOS_SWIZZLE_SECONDS", 1.5, lambda: 4.0)
    # extra latency spread on a swizzled link (uniform draw added per
    # message — far wider than SIM_LATENCY_MAX, so delivery order
    # genuinely scrambles)
    init("CHAOS_SWIZZLE_LATENCY", 0.25, lambda: 1.0)
    # probability a one-way datagram on a swizzled link delivers twice
    # (receivers must be idempotent; request/reply pairs never
    # duplicate — the transport models a TCP-like connection)
    init("CHAOS_SWIZZLE_DUP_PROB", 0.25)
    # bytes flipped by a raw sector-corruption injection
    init("CHAOS_CORRUPT_BYTES", 4)
    # kill rounds driven by the kill_mid_commit / recovery-storm
    # scenarios
    init("CHAOS_KILL_ROUNDS", 3, lambda: 5)
    # sim-seconds a storm allows between HEAL and a quiesced,
    # consistency-clean cluster (the bounded-recovery oracle)
    init("CHAOS_RECOVERY_BOUND", 120.0)
    # probability that the LAST surviving unsynced write is torn (a
    # seeded prefix survives instead of the whole write) at power loss
    # — the in-flight write at the instant the power fails (ref:
    # AsyncFileNonDurable's partial-write mode). Recovery must already
    # tolerate arbitrary tail damage (CRC cut), so this is on by
    # default and amplified under BUGGIFY
    init("SIM_TORN_WRITE_PROB", 0.25, lambda: 0.75)
    # a critical transaction-subsystem process unreachable (ping-failed
    # but alive — a partitioned or wedged machine) for this long ends
    # the epoch exactly like a death (ref: waitFailure heartbeats — the
    # reference's failure detection is network-based, so partitions
    # trigger real recoveries). Deliberately above every ordinary
    # BUGGIFY clog window so transient clogging never thrashes epochs;
    # never buggified for the same reason
    init("FAILURE_UNREACHABLE_SECONDS", 2.0)

    # -- simulation environment (ref: sim2 latency/reboot model) -------
    init("SIM_REBOOT_DELAY", 0.5, lambda: 5.0)
    init("QUIET_DATABASE_POLL", 0.25)
    init("SIM_LATENCY_MIN", 0.0002)
    init("SIM_LATENCY_MAX", 0.002, lambda: 0.02)
    init("SIM_CLOG_EXTRA_LATENCY", 0.05)
    init("SIM_DISK_WRITE_LATENCY", 0.0001)
    init("SIM_DISK_SYNC_LATENCY", 0.0005, lambda: 0.01)
    init("SIM_DISK_WRITE_JITTER", 0.0002)
    init("SIM_DISK_SYNC_JITTER", 0.002)
    init("SIM_POWER_LOSS_DROP_PROB", 0.5)

    # -- client (ref: fdbclient/Knobs.cpp) -----------------------------
    init("CLIENT_REQUEST_TIMEOUT", 5.0)
    init("CLIENT_RETRY_BACKOFF_MIN", 0.001)
    init("CLIENT_RETRY_BACKOFF_JITTER", 0.01, lambda: 0.1)
    init("CLIENT_DEFAULT_MAX_RETRIES", 100)
    # poll pace while re-finding the controller through coordinators
    # (ref: MonitorLeader's COORDINATOR_RECONNECTION_DELAY)
    init("CLIENT_REDISCOVER_DELAY", 0.5, lambda: 2.0)
    # remote (TCP gateway) client request timeout + reply-poll pace
    init("REMOTE_CLIENT_REQUEST_TIMEOUT", 30.0)
    init("REMOTE_CLIENT_POLL_DELAY", 0.005)

    # -- consistency check (ref: ConsistencyCheck workload knobs) ------
    init("CONSISTENCY_CHECK_PAGE_ROWS", 10_000, lambda: 7)
    init("CONSISTENCY_CHECK_READ_TIMEOUT", 30.0)

    # -- engines (ref: page/file sizing knobs; btree page geometry is a
    # module constant — an on-disk format, not a runtime tunable)
    init("DISK_QUEUE_FILE_SIZE", 1 << 20, lambda: 4096)

    # worker threads for blocking real-disk IO (wall-clock only;
    # ref: the EIO pool size behind AsyncFileEIO)
    init("DISK_IO_THREADS", 4)

    # -- real TCP transport (wall-clock; never BUGGIFY-distorted) ------
    init("TCP_HANDSHAKE_TIMEOUT", 5.0)
    init("TCP_CONNECT_TIMEOUT", 5.0)
    init("REMOTE_CONNECT_TIMEOUT", 30.0)
    init("REMOTE_CALL_TIMEOUT", 600.0)

    # -- supervisor (ref: fdbmonitor restart backoff) ------------------
    init("MONITOR_BACKOFF_INITIAL", 0.5)
    init("MONITOR_BACKOFF_MAX", 30.0)
    init("MONITOR_BACKOFF_RESET_AFTER", 10.0)

    # -- layers (ref: TaskBucket timeout + backup chunking) ------------
    init("TASKBUCKET_LEASE_SECONDS", 10.0, lambda: 0.5)
    init("BACKUP_AGENT_POLL_DELAY", 0.1, lambda: 1.0)
    init("BACKUP_TOOL_POLL_DELAY", 0.25, lambda: 2.0)
    init("SERVER_STATUS_POLL_DELAY", 0.5)
    # model-checker workload retry backoff + watch budget
    init("WORKLOAD_RETRY_DELAY_MIN", 0.05)
    init("WORKLOAD_RETRY_DELAY_SPAN", 0.2, lambda: 2.0)
    init("WORKLOAD_WATCH_TIMEOUT", 30.0)
    # real-TCP reactor inbox poll pace (wall-clock)
    init("TCP_REACTOR_POLL_DELAY", 0.001)
    init("BACKUP_LOG_CHUNK_RECORDS", 500, lambda: 3)
    init("BLOBSTORE_REQUEST_TIMEOUT", 10.0)
    # ref: BlobStore.actor.cpp knobs — request retry budget with
    # exponential backoff (wall-clock: the client is host-side IO),
    # multipart threshold/part sizing, and the signed-date replay
    # window for request authentication
    init("BLOBSTORE_REQUEST_TRIES", 5)
    init("BLOBSTORE_BACKOFF_MIN", 0.05)
    init("BLOBSTORE_BACKOFF_MAX", 2.0)
    init("BLOBSTORE_MULTIPART_THRESHOLD", 256 * 1024)
    init("BLOBSTORE_MULTIPART_PART_BYTES", 128 * 1024)
    init("BLOBSTORE_AUTH_WINDOW", 300.0)
    init("METRIC_LOGGER_INTERVAL", 1.0)

    # -- conflict-set backends (ref: resolver window GC cadence) -------
    init("CONFLICT_SET_COMPACT_EVERY", 16, lambda: 1)

    # -- conflict prediction & transaction repair (server/scheduler.py,
    # server/repair.py — ROADMAP item 2; steering per arXiv:2409.01675,
    # repair per arXiv:1403.5645). All three planes default OFF so the
    # abort-only pipeline is byte-identical until an operator (or the
    # --contention smoke) arms them; BUGGIFY arms them randomly so
    # chaos/sim runs exercise the new decision paths under faults.
    # proxy admission scheduling: defer commits whose predicted
    # conflict probability crosses SCHED_CONFLICT_THRESHOLD
    init("CONFLICT_SCHEDULING", 0, lambda: 1)
    init("SCHED_CONFLICT_THRESHOLD", 0.5, lambda: 0.05)
    # hot-score -> probability mapping: p = score / (score + scale)
    init("SCHED_HOT_SCORE_SCALE", 5.0)
    # bounded deferral: a deferred commit never waits longer than this
    init("SCHED_MAX_DELAY", 0.05, lambda: 0.2)
    # spacing between releases from one hot-range queue (one release
    # per spacing ≈ one commit batch apart, so queued rivals land at
    # successive versions instead of racing inside one batch window)
    init("SCHED_RELEASE_SPACING", 0.005, lambda: 0.02)
    init("SCHED_QUEUE_MAX", 64, lambda: 2)
    # CC cadence for pushing the cluster-merged hot-spot rows to the
    # proxies' predictors (and the GRV conflict-window piggyback)
    init("SCHED_HOT_PUSH_INTERVAL", 0.5, lambda: 0.05)
    # server-side repair of conflicted-but-repairable transactions:
    # re-read the invalidated ranges, revalidate at the conflict
    # version, commit without a client round trip
    init("TXN_REPAIR", 0, lambda: 1)
    init("REPAIR_MAX_ATTEMPTS", 2, lambda: 1)
    init("REPAIR_MAX_INFLIGHT", 128, lambda: 2)
    # re-read bounds: rows per invalidated range, and how long the
    # proxy waits for storage to reach the conflict version before
    # falling back to the ordinary abort
    init("REPAIR_REREAD_ROWS", 64, lambda: 2)
    init("REPAIR_READ_TIMEOUT", 1.0, lambda: 0.05)
    # client-side early abort: hot-key conflict windows ride GRV
    # replies into a per-Database cache; a commit whose read ranges
    # overlap a fresh window newer than its snapshot aborts locally
    init("CLIENT_CONFLICT_WINDOWS", 0, lambda: 1)
    init("CONFLICT_WINDOW_TTL", 2.0, lambda: 0.1)
    init("CONFLICT_WINDOW_SCORE_MIN", 0.5)
    init("CONFLICT_WINDOW_TOP_K", 8)
    # ratekeeper deferral-pressure input: smoothed deferred-commit
    # queue depth per proxy, spring-zone throttled like the queue-byte
    # inputs (0 disables the input)
    init("RK_SCHED_DEFER_LIMIT", 48.0, lambda: 2.0)
    init("RK_SCHED_DEFER_SPRING", 24.0)

    # -- dynamic resolver split/merge (ISSUE 15; ref: resolutionBalancing
    # + the keyResolvers history map, masterserver.actor.cpp:1008 /
    # MasterProxyServer.actor.cpp:204). The cluster-controller balance
    # loop watches per-resolver load and, on skew, moves a key range
    # with LIVE state handoff: donor checkpoint -> clip -> install on
    # the recipient -> early release of the former owner. Default OFF
    # (the commit path and the sim event schedule are byte-identical
    # until armed); deliberately NOT buggified — a new buggify site
    # would shift the shared randomization stream and invalidate every
    # seeded chaos baseline (the PR 14 discipline). Chaos cells arm it
    # explicitly via CHAOS_SPLITS=1.
    init("RESOLVER_BALANCE", 0)
    init("RESOLVER_BALANCE_INTERVAL", 0.5)
    # minimum per-round work delta on the loaded resolver before a
    # split is considered, and the max/min skew factor that triggers it
    init("RESOLVER_BALANCE_MIN_WORK", 100)
    init("RESOLVER_BALANCE_SKEW", 2.0)
    # a moved range whose traffic fell below this share of MIN_WORK is
    # merged back to its former owner (the symmetric stitch)
    init("RESOLVER_BALANCE_MERGE_WORK", 10)
    # test-only trigger: treat the thresholds as met on the first round
    # with ANY donor work, so smoke/CI can force one split under a
    # small seeded workload
    init("RESOLVER_BALANCE_FORCE", 0)
    # bound on each handoff RPC (checkpoint / install); a timed-out
    # handoff falls back to the reference's window-only semantics (the
    # former owner keeps voting for a full MVCC window) — correct,
    # just slower to retire the donor
    init("RESOLVER_HANDOFF_TIMEOUT", 5.0)
    # modeled resolver service time per transaction (seconds), the
    # system-bench saturation model (tools/clusterbench.py): resolution
    # cost is what the source paper scales against (arXiv:1804.00947),
    # and the sim otherwise resolves in zero sim time, hiding the
    # resolver axis entirely. Default 0.0 = off = byte-identical.
    init("SIM_RESOLVE_COST_PER_TXN", 0.0)
    # modeled proxy commit-pipeline service time per transaction
    # (seconds) — the proxy-side twin of SIM_RESOLVE_COST_PER_TXN so
    # the role-per-process bench (SYSBENCH r02) has BOTH axes of the
    # capacity model min(R/resolve_cost, P/commit_cost) binding.
    # Default 0.0 = off = byte-identical.
    init("SIM_COMMIT_COST_PER_TXN", 0.0)
    # wall-clock deadline for a RetryingTcpRef (rpc/tcp.py) to keep
    # re-issuing a request whose connection died — bridges role-process
    # kill -9 windows (respawn on the same port) via role idempotency.
    # Never BUGGIFY-distorted: retries ride real TCP only.
    init("ROLE_RETRY_DEADLINE", 30.0)

    # -- conflict-backend fault tolerance (models/failover.py) ---------
    # per-seam probability of a simulated device fault at the
    # submit/materialize/drain boundaries (ops/fault_injection.py).
    # NEVER buggify-distorted: the seams live inside backend code that
    # unit tests drive without the failover controller; arming is an
    # explicit act (sim fault workloads, CI smoke) — a BUGGIFY site
    # inside the injector amplifies an armed campaign x10 instead
    init("DEVICE_FAULT_INJECTION", 0.0)
    # resolver-side failover wrapper for the device backends: 0 runs
    # them bare (bench-style; a device fault then kills the role)
    init("CONFLICT_FAILOVER", 1)
    # checkpoint cadence in VERSIONS (~1s of commit traffic at the
    # reference VERSIONS_PER_SECOND); buggified tiny so sim runs
    # checkpoint every few batches and restores replay short logs
    init("CONFLICT_CHECKPOINT_VERSIONS", 1_000_000, lambda: 20_000)
    # hard bound on the replay log (batches since the last checkpoint);
    # reaching it forces a checkpoint whatever the version cadence says
    init("CONFLICT_REPLAY_LOG_MAX", 512, lambda: 4)
    # fresh-device rebuild attempts before declaring the device dead
    # and failing over to the CPU backend
    init("DEVICE_FAULT_RETRIES", 2, lambda: 0)
    # reattach-to-device backoff after a failover (doubles per failed
    # reattach, capped); CONFLICT_DEVICE_REATTACH=0 pins the fallback
    init("CONFLICT_DEVICE_REATTACH", 1)
    init("DEVICE_REATTACH_BACKOFF", 1.0, lambda: 0.05)
    init("DEVICE_REATTACH_BACKOFF_MAX", 30.0)
    # sampled shadow validation: every Nth batch is re-resolved on a
    # CPU shadow rebuilt from the last checkpoint and the verdicts
    # compared (0 disables; buggified high so sim runs cross-check
    # constantly — the early-detection discipline of arXiv:2301.06181)
    init("SHADOW_RESOLVE_SAMPLE", 0, lambda: 2)
    # a shadow mismatch normally traces SevError + surfaces in status;
    # with fail-stop armed it raises and halts the resolver, the way
    # check_consistency treats replica corruption
    init("SHADOW_RESOLVE_FAIL_STOP", 0)
    return k


SERVER_KNOBS = make_server_knobs()


def reset_server_knobs(randomize: bool = False) -> Knobs:
    """Re-randomize/reset the ambient knobs *in place* (shared by reference)."""
    return make_server_knobs(randomize, into=SERVER_KNOBS)
