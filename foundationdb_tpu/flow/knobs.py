"""Tunable knobs with simulation randomization.

Reference: flow/Knobs.h/.cpp (93 flow knobs), fdbserver/Knobs.cpp (284 server
knobs). Knobs are plain attributes initialized by ``init(name, default)``,
optionally distorted under BUGGIFY, and overridable by ``--knob_name=value``
style dicts.
"""

from __future__ import annotations

from typing import Callable, Optional

from .rng import g_buggify, g_random


class Knobs:
    def __init__(self):
        self._defaults: dict[str, float | int | str] = {}

    def init(self, name: str, default, buggify_fn: Optional[Callable[[], object]] = None):
        """Register a knob. `buggify_fn` returns a distorted value when the
        site fires under BUGGIFY (ref: `if (randomize && BUGGIFY)` in Knobs.cpp)."""
        value = default
        if buggify_fn is not None and g_buggify(f"knob/{name}"):
            value = buggify_fn()
        self._defaults[name] = default
        setattr(self, name.lower(), value)

    def set(self, name: str, value) -> None:
        setattr(self, name.lower(), value)


def make_server_knobs(randomize: bool = False, into: "Knobs | None" = None) -> Knobs:
    """Server knobs used by this framework (subset of fdbserver/Knobs.cpp,
    numerically identical defaults). Pass `into` to re-initialize an existing
    instance in place (so importers holding a reference see new values)."""
    k = into if into is not None else Knobs()

    def init(name, default, buggify_fn=None):
        k.init(name, default, buggify_fn if randomize else None)

    init("VERSIONS_PER_SECOND", 1_000_000)
    init("MAX_READ_TRANSACTION_LIFE_VERSIONS", 5 * 1_000_000,
         lambda: g_random.random_choice([1_000_000, 100_000, 10_000_000]))
    init("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 5 * 1_000_000,
         lambda: 1_000_000)
    init("MAX_VERSIONS_IN_FLIGHT", 100 * 1_000_000)
    init("MAX_COMMIT_BATCH_INTERVAL", 0.5, lambda: 2.0)
    init("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001)
    init("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 32768, lambda: 1000)
    init("COMMIT_TRANSACTION_BATCH_BYTES_MAX", 8 << 20)
    init("RESOLVER_STATE_MEMORY_LIMIT", 1 << 20)
    init("PROXY_SPIN_DELAY", 0.01)
    init("GRV_BATCH_INTERVAL", 0.0005)
    init("DESIRED_TOTAL_BYTES", 150000)
    init("STORAGE_DURABILITY_LAG", 5.0)
    init("TLOG_SPILL_THRESHOLD", 1500 << 20)
    init("MAX_TRANSACTION_BYTE_LIMIT", 10_000_000)
    init("TRANSACTION_SIZE_LIMIT", 10_000_000)
    init("KEY_SIZE_LIMIT", 10_000)
    init("VALUE_SIZE_LIMIT", 100_000)
    init("RESOLVER_COALESCE_TIME", 1.0)
    init("LOAD_BALANCE_BACKUP_DELAY", 0.005, lambda: 0.0005)
    # DD shard sizing (ref: SHARD_MAX_BYTES_PER_KSEC family — row-count
    # stand-ins for the byte/bandwidth thresholds)
    init("DD_SHARD_SPLIT_ROWS", 1000, lambda: 120)
    init("DD_SHARD_MERGE_ROWS", 40, lambda: 10)
    init("SAMPLE_EXPIRATION_TIME", 1.0)
    init("WATCH_TIMEOUT", 900.0, lambda: 20.0)
    return k


SERVER_KNOBS = make_server_knobs()


def reset_server_knobs(randomize: bool = False) -> Knobs:
    """Re-randomize/reset the ambient knobs *in place* (shared by reference)."""
    return make_server_knobs(randomize, into=SERVER_KNOBS)
