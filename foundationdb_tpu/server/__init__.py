"""Server roles: the transaction subsystem (ref: fdbserver/).

The minimum end-to-end slice per the build plan: master version
authority + proxy commit pipeline + resolver (pluggable conflict-set
backend) + in-memory tag log + versioned storage, all hosted on
simulated processes over the deterministic network.
"""

from .cluster import SimCluster
from .types import (
    CLEAR_RANGE,
    SET_VALUE,
    CommitRequest,
    MutationRef,
)

__all__ = ["SimCluster", "CommitRequest", "MutationRef", "SET_VALUE",
           "CLEAR_RANGE"]
