"""SimCluster: the whole transaction subsystem on one deterministic loop.

Reference: fdbserver/SimulatedCluster.actor.cpp setupSimulatedSystem
(:1078) — build simulated processes, start role actors on them, hand
back client handles; the same role code would run on real transports in
production (the INetwork seam). Fault API surfaces the sim2 primitives
(kill/clog/reboot) for workload tests; the TLog and storage roles keep
their state on the machines' simulated disks, so a rebooted role
recovers it (ref: simulatedFDBDRebooter, restartSimulatedSystem).
"""

from __future__ import annotations

from typing import Optional

from .. import flow
from ..rpc import SimNetwork
from .kvstore import KeyValueStoreMemory
from .master import Master
from .proxy import Proxy
from .resolver_role import Resolver
from .storage import StorageServer
from .tlog import TLog


class SimCluster:
    """Single-region, single-proxy minimum slice; grows toward the full
    recruitment flow (ClusterController/recovery) in later stages."""

    def __init__(self, seed: int = 0, conflict_backend: str = "python",
                 start_time: float = 0.0, n_resolvers: int = 1,
                 durable: bool = False,
                 storage_lag_versions: Optional[int] = None):
        flow.set_seed(seed)
        self.sched = flow.Scheduler(start_time=start_time, virtual=True)
        flow.set_scheduler(self.sched)
        self.net = SimNetwork(self.sched, flow.g_random)
        self.conflict_backend = conflict_backend
        self.durable = durable
        self.storage_lag_versions = storage_lag_versions

        p = self.net.new_process
        self.master = Master(p("master", machine="m1"))
        self.resolvers = [
            Resolver(p(f"resolver{i}", machine=f"m2.{i}"),
                     backend=conflict_backend)
            for i in range(n_resolvers)]
        self.resolver = self.resolvers[0]
        # evenly spaced single-byte split points (rebalancing arrives with
        # the resolutionBalancing equivalent)
        splits = [bytes([(i * 256) // n_resolvers])
                  for i in range(1, n_resolvers)]
        self.tlog = self._make_tlog(p("tlog", machine="m3"))
        self.proxy = Proxy(p("proxy", machine="m1"),
                           self.master.version_requests.ref(),
                           [r.resolves.ref() for r in self.resolvers],
                           [self.tlog.commits.ref()],
                           resolver_splits=splits)
        self.storage = self._make_storage(p("storage", machine="m4"))
        for role in (self.master, *self.resolvers, self.tlog, self.proxy,
                     self.storage):
            role.start()

    # -- role construction (also used by reboots) -----------------------
    def _make_tlog(self, process) -> TLog:
        disk = self.net.disk(process.machine) if self.durable else None
        return TLog(process, disk=disk)

    def _make_storage(self, process) -> StorageServer:
        kv = None
        if self.durable:
            kv = KeyValueStoreMemory(self.net.disk(process.machine),
                                     "storage", owner=process)
        return StorageServer(process, self.tlog.peeks.ref(), kv=kv,
                             tlog_pop=self.tlog.pops.ref(),
                             durability_lag_versions=self.storage_lag_versions)

    # -- faults ---------------------------------------------------------
    def reboot_tlog(self) -> TLog:
        """Kill the tlog process and restart the role from its disk
        files. Note: the proxy holds the OLD commit endpoint until a
        recovery re-wires it — restart tests talk to the new role
        directly, full re-recruitment arrives with the master recovery
        state machine."""
        proc = self.net.reboot("tlog")
        self.tlog = self._make_tlog(proc)
        self.tlog.start()
        return self.tlog

    def reboot_storage(self) -> StorageServer:
        proc = self.net.reboot("storage")
        self.storage = self._make_storage(proc)
        self.storage.start()
        return self.storage

    def client(self, name: str = "client", machine: str = ""):
        from ..client import Database  # avoid package-init cycle
        proc = self.net.new_process(name, machine or name)
        return Database(proc, self.proxy.grvs.ref(), self.proxy.commits.ref(),
                        self.storage.gets.ref(), self.storage.ranges.ref(),
                        self.storage.get_keys.ref(),
                        self.storage.watches.ref())

    # -- running --------------------------------------------------------
    def run(self, coro, timeout_time: Optional[float] = None):
        """Drive the loop until the given actor completes."""
        task = flow.spawn(coro, name="test-main")
        return self.sched.run(until=task, timeout_time=timeout_time)

    def shutdown(self) -> None:
        flow.set_scheduler(None)
