"""SimCluster: the whole cluster on one deterministic loop — workers,
coordinators, ClusterController, recruitment, recovery, and faults.

Reference: fdbserver/SimulatedCluster.actor.cpp setupSimulatedSystem
(:1078) — build simulated machines with workers, start coordination and
the cluster controller, and let recruitment bring up the transaction
subsystem exactly the way a real cluster boots (§3.4 call stack: worker
registration -> leader election -> masterCore recovery). Kills go
through the sim network's process-kill semantics; killed workers
auto-reboot after a delay (ref: simulatedFDBDRebooter,
SimulatedCluster.actor.cpp:194) and recover their disk stores, so the
recovery state machine — not test scaffolding — heals the cluster.
"""

from __future__ import annotations

from typing import Optional

from .. import flow
from ..rpc import SimNetwork
from .cluster_controller import ClusterConfig, ClusterController
from .coordination import Coordinator
from .worker import RegisterWorkerRequest, Worker

# seconds before a killed worker restarts: see SIM_REBOOT_DELAY knob

#: seed of the most recently constructed simulation — the test
#: harness's failure hook reads it to print a one-line seed-replay
#: repro for any red sim test (tests/conftest.py)
last_sim_seed: Optional[int] = None


class SimCluster:
    def __init__(self, seed: int = 0, conflict_backend: str = "python",
                 start_time: float = 0.0, n_resolvers: int = 1,
                 durable: bool = False,
                 storage_lag_versions: Optional[int] = None,
                 n_proxies: int = 1, n_logs: int = 1, n_storage: int = 1,
                 n_workers: Optional[int] = None, n_coordinators: int = 1,
                 auto_reboot: bool = True, buggify: bool = False,
                 storage_engine: str = "memory",
                 storage_replicas: int = 1,
                 share_with: "SimCluster" = None, name_prefix: str = "",
                 virtual: bool = True, data_dir: Optional[str] = None,
                 workers_per_machine: int = 1, n_zones: int = 0,
                 storage_policy=None, backup_driver: bool = False,
                 profile_janitor: bool = False,
                 metric_history: bool = False,
                 metrics_janitor: bool = False,
                 critical_path: bool = False):
        if storage_policy is not None and \
                storage_policy.replica_count() != max(1, storage_replicas):
            raise ValueError(
                f"storage_policy places {storage_policy.replica_count()} "
                f"replicas but storage_replicas={storage_replicas}: the "
                "team size and the tag-pinning/naming machinery would "
                "silently diverge")
        self.prefix = name_prefix
        self._owns_scheduler = share_with is None
        # co-scheduled clusters (share_with): any of them may publish a
        # broken picture, and whichever cluster's run() drives the loop
        # must surface it — track the sharing group both ways
        self._share_src = share_with
        self._peer_clusters: list = []
        if share_with is not None:
            if data_dir is not None:
                raise ValueError(
                    "data_dir on a share_with secondary is not supported: "
                    "it would silently run on the primary's sim disks")
            share_with._peer_clusters.append(self)
        self._io_pool = None   # IThreadPool for real-disk fsync offload
        if share_with is not None:
            # a second cluster INSIDE the same deterministic simulation
            # (multi-cluster tests: DR, cross-cluster tooling) — shares
            # the scheduler/network/RNG, distinct process namespace
            self.sched = share_with.sched
            self.net = share_with.net
        else:
            global last_sim_seed
            last_sim_seed = seed
            flow.set_seed(seed, buggify_enabled=buggify)
            # knob distortion rides the same switch as BUGGIFY (ref:
            # `if (randomize && BUGGIFY)` in Knobs.cpp); always re-init
            # so a prior run's distorted knobs never leak into this one
            flow.reset_server_knobs(randomize=buggify)
            # a previous simulation's armed chaos station hooks must
            # never leak into this one (process-global, like the knobs)
            from .chaos import clear_stations
            clear_stations()
            # the flight recorder is process-global like the stations:
            # a prior run's ring (and arming) must not leak in
            flow.g_flightrec.disarm()
            # virtual=False runs the same cluster on the wall clock so
            # real-socket peers (the TCP gateway + C binding) can attach
            self.sched = flow.Scheduler(start_time=start_time,
                                        virtual=virtual)
            flow.set_scheduler(self.sched)
            self.net = SimNetwork(self.sched, flow.g_random)
            # sim-perf attribution plane (SIM_TASK_STATS): armed at
            # boot so recovery, workload and quiesce windows are all
            # attributed. Profiling reads only the wall clock — the
            # sim timeline and every seeded draw are untouched, so the
            # armed run's event schedule is identical to the off run's
            # (test-pinned)
            if int(getattr(flow.SERVER_KNOBS, "sim_task_stats", 0)):
                self.sched.start_task_stats()
                self.net.arm_message_stats()
            if data_dir is not None:
                # REAL on-disk stores: durable state survives an actual
                # process restart (tools/server --data-dir)
                import os

                from ..rpc.disk import RealDisk
                if not virtual:
                    # wall-clock deployment: fsyncs run on an
                    # IThreadPool so a slow disk stalls one worker,
                    # never the whole event loop (ref: AsyncFileEIO's
                    # eio pool; flow/IThreadPool.h)
                    from ..flow.threadpool import ThreadPool
                    self._io_pool = ThreadPool(
                        n_threads=int(flow.SERVER_KNOBS.disk_io_threads),
                        name="diskio")
                    self._io_pool.start()
                self.net.disk_factory = lambda m: RealDisk(
                    os.path.join(data_dir, m), m, pool=self._io_pool)
        self.durable = durable
        self.auto_reboot = auto_reboot
        self.conflict_backend = conflict_backend
        self.storage_lag_versions = storage_lag_versions
        self.config = ClusterConfig(n_proxies=n_proxies,
                                    n_resolvers=n_resolvers,
                                    n_logs=n_logs, n_storage=n_storage,
                                    conflict_backend=conflict_backend,
                                    durable=durable,
                                    storage_engine=storage_engine,
                                    storage_replicas=storage_replicas,
                                    storage_policy=storage_policy)

        # coordinators (ref: coordinationServer)
        px = self.prefix
        self.coordinators = []
        for i in range(n_coordinators):
            cproc = self.net.new_process(f"{px}coord{i}",
                                         machine=f"{px}coord{i}")
            c = Coordinator(cproc, disk=(self.net.disk(f"{px}coord{i}")
                                         if durable else None))
            c.start()
            self.coordinators.append(c)

        # the longitudinal plane (ISSUE 17): must be armed BETWEEN the
        # knob reset above and CC construction — cc.start() decides at
        # spawn time whether the TimeKeeper/recorder/SLO loops exist at
        # all (the byte-identical off posture), so a post-construction
        # SERVER_KNOBS.set would be too late
        if metric_history:
            flow.SERVER_KNOBS.set("metric_history", 1)
        # latency forensics (ISSUE 18): same arming window as above —
        # cc.start() gates the fold loop at spawn time. The flight
        # recorder rides along: a forensics run wants the recent-event
        # ring available for `cli flightrec` / incident dumps
        if critical_path:
            flow.SERVER_KNOBS.set("critical_path", 1)
            flow.g_flightrec.arm()

        # the cluster controller (single candidate; contested elections
        # are exercised in the coordination unit tests)
        self.cc = ClusterController(
            self.net.new_process(f"{px}cc", machine=f"{px}cc"),
            [self._coord_refs(c) for c in self.coordinators],
            self.config)
        self.cc.start()

        # sim_validation: every simulation continuously re-checks the
        # published cluster picture's invariants (ref: sim_validation.cpp
        # debug hooks) — a broken shard map or regressed epoch fails the
        # test at its source, not where a workload later trips
        from .sim_validation import validator
        self.validator_state: dict = {}
        self._validator = flow.spawn(
            validator(self.cc.dbinfo, self.validator_state),
            name=f"{px}simValidator")

        # workers grouped onto machines and zones (ref: simulator.h
        # MachineInfo + SimulatedCluster setupSimulatedSystem building
        # machines across zones/DCs). Defaults keep the legacy model:
        # one worker per machine, each machine its own zone.
        if n_workers is None:
            n_workers = max(4, n_logs + 1, n_storage * storage_replicas,
                            n_resolvers, storage_replicas + 1)
        self.n_workers = n_workers
        self.workers_per_machine = max(1, workers_per_machine)
        self.n_zones = n_zones
        # the cluster-side backup runner (ref: `fdbbackup agent`
        # processes run alongside the cluster) — opt-in; the
        # fdbtpu-backup tool needs one watching the control rows
        self.backup_driver = None
        if backup_driver:
            from ..layers.backup_driver import BackupDriver
            self.backup_driver = BackupDriver(self)
            self.backup_driver.start()
        # retention trimming for the sampled-transaction profiling
        # keyspace (layers/clientlog.py) — opt-in, like the backup
        # driver: a cluster running PROFILE_SAMPLE_RATE > 0 for long
        # wants one
        self.client_log_janitor = None
        if profile_janitor:
            from ..layers.clientlog import ClientLogJanitor
            self.client_log_janitor = ClientLogJanitor(self)
            self.client_log_janitor.start()
        # retention trimming for the longitudinal keyspaces — the
        # metric history, the legacy counter series, AND the TimeKeeper
        # map through ONE bounded-scan janitor (layers/metrics.py);
        # opt-in like the two drivers above
        self.metrics_janitor = None
        if metrics_janitor:
            from ..layers.metrics import MetricsJanitor
            self.metrics_janitor = MetricsJanitor(self)
            self.metrics_janitor.start()
        self.workers: dict = {}
        for i in range(n_workers):
            if self.workers_per_machine > 1 or n_zones > 0:
                mi = i // self.workers_per_machine
                machine = f"{px}m{mi}"
                zone = f"{px}z{mi % n_zones}" if n_zones else machine
            else:
                machine, zone = f"{px}w{i}", ""
            self._start_worker(f"{px}worker{i}", machine, zone)

    @staticmethod
    def _coord_refs(c: Coordinator) -> tuple:
        return (c.reads.ref(), c.writes.ref(), c.candidacies.ref(),
                c.forwards.ref())

    def add_coordinators(self, n: int, tag: str = "new") -> list:
        """Start n fresh coordinator servers (for a coordinators
        change); returns their ref 4-tuples (ref: the operator standing
        up new coordination hosts before `coordinators ...`)."""
        out = []
        for i in range(n):
            name = f"{self.prefix}coord-{tag}{i}"
            cproc = self.net.new_process(name, machine=name)
            c = Coordinator(cproc, disk=(self.net.disk(name)
                                         if self.durable else None))
            c.start()
            self.coordinators.append(c)
            out.append(self._coord_refs(c))
        return out

    # -- worker lifecycle ------------------------------------------------
    def _start_worker(self, name: str, machine: str,
                      zone: str = "") -> Worker:
        proc = self.net.new_process(name, machine=machine, zone=zone)
        w = Worker(proc, self.net, durable=self.durable,
                   dbinfo=self.cc.dbinfo,
                   conflict_backend=self.conflict_backend,
                   storage_lag_versions=self.storage_lag_versions,
                   storage_engine=self.config.storage_engine)
        w.start()
        self.workers[name] = w
        flow.spawn(self._register_worker(w), name=f"{name}.register")
        if self.auto_reboot:
            proc.on_kill(lambda: flow.spawn(
                self._reboot_worker(name, machine, zone),
                name=f"{name}.rebooter"))
        return w

    async def _register_worker(self, w: Worker) -> None:
        logs, storages = await w.recover_stores()
        await self.cc.registrations.ref().get_reply(
            RegisterWorkerRequest(w.process.name, w.process.machine, w,
                                  logs, storages), w.process)

    async def _reboot_worker(self, name: str, machine: str,
                             zone: str = "") -> None:
        """(ref: simulatedFDBDRebooter — the machine comes back after a
        delay and its worker recovers whatever the disk kept)"""
        await flow.delay(flow.SERVER_KNOBS.sim_reboot_delay)
        if name in self.net.processes and self.net.processes[name].alive:
            return
        self._start_worker(name, machine, zone)

    # -- faults ----------------------------------------------------------
    def kill_worker(self, name: str) -> None:
        self.net.kill(self.net.processes[name])

    def kill_machine(self, machine: str) -> list:
        """Correlated whole-machine failure: every co-located worker
        dies at once; auto-reboot (if on) brings each back onto the
        same machine/zone with its disks intact (ref: killMachine,
        sim2.actor.cpp:1717)."""
        return self.net.kill_machine(machine)

    def machine_of(self, worker_name: str) -> str:
        return self.net.processes[worker_name].machine

    def _find_worker_of(self, prefix: str) -> Optional[str]:
        """Name of a live worker hosting a role whose name starts with
        `prefix` in the CURRENT epoch."""
        epoch = self.cc.dbinfo.get().epoch
        for name, w in self.workers.items():
            if not w.process.alive:
                continue
            for role_name in w.roles:
                if role_name.startswith(prefix) and \
                        (f"-e{epoch}-" in role_name
                         or not role_name.startswith(("proxy", "resolver",
                                                      "tlog"))):
                    return name
        return None

    def kill_role(self, kind: str) -> str:
        """Kill the worker hosting a role of this kind ('tlog', 'proxy',
        'resolver', 'storage'); returns the worker name killed."""
        prefix = {"tlog": "tlog-e", "proxy": "proxy-e",
                  "resolver": "resolver-e", "storage": "storage-"}[kind]
        name = self._find_worker_of(prefix)
        if name is None:
            raise KeyError(f"no live worker hosts a {kind}")
        self.kill_worker(name)
        return name

    # -- clients ---------------------------------------------------------
    def client(self, name: str = "client", machine: str = ""):
        from ..client import Database  # avoid package-init cycle
        name = self.prefix + name
        proc = self.net.new_process(name, machine or name)
        return Database(proc, self.cc.open_db.ref(),
                        status_ref=self.cc.status_requests.ref(),
                        management_ref=self.cc.management.ref(),
                        coordinators=[self._coord_refs(c)
                                      for c in self.coordinators])

    async def quiet_database(self, max_wait: float = 60.0) -> None:
        """Wait until the cluster is quiescent: every storage replica
        has pulled to the log's committed frontier and the TLog backlog
        is fully popped (ref: fdbserver/QuietDatabase.actor.cpp — the
        post-workload settling tests rely on)."""
        # the latency probe's own writes would keep the log from ever
        # draining to zero — pause it while quiescing (the reference's
        # quiet database similarly suppresses background traffic)
        self.cc.probe_paused = True
        try:
            return await self._quiet_inner(max_wait)
        finally:
            self.cc.probe_paused = False

    async def _quiet_inner(self, max_wait: float) -> None:
        deadline = flow.now() + max_wait
        while flow.now() < deadline:
            info = self.cc.dbinfo.get()
            logs = self.cc.tlog_objs()
            storages = [self.cc._storage_objs.get(rep.name)
                        for s in info.storages for rep in s.replicas]
            if (info.recovery_state == "fully_recovered" and logs
                    and all(o is not None and o.process.alive
                            for o in storages)):
                frontier = max(t.version.get() for t in logs)
                caught_up = all(o.version.get() >= frontier
                                for o in storages)
                drained = all(len(t.entries) == 0 for t in logs)
                if caught_up and drained:
                    return
                # the durability horizon (known_committed) only advances
                # with fresh commits: nudge one through so the tail
                # drains on an otherwise idle cluster
                from .types import CommitRequest
                await flow.catch_errors(flow.timeout_error(
                    info.proxies[0].commits.get_reply(
                        CommitRequest(0, (), (), ()),
                        self.cc.process), 1.0))
            await flow.delay(flow.SERVER_KNOBS.quiet_database_poll)
        diag = self._quiet_diagnosis()
        flow.TraceEvent("QuietDatabaseTimeout", self.cc.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
            MaxWait=max_wait, Diagnosis=diag).log()
        raise flow.error("timed_out",
                         f"quiet_database timed out after {max_wait}s: "
                         + diag)

    def _quiet_diagnosis(self) -> str:
        """WHY the cluster never quiesced: which condition failed, and
        which roles/counters are behind — a hung chaos storm is
        triagable from the error message alone, not from a debugger."""
        parts = []
        info = self.cc.dbinfo.get()
        if info.recovery_state != "fully_recovered":
            parts.append(f"recovery_state={info.recovery_state} "
                         f"(epoch {info.epoch})")
        logs = self.cc.tlog_objs()
        if not logs:
            parts.append("no live current-generation tlogs")
        frontier = max((t.version.get() for t in logs), default=0)
        undrained = [(lr.store, len(obj.entries))
                     for lr in info.logs.logs
                     for wi in self.cc.workers.values()
                     for obj in (wi.worker.roles.get(lr.store),)
                     if obj is not None and obj.process.alive
                     and len(obj.entries) > 0]
        for store, n in undrained:
            parts.append(f"tlog {store} holds {n} unpopped entries")
        for s in info.storages:
            for rep in s.replicas:
                obj = self.cc._storage_objs.get(rep.name)
                if obj is None:
                    parts.append(f"storage {rep.name} unregistered")
                elif not obj.process.alive:
                    parts.append(f"storage {rep.name} dead "
                                 "(no reboot/rebuild landed)")
                elif obj.version.get() < frontier:
                    parts.append(
                        f"storage {rep.name} at v{obj.version.get()} "
                        f"trails the log frontier v{frontier} by "
                        f"{frontier - obj.version.get()}")
        if not parts:
            parts.append("all conditions met on the final poll "
                         "(quiesced too late)")
        return "; ".join(parts)

    # -- running ---------------------------------------------------------
    def run(self, coro, timeout_time: Optional[float] = None):
        """Drive the loop until the given actor completes. A
        sim-validation violation outranks the workload's own outcome —
        a detached validator's error would otherwise die silently in
        its task future (code review r3)."""
        task = flow.spawn(coro, name="test-main")
        try:
            result = self.sched.run(until=task, timeout_time=timeout_time)
        except BaseException:
            self._raise_validator_error()
            raise
        self._raise_validator_error()
        return result

    def _raise_validator_error(self) -> None:
        # walk to the sharing group's root, then check every member —
        # a share_with secondary's violation must not die silently just
        # because the PRIMARY's run() drives the loop
        root = self
        while root._share_src is not None:
            root = root._share_src
        stack = [root]
        while stack:
            c = stack.pop()
            stack.extend(c._peer_clusters)
            v = getattr(c, "_validator", None)
            if v is not None and v.is_ready and v.is_error:
                raise v.exception()

    def shutdown(self) -> None:
        # only the cluster that created the scheduler tears it down — a
        # share_with secondary must not pull it from under the primary
        if self._owns_scheduler:
            if self._io_pool is not None:
                self._io_pool.close()
            for d in self.net.disks.values():
                if hasattr(d, "close_all"):
                    d.close_all()   # release real-file handles
            flow.set_scheduler(None)
