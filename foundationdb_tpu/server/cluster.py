"""SimCluster: the whole transaction subsystem on one deterministic loop.

Reference: fdbserver/SimulatedCluster.actor.cpp setupSimulatedSystem
(:1078) — build simulated processes, start role actors on them, hand
back client handles; the same role code would run on real transports in
production (the INetwork seam). Fault API surfaces the sim2 primitives
(kill/clog) for workload tests.
"""

from __future__ import annotations

from typing import Optional

from .. import flow
from ..rpc import SimNetwork
from .master import Master
from .proxy import Proxy
from .resolver_role import Resolver
from .storage import StorageServer
from .tlog import TLog


class SimCluster:
    """Single-region, single-proxy minimum slice; grows toward the full
    recruitment flow (ClusterController/recovery) in later stages."""

    def __init__(self, seed: int = 0, conflict_backend: str = "python",
                 start_time: float = 0.0):
        flow.set_seed(seed)
        self.sched = flow.Scheduler(start_time=start_time, virtual=True)
        flow.set_scheduler(self.sched)
        self.net = SimNetwork(self.sched, flow.g_random)

        p = self.net.new_process
        self.master = Master(p("master", machine="m1"))
        self.resolver = Resolver(p("resolver", machine="m2"),
                                 backend=conflict_backend)
        self.tlog = TLog(p("tlog", machine="m3"))
        self.proxy = Proxy(p("proxy", machine="m1"),
                           self.master.version_requests.ref(),
                           self.resolver.resolves.ref(),
                           self.tlog.commits.ref())
        self.storage = StorageServer(p("storage", machine="m4"),
                                     self.tlog.peeks.ref())
        for role in (self.master, self.resolver, self.tlog, self.proxy,
                     self.storage):
            role.start()

    def client(self, name: str = "client", machine: str = ""):
        from ..client import Database  # avoid package-init cycle
        proc = self.net.new_process(name, machine or name)
        return Database(proc, self.proxy.grvs.ref(), self.proxy.commits.ref(),
                        self.storage.gets.ref(), self.storage.ranges.ref())

    # -- running --------------------------------------------------------
    def run(self, coro, timeout_time: Optional[float] = None):
        """Drive the loop until the given actor completes."""
        task = flow.spawn(coro, name="test-main")
        return self.sched.run(until=task, timeout_time=timeout_time)

    def shutdown(self) -> None:
        flow.set_scheduler(None)
