"""KeyValueStoreBTree: a page-based copy-on-write B-tree engine.

Reference: fdbserver/VersionedBTree.actor.cpp (Redwood) +
IndirectShadowPager — the design re-expressed, not translated: 4KiB
checksummed pages, copy-on-write updates (modified paths are written to
FRESH pages), and a dual-slot superblock whose atomic flip commits the
new tree — a torn commit leaves the previous superblock (and therefore
the previous tree) fully intact, which is the crash-consistency story
(ref: IndirectShadowPager's shadowed page map; KeyValueStoreSQLite's
journaled btree plays this role for the ssd engine). Pages freed by
commit N re-enter circulation only after superblock N lands, so the
previous tree stays readable throughout.

The page set is write-through cached in RAM (reads are synchronous per
the IKeyValueStore contract; Redwood's page cache plays this role) and
the disk is the durability story.
"""

from __future__ import annotations

import struct
import zlib
from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from ..rpc.disk import SimDisk
from .kvstore import IKeyValueStore

PAGE_SIZE = 4096
_SUPER = struct.Struct("<IQQQQ")      # crc, commit_seq, root, next_page, nfree
_PHDR = struct.Struct("<IBH")         # crc, kind, n_items
_LEAF, _INNER, _FREE = 0, 1, 2
MAX_FANOUT = 32        # split threshold (items per page)
# per-item limits keep any two items fitting one page, so byte-aware
# splits always converge (the reference stores oversized values via
# overflow pages; this engine enforces limits instead — fdbcli-visible
# as key_too_large / value_too_large)
MAX_KEY = 1500
MAX_VALUE = 2000
_PAGE_BUDGET = PAGE_SIZE - _PHDR.size - 16


def _leaf_bytes(keys, vals) -> int:
    return sum(6 + len(k) + len(v) for k, v in zip(keys, vals))


def _inner_bytes(keys) -> int:
    return 8 + sum(10 + len(k) for k in keys)


class _Node:
    __slots__ = ("kind", "keys", "vals", "children")

    def __init__(self, kind, keys=None, vals=None, children=None):
        self.kind = kind
        self.keys: List[bytes] = keys if keys is not None else []
        # leaf: vals parallel to keys; inner: children = keys+1 page ids
        self.vals: List[bytes] = vals if vals is not None else []
        self.children: List[int] = children if children is not None else []


def _encode_node(n: _Node) -> bytes:
    out = []
    if n.kind == _LEAF:
        for k, v in zip(n.keys, n.vals):
            out.append(struct.pack("<HI", len(k), len(v)))
            out.append(k)
            out.append(v)
    else:
        out.append(struct.pack("<Q", n.children[0]))
        for k, c in zip(n.keys, n.children[1:]):
            out.append(struct.pack("<HQ", len(k), c))
            out.append(k)
    body = b"".join(out)
    hdr = _PHDR.pack(0, n.kind, len(n.keys))
    page = hdr + body
    if len(page) > PAGE_SIZE:
        raise ValueError("btree page overflow — lower MAX_FANOUT")
    page = page + b"\x00" * (PAGE_SIZE - len(page))
    crc = zlib.crc32(page[4:])
    return struct.pack("<I", crc) + page[4:]


def _decode_node(page: bytes) -> _Node:
    crc, kind, n_items = _PHDR.unpack_from(page, 0)
    if zlib.crc32(page[4:]) != crc:
        raise ValueError("btree page checksum mismatch")
    off = _PHDR.size
    node = _Node(kind)
    if kind == _LEAF:
        for _ in range(n_items):
            kl, vl = struct.unpack_from("<HI", page, off)
            off += 6
            node.keys.append(bytes(page[off:off + kl]))
            off += kl
            node.vals.append(bytes(page[off:off + vl]))
            off += vl
    else:
        (c0,) = struct.unpack_from("<Q", page, off)
        off += 8
        node.children.append(c0)
        for _ in range(n_items):
            kl, c = struct.unpack_from("<HQ", page, off)
            off += 10
            node.keys.append(bytes(page[off:off + kl]))
            off += kl
            node.children.append(c)
    return node


class KeyValueStoreBTree(IKeyValueStore):
    def __init__(self, disk: SimDisk, name: str, owner=None):
        self._file = disk.open(f"{name}.btree", owner)
        self._cache: Dict[int, _Node] = {}    # page id -> node (resident)
        self._root = 0
        self._next_page = 2                   # 0,1 are superblock slots
        self._free: List[int] = []            # reusable page ids
        self._pending_free: List[int] = []    # freed by the open commit
        self._commit_seq = 0
        self._staged: List[Tuple[int, bytes, bytes]] = []  # (op, a, b)
        self._dirty: Dict[int, _Node] = {}    # pages to write at commit
        self._rows = 0                        # committed row count

    # -- recovery --------------------------------------------------------
    async def recover(self) -> None:
        size = await self._file.size()
        best = None
        for slot in (0, 1):
            if size < (slot + 1) * PAGE_SIZE:
                continue
            raw = await self._file.read(slot * PAGE_SIZE, PAGE_SIZE)
            try:
                crc, seq, root, nxt, nfree = _SUPER.unpack_from(raw, 0)
            except struct.error:
                continue
            if zlib.crc32(raw[4:]) != crc:
                continue
            if best is None or seq > best[0]:
                best = (seq, root, nxt, nfree, raw)
        self._cache.clear()
        if best is None:
            self._root = 0
            self._next_page = 2
            self._free = []
            self._commit_seq = 0
            return
        seq, root, nxt, nfree, raw = best
        self._commit_seq = seq
        self._root = root
        self._next_page = nxt
        off = _SUPER.size
        self._free = list(struct.unpack_from(f"<{nfree}Q", raw, off))
        # load the reachable tree into the resident cache
        self._rows = 0
        if root:
            await self._load(root)

    async def _load(self, page_id: int) -> None:
        raw = await self._file.read(page_id * PAGE_SIZE, PAGE_SIZE)
        node = _decode_node(raw)
        self._cache[page_id] = node
        if node.kind == _INNER:
            for c in node.children:
                await self._load(c)
        else:
            self._rows += len(node.keys)

    # -- staged mutations -------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        if len(key) > MAX_KEY:
            raise ValueError("btree key exceeds engine limit")
        if len(value) > MAX_VALUE:
            raise ValueError("btree value exceeds engine limit")
        self._staged.append((0, key, value))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._staged.append((1, begin, end))

    # -- reads (resident tree + staged overlay) ---------------------------
    def _tree_get(self, key: bytes) -> Optional[bytes]:
        pid = self._root
        if not pid:
            return None
        while True:
            node = self._cache[pid]
            if node.kind == _LEAF:
                i = bisect_left(node.keys, key)
                if i < len(node.keys) and node.keys[i] == key:
                    return node.vals[i]
                return None
            pid = node.children[bisect_right(node.keys, key)]

    def get(self, key: bytes) -> Optional[bytes]:
        found, val = self._overlay(key)
        return val if found else self._tree_get(key)

    def _overlay(self, key: bytes):
        for op, a, b in reversed(self._staged):
            if op == 0 and a == key:
                return True, b
            if op == 1 and a <= key < b:
                return True, None
        return False, None

    def _tree_scan(self, begin: bytes, end: bytes, out: List,
                   pid: int, limit: int) -> None:
        node = self._cache[pid]
        if node.kind == _LEAF:
            lo = bisect_left(node.keys, begin)
            hi = bisect_left(node.keys, end)
            for i in range(lo, hi):
                out.append((node.keys[i], node.vals[i]))
                if len(out) >= limit:
                    return
            return
        lo = bisect_right(node.keys, begin)
        hi = bisect_left(node.keys, end)
        for i in range(lo - 1 if lo else 0, min(hi, len(node.keys)) + 1):
            self._tree_scan(begin, end, out, node.children[i], limit)
            if len(out) >= limit:
                return

    def _tree_scan_rev(self, begin: bytes, end: bytes, out: List,
                       pid: int, limit: int) -> None:
        """Descending scan yielding the rows nearest `end` first — the
        contract reverse paging callers rely on."""
        node = self._cache[pid]
        if node.kind == _LEAF:
            lo = bisect_left(node.keys, begin)
            hi = bisect_left(node.keys, end)
            for i in range(hi - 1, lo - 1, -1):
                out.append((node.keys[i], node.vals[i]))
                if len(out) >= limit:
                    return
            return
        lo = bisect_right(node.keys, begin)
        hi = bisect_left(node.keys, end)
        first = lo - 1 if lo else 0
        last = min(hi, len(node.keys))
        for i in range(last, first - 1, -1):
            self._tree_scan_rev(begin, end, out, node.children[i], limit)
            if len(out) >= limit:
                return

    def row_count(self) -> int:
        return self._rows

    def get_range(self, begin: bytes, end: bytes, limit: int = 1 << 30,
                  reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        rows: List[Tuple[bytes, bytes]] = []
        if self._root and not self._staged:
            if reverse:
                self._tree_scan_rev(begin, end, rows, self._root, limit)
            else:
                self._tree_scan(begin, end, rows, self._root, limit)
            return rows[:limit]
        if self._root:
            # staged clears/sets can alter the window: fetch it all
            self._tree_scan(begin, end, rows, self._root, 1 << 30)
        merged = dict(rows)
        for op, a, b in self._staged:
            if op == 0:
                if begin <= a < end:
                    merged[a] = b
            else:
                for k in [k for k in merged if a <= k < b]:
                    del merged[k]
        rows = sorted(merged.items())
        if reverse:
            rows = rows[::-1]
        return rows[:limit]

    # -- commit: apply staged ops copy-on-write, flip the superblock ------
    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        pid = self._next_page
        self._next_page += 1
        return pid

    def _free_page(self, pid: int) -> None:
        self._pending_free.append(pid)
        self._cache.pop(pid, None)
        self._dirty.pop(pid, None)

    def _write_node(self, node: _Node) -> int:
        pid = self._alloc()
        self._cache[pid] = node
        self._dirty[pid] = node
        return pid

    def _apply_set(self, pid: int, key: bytes, value: bytes) -> List:
        """Returns [(sep_key?, new_pid), ...] (1 entry, or 2 on split)."""
        if not pid:
            return [(None, self._write_node(_Node(_LEAF, [key], [value])))]
        node = self._cache[pid]
        if node.kind == _LEAF:
            keys, vals = list(node.keys), list(node.vals)
            i = bisect_left(keys, key)
            if i < len(keys) and keys[i] == key:
                vals[i] = value
            else:
                keys.insert(i, key)
                vals.insert(i, value)
                self._rows += 1
            self._free_page(pid)
            return self._maybe_split(_Node(_LEAF, keys, vals))
        ci = bisect_right(node.keys, key)
        parts = self._apply_set(node.children[ci], key, value)
        return self._replace_child(node, pid, ci, parts)

    def _replace_child(self, node: _Node, pid: int, ci: int,
                       parts: List) -> List:
        keys = list(node.keys)
        children = list(node.children)
        children[ci] = parts[0][1]
        for sep, new_pid in parts[1:]:
            keys.insert(ci, sep)
            children.insert(ci + 1, new_pid)
            ci += 1
        self._free_page(pid)
        return self._maybe_split(_Node(_INNER, keys, None, children))

    def _maybe_split(self, node: _Node) -> List:
        over_bytes = (_leaf_bytes(node.keys, node.vals) if node.kind == _LEAF
                      else _inner_bytes(node.keys)) > _PAGE_BUDGET
        if len(node.keys) <= MAX_FANOUT and not over_bytes:
            return [(None, self._write_node(node))]
        if len(node.keys) < 2:
            # a single item always fits (enforced at set())
            return [(None, self._write_node(node))]
        mid = len(node.keys) // 2
        if node.kind == _LEAF:
            left = _Node(_LEAF, node.keys[:mid], node.vals[:mid])
            right = _Node(_LEAF, node.keys[mid:], node.vals[mid:])
            sep = right.keys[0]
        else:
            left = _Node(_INNER, node.keys[:mid], None,
                         node.children[:mid + 1])
            right = _Node(_INNER, node.keys[mid + 1:], None,
                          node.children[mid + 1:])
            sep = node.keys[mid]
        # recurse: a half of few-but-large items may still exceed the
        # byte budget (item limits guarantee convergence)
        lp = self._maybe_split(left)
        rp = self._maybe_split(right)
        return lp + [(sep, rp[0][1])] + rp[1:]

    def _apply_clear(self, begin: bytes, end: bytes) -> None:
        """Rebuild-free range clear: collect survivors per overlapping
        leaf and rewrite those paths (simple COW delete; underfull
        leaves are tolerated — Redwood also defers rebalancing)."""
        doomed = []
        if self._root:
            self._tree_scan(begin, end, doomed, self._root, 1 << 30)
        for k, _v in doomed:
            self._root = self._delete_key(self._root, k)

    def _delete_key(self, pid: int, key: bytes) -> int:
        node = self._cache[pid]
        if node.kind == _LEAF:
            keys, vals = list(node.keys), list(node.vals)
            i = bisect_left(keys, key)
            if i < len(keys) and keys[i] == key:
                del keys[i]
                del vals[i]
                self._rows -= 1
            self._free_page(pid)
            return self._write_node(_Node(_LEAF, keys, vals))
        ci = bisect_right(node.keys, key)
        new_child = self._delete_key(node.children[ci], key)
        children = list(node.children)
        children[ci] = new_child
        # collapse empty leaves out of the inner node
        child_node = self._cache[new_child]
        keys = list(node.keys)
        if child_node.kind == _LEAF and not child_node.keys and \
                len(children) > 1:
            self._free_page(new_child)
            del children[ci]
            del keys[max(0, ci - 1)]
        self._free_page(pid)
        if not keys and len(children) == 1:
            return children[0]
        return self._write_node(_Node(_INNER, keys, None, children))

    async def commit(self) -> None:
        staged, self._staged = self._staged, []
        for op, a, b in staged:
            if op == 0:
                parts = self._apply_set(self._root, a, b)
                while len(parts) > 1:   # grow new root levels as needed
                    keys = [sep for sep, _ in parts[1:]]
                    children = [pid for _, pid in parts]
                    parts = self._maybe_split(
                        _Node(_INNER, keys, None, children))
                self._root = parts[0][1]
            else:
                self._apply_clear(a, b)
        # write dirty pages, sync, then flip the superblock
        dirty, self._dirty = self._dirty, {}
        for pid, node in dirty.items():
            await self._file.write(pid * PAGE_SIZE, _encode_node(node))
        await self._file.sync()
        self._commit_seq += 1
        all_free = self._free + self._pending_free
        cap_entries = (PAGE_SIZE - _SUPER.size) // 8
        # the superblock lists as many free pages as fit; the remainder
        # stays reusable in RAM and gets another shot at durability on
        # the next commit — only a crash while the overflow is non-empty
        # leaks those pages (bounded, unlike silent truncation; the
        # reference chains its free list through pages instead)
        durable_free = all_free[:cap_entries]
        body = _SUPER.pack(0, self._commit_seq, self._root,
                           self._next_page, len(durable_free))
        body += struct.pack(f"<{len(durable_free)}Q", *durable_free)
        body += b"\x00" * (PAGE_SIZE - len(body))
        crc = zlib.crc32(body[4:])
        page = struct.pack("<I", crc) + body[4:]
        slot = self._commit_seq % 2
        await self._file.write(slot * PAGE_SIZE, page)
        await self._file.sync()
        # the old tree is no longer referenced: recycle its pages
        self._free = all_free
        self._pending_free = []
