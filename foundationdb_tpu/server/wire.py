"""Binary encoding of mutations and log entries.

Reference: flow/serialize.h — byte-identical, versioned archives; the
TLog's persisted format and (later) the RPC wire format both build on
this. Little-endian, length-prefixed; a one-byte protocol version
leads every entry so future formats can evolve (ref: IncludeVersion,
flow/serialize.h:276).
"""

from __future__ import annotations

import struct
from typing import Tuple

from ..flow import error
from .types import MutationRef, TaggedMutation

PROTOCOL_VERSION = 2
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def encode_mutation(m: MutationRef) -> bytes:
    return b"".join((bytes([m.type]), _U32.pack(len(m.param1)), m.param1,
                     _U32.pack(len(m.param2)), m.param2))


def decode_mutation(buf: bytes, off: int):
    t = buf[off]
    off += 1
    (l1,) = _U32.unpack_from(buf, off)
    p1 = bytes(buf[off + 4:off + 4 + l1])
    off += 4 + l1
    (l2,) = _U32.unpack_from(buf, off)
    p2 = bytes(buf[off + 4:off + 4 + l2])
    off += 4 + l2
    return MutationRef(t, p1, p2), off


def encode_mutations(mutations) -> bytes:
    out = [_U32.pack(len(mutations))]
    for m in mutations:
        out.append(encode_mutation(m))
    return b"".join(out)


def decode_mutations(buf: bytes, off: int = 0):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    out = []
    for _ in range(n):
        m, off = decode_mutation(buf, off)
        out.append(m)
    return tuple(out), off


def encode_tagged_mutations(tagged) -> bytes:
    out = [_U32.pack(len(tagged))]
    for tm in tagged:
        out.append(_U16.pack(len(tm.tags)))
        for t in tm.tags:
            out.append(_U16.pack(t))
        out.append(encode_mutation(tm.mutation))
    return b"".join(out)


def decode_tagged_mutations(buf: bytes, off: int = 0):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    out = []
    for _ in range(n):
        (ntags,) = _U16.unpack_from(buf, off)
        off += 2
        tags = []
        for _t in range(ntags):
            (tag,) = _U16.unpack_from(buf, off)
            tags.append(tag)
            off += 2
        m, off = decode_mutation(buf, off)
        out.append(TaggedMutation(tuple(tags), m))
    return tuple(out), off


def encode_log_entry(version: int, tagged_mutations) -> bytes:
    """One TLog record: [proto u8][version u64][tagged mutations]."""
    return bytes([PROTOCOL_VERSION]) + _U64.pack(version) + \
        encode_tagged_mutations(tagged_mutations)


def decode_log_entry(buf: bytes) -> Tuple[int, Tuple[TaggedMutation, ...]]:
    if not buf or buf[0] != PROTOCOL_VERSION:
        raise error("incompatible_protocol_version")
    (version,) = _U64.unpack_from(buf, 1)
    tagged, _ = decode_tagged_mutations(buf, 9)
    return version, tagged
