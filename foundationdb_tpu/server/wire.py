"""Binary encoding of mutations and log entries.

Reference: flow/serialize.h — byte-identical, versioned archives; the
TLog's persisted format and (later) the RPC wire format both build on
this. Little-endian, length-prefixed; a one-byte protocol version
leads every entry so future formats can evolve (ref: IncludeVersion,
flow/serialize.h:276).
"""

from __future__ import annotations

import struct
from typing import Tuple

from ..flow import error
from .types import MutationRef

PROTOCOL_VERSION = 1
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def encode_mutations(mutations) -> bytes:
    out = [_U32.pack(len(mutations))]
    for m in mutations:
        out.append(bytes([m.type]))
        out.append(_U32.pack(len(m.param1)))
        out.append(m.param1)
        out.append(_U32.pack(len(m.param2)))
        out.append(m.param2)
    return b"".join(out)


def decode_mutations(buf: bytes, off: int = 0):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    out = []
    for _ in range(n):
        t = buf[off]
        off += 1
        (l1,) = _U32.unpack_from(buf, off)
        p1 = bytes(buf[off + 4:off + 4 + l1])
        off += 4 + l1
        (l2,) = _U32.unpack_from(buf, off)
        p2 = bytes(buf[off + 4:off + 4 + l2])
        off += 4 + l2
        out.append(MutationRef(t, p1, p2))
    return tuple(out), off


def encode_log_entry(version: int, mutations) -> bytes:
    """One TLog record: [proto u8][version u64][mutations]."""
    return bytes([PROTOCOL_VERSION]) + _U64.pack(version) + \
        encode_mutations(mutations)


def decode_log_entry(buf: bytes) -> Tuple[int, Tuple[MutationRef, ...]]:
    if not buf or buf[0] != PROTOCOL_VERSION:
        raise error("incompatible_protocol_version")
    (version,) = _U64.unpack_from(buf, 1)
    mutations, _ = decode_mutations(buf, 9)
    return version, mutations
