"""Coordination: generation registers, quorum coordinated state, and
leader election.

Reference: fdbserver/Coordination.actor.cpp (GenerationRegInterface —
a per-coordinator two-field generation register), CoordinatedState
.actor.cpp:60-197 (read / setExclusive with majority quorums: a reader
picks a fresh generation, performs a quorum read that also raises each
register's read-generation, then a quorum write commits at that
generation; a competing writer with a newer generation makes the older
one fail with coordinated_state_conflict), and LeaderElection.actor.cpp
:78 (candidacy polling with majority nomination).

The registers live in coordinator processes reached over the simulated
network, so partitions/kills exercise the quorum logic for real. State
is in-memory per coordinator process lifetime — the reference persists
it via an OnDemandStore; killing a majority of coordinators here is
cluster loss, same as the reference's guidance.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .. import flow
from ..flow import FlowLock, TaskPriority, error
from ..rpc import RequestStream, SimProcess


class UniqueGeneration(NamedTuple):
    """(ref: UniqueGeneration in CoordinationInterface.h — ordered by
    (generation, uid) so concurrent readers with the same count still
    totally order)."""

    gen: int
    uid: int


ZERO_GEN = UniqueGeneration(0, 0)


class GenRegReadRequest(NamedTuple):
    key: bytes
    gen: UniqueGeneration


class GenRegReadReply(NamedTuple):
    value: Optional[object]
    gen: UniqueGeneration        # generation the value was written at
    read_gen: UniqueGeneration   # the register's (raised) read generation


class GenRegWriteRequest(NamedTuple):
    key: bytes
    gen: UniqueGeneration
    value: object


class GenRegWriteReply(NamedTuple):
    gen: UniqueGeneration        # register's read gen (== req.gen on success)


class CandidacyRequest(NamedTuple):
    key: bytes
    candidate: object            # LeaderInfo (None = read-only poll)
    prev_change_id: int


class CandidacyReply(NamedTuple):
    leader: object
    change_id: int


class LeaderInfo(NamedTuple):
    """What the winning candidate publishes through the coordinators:
    enough for a CLIENT to (re)connect to the cluster controller (ref:
    LeaderInterface / ClientDBInfo reaching clients via MonitorLeader —
    the coordinators are how a client finds the CC after the one it
    knew died). Ordered by (priority, name): a lower priority value
    wins, so an explicitly promoted controller (region failover,
    forceRecovery) can take leadership over a dead incumbent the
    coordinators cannot themselves detect (ref: the bestPriority rules
    in LeaderElection.actor.cpp / ClusterController's leader fitness)."""

    priority: int
    name: str
    open_db: object = None       # NetworkRef: openDatabase endpoint
    status: object = None        # NetworkRef: status endpoint
    management: object = None    # NetworkRef: management endpoint


def _cand_key(c) -> tuple:
    """Election ordering/equality key (refs deserialize into fresh
    objects — never compare or hash them)."""
    if isinstance(c, LeaderInfo):
        return (c.priority, c.name)
    return (0, c)


class ForwardRequest(NamedTuple):
    """Decommission this coordinator: every further register/candidacy
    request is answered with the NEW coordinator set (ref:
    ForwardRequest, fdbserver/CoordinationInterface.h — the old quorum
    keeps redirecting clients after a coordinators change)."""

    coordinators: tuple          # ref 4-tuples (reads,writes,cand,fwd)


class Forwarded(NamedTuple):
    """Reply from a decommissioned coordinator."""

    coordinators: tuple


class MovedValue(NamedTuple):
    """Tombstone written EXCLUSIVELY into the old quorum when the
    coordinated state moves: readers that raced the forward requests
    still learn the new set, and the carried value keeps the state
    readable even if the mover crashed before the forwards landed
    (ref: MovableValue modes, CoordinatedState.actor.cpp:220)."""

    coordinators: tuple
    value: object


class Coordinator:
    """One coordination server (ref: coordinationServer,
    Coordination.actor.cpp). With a disk, the generation register
    persists through an OnDemandStore analogue (a DiskQueue holding the
    latest register image), so the coordinated state — and therefore
    the whole cluster — survives a full process restart."""

    def __init__(self, process: SimProcess, disk=None):
        self.process = process
        # generation register: key -> (value, write_gen, read_gen)
        self._reg: dict = {}
        # leader election register: key -> (leader, change_id) —
        # ephemeral by design: elections re-run on boot
        self._leader: dict = {}
        self.reads = RequestStream(process)
        self.writes = RequestStream(process)
        self.candidacies = RequestStream(process)
        self.forwards = RequestStream(process)
        # set after a coordinators change: all traffic redirects. Not
        # persisted — refs don't survive a process restart in sim, and
        # a moved-away quorum is decommissioned anyway (the reference
        # persists a connection STRING with an expiry instead).
        self._forward: Optional[tuple] = None
        if disk is not None:
            from .diskqueue import DiskQueue
            self._dq = DiskQueue(disk, f"{process.name}.reg", owner=process)
        else:
            self._dq = None
        # the DiskQueue is single-writer; reads raising read_gen and
        # writes both persist, so their pushes must serialize
        self._persist_lock = FlowLock()
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._run(), TaskPriority.COORDINATION,
                                    name=f"{self.process.name}.run"))
        self.process.on_kill(self._actors.cancel_all)

    async def _run(self) -> None:
        if self._dq is not None:
            payloads = await self._dq.recover()
            if payloads:
                from ..rpc import wire
                self._reg = wire.from_bytes(payloads[-1], None)
                self._dq.pop(self._dq.next_seq - 2)
        for coro, name in ((self._read_loop(), "genReads"),
                           (self._write_loop(), "genWrites"),
                           (self._leader_loop(), "leader"),
                           (self._forward_loop(), "forward")):
            self._actors.add(flow.spawn(coro, TaskPriority.COORDINATION,
                                        name=f"{self.process.name}.{name}"))

    async def _forward_loop(self):
        while True:
            req, reply = await self.forwards.pop()
            if any(c[0].endpoint.process.name == self.process.name
                   for c in req.coordinators):
                # this coordinator is a MEMBER of the new set: it is
                # rejoining, not being decommissioned — clear any stale
                # forward so a change-back can reuse old hosts
                flow.cover("coordination.forward.rejoin")
                self._forward = None
            else:
                self._forward = tuple(req.coordinators)
            reply.send(None)

    async def _persist(self) -> None:
        """Fsync the register image BEFORE acking (ref: the reference's
        OnDemandStore commit before GenerationReg replies)."""
        if self._dq is None:
            return
        from ..rpc import wire
        payload = wire.to_bytes(self._reg)
        await self._persist_lock.take()
        try:
            seq = await self._dq.push(payload)
            await self._dq.commit()
            self._dq.pop(seq - 1)   # only the newest image matters
        finally:
            self._persist_lock.release()

    async def _read_loop(self):
        while True:
            req, reply = await self.reads.pop()
            if self._forward is not None:
                reply.send(Forwarded(self._forward))
                continue
            value, wgen, rgen = self._reg.get(req.key, (None, ZERO_GEN,
                                                        ZERO_GEN))
            if req.gen > rgen:
                rgen = req.gen
                self._reg[req.key] = (value, wgen, rgen)
                # the raised read generation must survive a crash, or a
                # pre-crash writer could still commit at an old gen
                await self._persist()
            reply.send(GenRegReadReply(value, wgen, rgen))

    async def _write_loop(self):
        while True:
            req, reply = await self.writes.pop()
            if self._forward is not None:
                reply.send(Forwarded(self._forward))
                continue
            value, wgen, rgen = self._reg.get(req.key, (None, ZERO_GEN,
                                                        ZERO_GEN))
            if req.gen >= rgen and req.gen >= wgen:
                self._reg[req.key] = (req.value, req.gen,
                                      max(rgen, req.gen))
                await self._persist()
                reply.send(GenRegWriteReply(req.gen))
            else:
                # a newer reader/writer got here first
                reply.send(GenRegWriteReply(max(rgen, wgen)))

    async def _leader_loop(self):
        while True:
            req, reply = await self.candidacies.pop()
            if self._forward is not None:
                reply.send(Forwarded(self._forward))
                continue
            cur, change = self._leader.get(req.key, (None, 0))
            if req.candidate is not None and (
                    cur is None
                    or _cand_key(req.candidate) < _cand_key(cur)):
                # smaller (priority, id) wins (deterministic; ref:
                # LeaderElection nominates the best candidate)
                cur, change = req.candidate, change + 1
                self._leader[req.key] = (cur, change)
            reply.send(CandidacyReply(cur, change))


class CoordinatedState:
    """Majority-quorum client over the coordinators' generation
    registers (ref: CoordinatedState.actor.cpp:60-197)."""

    def __init__(self, coordinators, process: SimProcess,
                 key: bytes = b"\xff/coordinatedState"):
        self.coordinators = list(coordinators)  # [(reads, writes) refs]
        self.process = process
        self.key = key
        self._gen = ZERO_GEN

    def _fresh_gen(self) -> UniqueGeneration:
        return UniqueGeneration(self._gen.gen + 1,
                                flow.g_random.random_int(0, 1 << 30))

    async def _quorum(self, futs):
        """Wait until every attempt settles (sends to dead coordinators
        error rather than hang in sim), then require a majority of
        successes (ref: replicatedRead/Write quorum checks)."""
        need = len(futs) // 2 + 1
        settled = await flow.all_of(futs)  # catch_errors wrappers
        oks = [f.get() for f in settled if not f.is_error]
        if len(oks) < need:
            raise error("coordinators_changed")
        return oks

    @staticmethod
    def _ref_id(r) -> tuple:
        return (r.endpoint.process.name, r.endpoint.token)

    def _is_current_set(self, coordinators: tuple) -> bool:
        """True iff `coordinators` names the set this client already
        targets (refs deserialize into fresh objects — compare
        process/token identity)."""
        mine = {(self._ref_id(r), self._ref_id(w))
                for r, w in self.coordinators}
        theirs = {(self._ref_id(c[0]), self._ref_id(c[1]))
                  for c in coordinators}
        return mine == theirs

    def _follow(self, coordinators: tuple) -> None:
        """Retarget at a forwarded-to coordinator set (ref:
        MovableCoordinatedState following a move)."""
        self.coordinators = [(c[0], c[1]) for c in coordinators]
        self._gen = ZERO_GEN

    async def read(self):
        """Quorum read, raising read generations so any older in-flight
        write can no longer succeed (ref: replicatedRead). Follows a
        moved quorum: Forwarded replies from decommissioned
        coordinators, or a MovedValue tombstone left by the mover."""
        for _hop in range(4):
            g = self._fresh_gen()
            futs = [flow.catch_errors(reads.get_reply(
                GenRegReadRequest(self.key, g), self.process))
                for reads, _w in self.coordinators]
            replies = await self._quorum(futs)
            fwd = next((r for r in replies if isinstance(r, Forwarded)),
                       None)
            if fwd is not None:
                self._follow(fwd.coordinators)
                continue
            best = max(replies, key=lambda r: r.gen)
            max_rgen = max(r.read_gen for r in replies)
            self._gen = max(g, max_rgen, best.gen)
            if isinstance(best.value, MovedValue):
                if self._is_current_set(best.value.coordinators):
                    # the move landed HERE: when old and new sets
                    # overlap, shared members hold the tombstone as
                    # their newest write — its carried value IS the
                    # state (following would loop into ourselves)
                    flow.cover("coordination.read.moved_self")
                    return best.value.value
                # mover may have crashed before the forwards landed:
                # the new quorum was seeded BEFORE this tombstone was
                # written, so following always finds the state
                flow.cover("coordination.read.moved_value")
                self._follow(best.value.coordinators)
                continue
            return best.value
        raise error("coordinators_changed")

    async def set_exclusive(self, value) -> None:
        """Quorum write at the generation observed by the last read;
        fails with coordinated_state_conflict if any newer reader or
        writer intervened (ref: replicatedWrite + seq checks). A
        forwarded coordinator means the quorum moved under us — the
        caller must re-read (which follows) before writing again."""
        g = self._gen
        futs = [flow.catch_errors(writes.get_reply(
            GenRegWriteRequest(self.key, g, value), self.process))
            for _r, writes in self.coordinators]
        replies = await self._quorum(futs)
        if any(isinstance(r, Forwarded) for r in replies):
            raise error("coordinated_state_conflict")
        if any(r.gen > g for r in replies):
            raise error("coordinated_state_conflict")


async def elect_leader(coordinators, key: bytes, candidate,
                       process: SimProcess):
    """Poll the coordinators until a majority nominate `candidate`
    (ref: tryBecomeLeaderInternal, LeaderElection.actor.cpp:78).
    `coordinators` is the ref-tuple list (candidacy endpoint at [2]).
    Returns the coordinator set the election concluded on — a
    forwarded (moved-away) quorum redirects the candidate to the new
    set. Raises operation_failed if a different candidate holds a
    majority."""
    hops = 0
    while True:
        futs = [flow.catch_errors(c[2].get_reply(
            CandidacyRequest(key, candidate, 0), process))
            for c in coordinators]
        settled = await flow.all_of(futs)
        replies = [f.get() for f in settled if not f.is_error]
        fwd = next((r for r in replies if isinstance(r, Forwarded)), None)
        if fwd is not None:
            # bounded: a forward CYCLE (only possible via operator
            # error) must surface as a failure, not an infinite chase
            hops += 1
            if hops > flow.SERVER_KNOBS.coordinator_forward_hops_max:
                raise error("coordinators_changed")
            coordinators = list(fwd.coordinators)
            continue
        hops = 0
        votes: dict = {}
        for r in replies:
            k = None if r.leader is None else _cand_key(r.leader)
            votes[k] = votes.get(k, 0) + 1
        need = len(coordinators) // 2 + 1
        if votes.get(_cand_key(candidate), 0) >= need:
            return coordinators
        for other, n in votes.items():
            if other is not None and other != _cand_key(candidate) \
                    and n >= need:
                raise error("operation_failed")
        await flow.delay(flow.SERVER_KNOBS.candidacy_poll_interval,
                         TaskPriority.COORDINATION)


async def get_leader(coordinators, key: bytes, process: SimProcess):
    """Read the current leader from a coordinator majority WITHOUT
    nominating (ref: MonitorLeader's getLeader — clients poll the
    coordinators to find the cluster controller; this is how a client
    survives the death of the CC it was handed at construction).
    Returns the nominated LeaderInfo, or None when no majority of
    coordinators agrees (election in progress / quorum loss)."""
    for _hop in range(flow.SERVER_KNOBS.coordinator_forward_hops_max + 1):
        futs = [flow.catch_errors(flow.timeout_error(
            c[2].get_reply(CandidacyRequest(key, None, 0), process),
            flow.SERVER_KNOBS.failure_monitor_ping_timeout))
            for c in coordinators]
        settled = await flow.all_of(futs)
        replies = [f.get() for f in settled if not f.is_error]
        fwd = next((r for r in replies if isinstance(r, Forwarded)), None)
        if fwd is not None:
            # bounded like elect_leader: a forward cycle (operator
            # error) must surface as "no leader", not unbounded chasing
            coordinators = list(fwd.coordinators)
            continue
        votes: dict = {}
        leaders: dict = {}
        for r in replies:
            if r.leader is None:
                continue
            k = _cand_key(r.leader)
            votes[k] = votes.get(k, 0) + 1
            leaders[k] = r.leader
        need = len(coordinators) // 2 + 1
        for k, n in votes.items():
            if n >= need:
                return leaders[k]
        return None
    return None

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
