"""Conflict prediction & admission scheduling: the decision plane that
turns PR 2's conflict-attribution telemetry into committed goodput.

Reference: *Intelligent Transaction Scheduling via Conflict Prediction
in OLTP DBMS* (arXiv:2409.01675) — score each transaction's conflict
probability from observed per-range conflict statistics and steer the
likely losers at admission instead of letting them race to a
near-certain abort — and *Early Detection for MVCC Conflicts in
Hyperledger Fabric* (PAPERS.md) — push hot-key conflict windows to
clients so doomed transactions abort before they consume the commit
pipeline.

Three cooperating pieces, all fed by the cluster-merged decaying
`ConflictHotSpots` table the CC pushes at SCHED_HOT_PUSH_INTERVAL:

- `ConflictPredictor`: hot rows -> P(conflict) for a set of conflict
  ranges. Per-range probability is score/(score+SCHED_HOT_SCORE_SCALE)
  and independent ranges combine as 1 - prod(1 - p).
- `AdmissionScheduler` (proxy-side): commits whose probability crosses
  SCHED_CONFLICT_THRESHOLD are captured into a per-hot-range queue and
  released one per SCHED_RELEASE_SPACING, priority-aware (IMMEDIATE
  never defers, BATCH sorts last) and delay-bounded (SCHED_MAX_DELAY —
  a queue that cannot honor the bound admits immediately, counted as
  `sched_overflow`). Serialized releases land in successive commit
  batches at successive versions, so with transaction repair armed
  (server/repair.py) each released rival is repaired at its
  predecessor's version instead of the whole set racing one winner.
- `ConflictWindowCache` (client-side): hot windows piggybacked on GRV
  replies; `Transaction.commit` consults the cache and aborts locally
  (the same not_committed a resolver abort raises, from the same place
  in the commit path) when a read range overlaps a fresh window newer
  than the snapshot. Entries expire after CONFLICT_WINDOW_TTL.

Everything is knob-gated OFF by default: with CONFLICT_SCHEDULING=0,
TXN_REPAIR=0 and CLIENT_CONFLICT_WINDOWS=0 the commit path is
byte-identical to the abort-only pipeline.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .. import flow
from ..flow import SERVER_KNOBS, TaskPriority, error
from .types import PRIORITY_DEFAULT, PRIORITY_IMMEDIATE

#: hot row shape pushed by the CC: (begin, end, decayed score, raw
#: total, last attributed conflict version)
HotRow = Tuple[bytes, bytes, float, int, int]


class ConflictPredictor:
    """Hot-spot rows -> conflict probability (the admission scorer of
    arXiv:2409.01675, with the decaying range table standing in for
    the paper's learned per-type statistics)."""

    __slots__ = ("rows", "updated_at")

    def __init__(self):
        self.rows: Tuple[HotRow, ...] = ()
        self.updated_at = 0.0

    def update(self, rows, now: float) -> None:
        self.rows = tuple(rows)
        self.updated_at = now

    @staticmethod
    def range_probability(score: float) -> float:
        """One hot range's conflict probability from its decayed score
        (saturating map: a range attributed `scale` conflicts per
        half-life sits at 0.5)."""
        scale = float(SERVER_KNOBS.sched_hot_score_scale)
        if scale <= 0:
            return 1.0 if score > 0 else 0.0
        return score / (score + scale)

    def score(self, ranges) -> Tuple[float, Optional[Tuple[bytes, bytes]]]:
        """P(conflict) for a transaction touching `ranges`, plus the
        hottest overlapped hot range (the scheduler's queue key).
        Ranges are treated as independent: 1 - prod(1 - p_range)."""
        p_clear = 1.0
        hottest = None
        hot_score = -1.0
        for hb, he, s, _total, _v in self.rows:
            for b, e in ranges:
                if b < he and hb < e:
                    p_clear *= 1.0 - self.range_probability(s)
                    if s > hot_score:
                        hot_score, hottest = s, (hb, he)
                    break
        return 1.0 - p_clear, hottest


class AdmissionScheduler:
    """Per-hot-range deferral queues at the proxy (the steering half of
    the subsystem). Counters live in the owning proxy's
    CounterCollection (`sched_*`), so the metric sampler, status and
    exporter pick them up like every other proxy counter."""

    def __init__(self, process, stats: "flow.CounterCollection", release):
        self.process = process
        self.stats = stats
        self._release = release          # (req, reply) -> re-enqueue
        self.predictor = ConflictPredictor()
        #: (begin, end) -> [(-priority, seq, req, reply), ...]
        self._queues: dict = {}
        self._runners: dict = {}
        self._released_ids: set = set()
        self._seq = 0
        self._depth = 0
        self._actors = flow.ActorCollection()

    # -- feed ------------------------------------------------------------
    def update_hot_spots(self, rows, now: float) -> None:
        self.predictor.update(rows, now)
        self.stats.counter("sched_pushes").add(1)

    def queue_depth(self) -> int:
        """Deferred commits currently held (the ratekeeper's
        deferral-pressure input)."""
        return self._depth

    # -- admission -------------------------------------------------------
    def consider(self, req, reply) -> bool:
        """True when the commit was captured for deferred release; the
        caller must then NOT batch it — it re-enters the commit stream
        through the release callback."""
        rid = id(reply)
        if rid in self._released_ids:
            # a release coming back through the batcher: admit
            self._released_ids.discard(rid)
            return False
        k = SERVER_KNOBS
        if not k.conflict_scheduling or not self.predictor.rows:
            return False
        if getattr(req, "repair_attempt", 0):
            return False    # repair resubmissions already waited
        if getattr(req, "priority", PRIORITY_DEFAULT) >= PRIORITY_IMMEDIATE:
            return False
        if not req.mutations:
            return False
        prob, hot = self.predictor.score(
            tuple(req.read_conflict_ranges)
            + tuple(req.write_conflict_ranges))
        if hot is None or prob < float(k.sched_conflict_threshold):
            return False
        q = self._queues.setdefault(hot, [])
        spacing = float(k.sched_release_spacing)
        if len(q) >= int(k.sched_queue_max) or \
                (len(q) + 1) * spacing > float(k.sched_max_delay):
            # the bounded-delay contract beats the steering: admit now
            if not q:
                self._queues.pop(hot, None)
            flow.cover("sched.overflow")
            self.stats.counter("sched_overflow").add(1)
            return False
        flow.cover("sched.deferred")
        self._seq += 1
        q.append((-int(getattr(req, "priority", PRIORITY_DEFAULT)),
                  self._seq, req, reply))
        self._depth += 1
        self.stats.counter("sched_deferrals").add(1)
        self.stats.counter("sched_deferred_now").set(self._depth)
        runner = self._runners.get(hot)
        if runner is None or runner.is_ready:
            t = flow.spawn(self._drain(hot),
                           TaskPriority.PROXY_COMMIT_BATCHER,
                           name=f"{self.process.name}.schedDrain")
            self._runners[hot] = t
            self._actors.add(t)
        return True

    async def _drain(self, key) -> None:
        """Serialize one hot range's deferred commits: one release per
        spacing, highest priority first (ties FIFO), so rivals land in
        successive commit batches instead of one racing batch."""
        q = self._queues.get(key)
        while q:
            await flow.delay(float(SERVER_KNOBS.sched_release_spacing),
                             TaskPriority.PROXY_COMMIT_BATCHER)
            q = self._queues.get(key)
            if not q:
                break
            q.sort(key=lambda en: (en[0], en[1]))
            _p, _s, req, reply = q.pop(0)
            self._depth -= 1
            self._released_ids.add(id(reply))
            self.stats.counter("sched_released").add(1)
            self.stats.counter("sched_deferred_now").set(self._depth)
            self._release(req, reply)
        self._queues.pop(key, None)
        self._runners.pop(key, None)   # dead Task must not accumulate

    # -- surfaces --------------------------------------------------------
    def status(self) -> dict:
        snap = self.stats.snapshot()
        return {
            "enabled": int(bool(SERVER_KNOBS.conflict_scheduling)),
            "deferrals": snap.get("sched_deferrals", 0),
            "released": snap.get("sched_released", 0),
            "overflow": snap.get("sched_overflow", 0),
            "pushes": snap.get("sched_pushes", 0),
            "deferred_now": self._depth,
            "queue_ranges": len([q for q in self._queues.values() if q]),
            "hot_rows": len(self.predictor.rows),
        }

    def shutdown(self) -> None:
        """Epoch over: break every held commit so clients fail over
        instead of hanging (same contract as Proxy.stop's GRV drain)."""
        self._actors.cancel_all()
        for q in self._queues.values():
            for _p, _s, _req, reply in q:
                try:
                    reply.send_error(error("broken_promise"))
                except Exception:
                    pass  # already answered
        self._queues.clear()
        self._runners.clear()
        self._released_ids.clear()
        self._depth = 0


# -- client side -------------------------------------------------------

#: process-wide client-cache counters (the client_profile pattern:
#: every simulated client shares one collection, surfaced through
#: status.cluster.conflict_scheduling.client and the exporter)
g_client_window_stats = flow.CounterCollection("client_windows")


def note_windows_cached(n: int) -> None:
    g_client_window_stats.counter("windows_cached").set(n)
    g_client_window_stats.counter("window_updates").add(1)


def note_early_abort() -> None:
    g_client_window_stats.counter("early_aborts").add(1)


def client_window_counters() -> dict:
    return g_client_window_stats.snapshot()


class ConflictWindowCache:
    """Per-Database cache of hot-key conflict windows ridden in on GRV
    replies (the Hyperledger-style early-detection half). A window is
    (begin, end, last_version): the range has been aborting
    transactions, most recently at last_version. A commit whose read
    ranges overlap a LIVE window and whose snapshot predates the
    window's version is near-certain to abort at the resolver — the
    client aborts it locally instead. Entries expire
    CONFLICT_WINDOW_TTL seconds after arrival, so a range that cooled
    off (or a partitioned proxy's stale picture) stops aborting
    traffic without any cluster round trip."""

    __slots__ = ("_rows",)

    def __init__(self):
        #: (begin, end, last_version, expires_at)
        self._rows: tuple = ()

    def update(self, windows, now: float) -> None:
        ttl = float(SERVER_KNOBS.conflict_window_ttl)
        self._rows = tuple((b, e, v, now + ttl) for b, e, v in windows)
        note_windows_cached(len(self._rows))

    def live_rows(self, now: float) -> tuple:
        if self._rows and any(exp <= now for *_x, exp in self._rows):
            self._rows = tuple(r for r in self._rows if r[3] > now)
        return self._rows

    def doomed(self, read_ranges, snapshot: int, now: float) -> tuple:
        """The read ranges a live window dooms at this snapshot
        (empty tuple = submit normally)."""
        rows = self.live_rows(now)
        if not rows:
            return ()
        g_client_window_stats.counter("checks").add(1)
        out = []
        for b, e in read_ranges:
            for wb, we, wv, _exp in rows:
                if b < we and wb < e and snapshot < wv:
                    out.append((b, e))
                    break
        return tuple(out)
