"""Resolver role: ordered conflict-batch processing over a pluggable
conflict-set backend.

Reference: fdbserver/Resolver.actor.cpp `resolveBatch` (:71) — batches
arrive tagged (prev_version, version); processing waits until the
resolver has seen prev_version (NotifiedVersion ordering, :104-115),
runs the ConflictSet (SkipList.cpp; here any backend behind the
create_conflict_set plugin seam: python / native C++ / tpu / sharded
tpu), advances the window to version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
(:155), and replies one verdict per transaction.
"""

from __future__ import annotations

from collections import deque

from .. import flow
from ..flow import SERVER_KNOBS, NotifiedVersion, TaskPriority
from ..models import ResolverTransaction, create_conflict_set
from ..rpc import RequestStream, SimProcess
from .types import ResolutionMetricsReply, ResolveRequest


class Resolver:
    def __init__(self, process: SimProcess, backend: str = "python",
                 recovery_version: int = 0):
        self.process = process
        self.conflict_set = create_conflict_set(backend, recovery_version)
        # the MVCC window width (ref: Knobs.cpp:35; BUGGIFY shrinks it)
        self._mwtlv = SERVER_KNOBS.max_write_transaction_life_versions
        self.version = NotifiedVersion(recovery_version)
        self.resolves = RequestStream(process)
        # load accounting for resolutionBalancing (ref: the resolver's
        # iopsSample, Resolver.actor.cpp:277-283)
        self.work_units = 0
        self.key_hist = [0] * 256
        self.metrics = RequestStream(process)
        self.stats = flow.CounterCollection("resolver")
        # banded + sampled batch-resolve latency (the resolver stage of
        # the commit pipeline; ref: LatencyBands in status)
        self.resolve_bands = flow.RequestLatency("resolve")
        self._pressure_traced = False
        self._actors = flow.ActorCollection()
        # reply cache for duplicate delivery (proxy retry after a broken
        # reply): version -> verdicts, evicted incrementally once a
        # bounded number of newer batches exist
        # (ref: outstandingBatches, Resolver.actor.cpp:159,:241-257)
        self._reply_cache: dict[int, list[int]] = {}
        self._reply_order: deque[int] = deque()
        # a tiny cache stresses the duplicate-delivery fallback path
        self._cache_cap = 2 if flow.buggify("resolver/small_reply_cache") \
            else int(SERVER_KNOBS.resolver_reply_cache_size)

    def start(self) -> None:
        self._actors.add(flow.spawn(self._resolve_loop(),
                                    TaskPriority.PROXY_RESOLVER_REPLY,
                                    name=f"{self.process.name}.resolve"))
        self._actors.add(flow.spawn(self._metrics_loop(),
                                    TaskPriority.RESOLUTION_METRICS,
                                    name=f"{self.process.name}.metrics"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()
        self.resolves.close()
        self.metrics.close()

    async def _metrics_loop(self):
        while True:
            _req, reply = await self.metrics.pop()
            reply.send(ResolutionMetricsReply(self.work_units,
                                              tuple(self.key_hist)))

    @staticmethod
    def _mark(req, location):
        flow.g_trace_batch.add_events(getattr(req, "debug_ids", ()),
                                      "CommitDebug", location)

    async def _resolve_loop(self):
        while True:
            req, reply = await self.resolves.pop()
            flow.spawn(self._resolve_batch(req, reply),
                       TaskPriority.PROXY_RESOLVER_REPLY)

    async def _resolve_batch(self, req: ResolveRequest, reply):
        t0 = flow.now()
        # order batches by version, whatever the arrival order
        await self.version.when_at_least(req.prev_version)
        if self.version.get() >= req.version:
            # duplicate delivery (e.g. proxy retry): replay the original
            # verdicts so a retrying proxy cannot livelock
            # (ref: Resolver.actor.cpp:241-257). Conflict-everything only
            # if the entry aged out of the window.
            cached = self._reply_cache.get(req.version)
            flow.cover("resolver.reply_cache.hit", cached is not None)
            flow.cover("resolver.reply_cache.aged_out", cached is None)
            reply.send(cached if cached is not None
                       else [0] * len(req.transactions))
            return
        # resolver-leg stations + spans fire only on ACCEPTED first
        # deliveries (after the duplicate check): a proxy retry must
        # not file a phantom second resolver leg — or an unpaired
        # opening station — into the sampled stitching. Named for
        # where it sits (ref: the reference's post-version-ordering
        # AfterQueueSorted station) so a prev_version stall reads as
        # in-resolver ordering wait, not proxy->resolver network time.
        # Spans auto-parent onto the proxy's open commitBatch span.
        self._mark(req, "Resolver.resolveBatch.AfterQueueSorted")
        spans = flow.g_trace_batch.begin_spans(
            getattr(req, "debug_ids", ()), "Resolver.resolveBatch")
        try:
            txns = [ResolverTransaction(t.read_snapshot,
                                        t.read_conflict_ranges,
                                        t.write_conflict_ranges)
                    for t in req.transactions]
            for t in txns:
                for b, _e in t.read_ranges:
                    self.key_hist[b[0] if b else 0] += 1
                for b, _e in t.write_ranges:
                    self.key_hist[b[0] if b else 0] += 1
                self.work_units += len(t.read_ranges) + len(t.write_ranges)
            new_oldest = max(0, req.version - self._mwtlv)
            try:
                verdicts = self.conflict_set.resolve(txns, req.version,
                                                     new_oldest)
            except (ValueError, OverflowError) as e:
                # A malformed batch (e.g. a key wider than the backend's key
                # bucket) must not wedge the pipeline: conflict the whole
                # batch — clients see not_committed and retry — and still
                # advance the version so later batches proceed.
                flow.cover("resolver.batch.rejected")
                flow.TraceEvent("ResolverBatchRejected", self.process.name,
                                severity=flow.trace.SevWarnAlways).detail(
                    Version=req.version, Error=str(e)).log()
                verdicts = [0] * len(req.transactions)
                self.conflict_set.resolve([], req.version, new_oldest)
            self._reply_cache[req.version] = verdicts
            self._reply_order.append(req.version)
            while len(self._reply_order) > self._cache_cap:
                self._reply_cache.pop(self._reply_order.popleft(), None)
            self.version.set(req.version)
            self._mark(req, "Resolver.resolveBatch.After")
            self.stats.counter("batches_resolved").add(1)
            self.stats.counter("transactions_resolved").add(len(txns))
            self.resolve_bands.record(flow.now() - t0)
            reply.send(verdicts)
            self._check_state_pressure(req.version)
        finally:
            flow.g_trace_batch.finish_spans(spans)

    def kernel_stats(self) -> dict:
        """The conflict backend's device-kernel profile (occupancy,
        compile/execute accounting) for the status document; {} for
        host-only backends."""
        return self.conflict_set.kernel_stats()

    def state_size(self) -> int:
        """Conflict-history row estimate across backends (boundary rows
        for interval backends; a bisect-list length for the Python
        baseline)."""
        cs = self.conflict_set
        ic = getattr(cs, "interval_count", None)
        if ic is not None:
            # a method on the native backend, a property on the device
            # backends (incl. tpu-point) — support both
            return int(ic() if callable(ic) else ic)
        return len(getattr(cs, "_keys", ()))

    def _check_state_pressure(self, version: int) -> None:
        """(ref: the resolver memory back-pressure, Resolver.actor.cpp
        :91-98 — state beyond RESOLVER_STATE_MEMORY_LIMIT is a red
        flag: the window GC is not keeping up with the write rate.
        Interpreted here as a row count; surfaced via trace + counter
        so ratekeeper/status consumers can see it.)"""
        size = self.state_size()
        self.stats.counter("state_rows").set(size)
        limit = flow.SERVER_KNOBS.resolver_state_memory_limit
        if size > limit and not self._pressure_traced:
            self._pressure_traced = True
            flow.TraceEvent("ResolverStatePressure", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Rows=size, Limit=limit, Version=version).log()
        elif size <= limit:
            self._pressure_traced = False
