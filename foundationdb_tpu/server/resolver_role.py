"""Resolver role: ordered conflict-batch processing over a pluggable
conflict-set backend.

Reference: fdbserver/Resolver.actor.cpp `resolveBatch` (:71) — batches
arrive tagged (prev_version, version); processing waits until the
resolver has seen prev_version (NotifiedVersion ordering, :104-115),
runs the ConflictSet (SkipList.cpp; here any backend behind the
create_conflict_set plugin seam: python / native C++ / tpu / sharded
tpu), advances the window to version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
(:155), and replies one verdict per transaction.
"""

from __future__ import annotations

from .. import flow
from ..flow import NotifiedVersion, TaskPriority
from ..models import ResolverTransaction, create_conflict_set
from ..rpc import RequestStream, SimProcess
from .types import ResolveRequest

MAX_WRITE_TRANSACTION_LIFE_VERSIONS = 5_000_000  # ref: Knobs.cpp:35


class Resolver:
    def __init__(self, process: SimProcess, backend: str = "python",
                 recovery_version: int = 0):
        self.process = process
        self.conflict_set = create_conflict_set(backend, recovery_version)
        self.version = NotifiedVersion(recovery_version)
        self.resolves = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._resolve_loop(),
                                    TaskPriority.PROXY_RESOLVER_REPLY,
                                    name=f"{self.process.name}.resolve"))
        self.process.on_kill(self._actors.cancel_all)

    async def _resolve_loop(self):
        while True:
            req, reply = await self.resolves.pop()
            flow.spawn(self._resolve_batch(req, reply),
                       TaskPriority.PROXY_RESOLVER_REPLY)

    async def _resolve_batch(self, req: ResolveRequest, reply):
        # order batches by version, whatever the arrival order
        await self.version.when_at_least(req.prev_version)
        if self.version.get() >= req.version:
            # duplicate delivery (e.g. proxy retry): conflict everything;
            # the proxy treats it as not_committed and clients retry
            reply.send([0] * len(req.transactions))
            return
        txns = [ResolverTransaction(t.read_snapshot, t.read_conflict_ranges,
                                    t.write_conflict_ranges)
                for t in req.transactions]
        new_oldest = max(0, req.version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
        verdicts = self.conflict_set.resolve(txns, req.version, new_oldest)
        self.version.set(req.version)
        reply.send(verdicts)
