"""Resolver role: ordered conflict-batch processing over a pluggable
conflict-set backend.

Reference: fdbserver/Resolver.actor.cpp `resolveBatch` (:71) — batches
arrive tagged (prev_version, version); processing waits until the
resolver has seen prev_version (NotifiedVersion ordering, :104-115),
runs the ConflictSet (SkipList.cpp; here any backend behind the
create_conflict_set plugin seam: python / native C++ / tpu / sharded
tpu), advances the window to version - MAX_WRITE_TRANSACTION_LIFE_VERSIONS
(:155), and replies one verdict per transaction.
"""

from __future__ import annotations

from collections import deque

from .. import flow
from ..flow import SERVER_KNOBS, NotifiedVersion, TaskPriority
from ..models import ResolverTransaction, create_resilient_conflict_set
from ..models.conflict_set import clip_checkpoint, graft_checkpoint
from ..rpc import RequestStream, SimProcess
from .critical_path import RolePathRecorder
from .types import (ResolutionMetricsReply, ResolveReply, ResolveRequest,
                    ResolverCheckpointReply, ResolverCheckpointRequest,
                    ResolverInstallRequest)


class ConflictHotSpots:
    """Decaying top-K table of conflict-causing key ranges (ref: the
    per-range busyness tracking behind FDB's hot-key/hot-shard
    telemetry — TransactionTagCounter / StorageMetrics byteSample style
    exponential decay, applied here to attributed conflict ranges).

    Each attributed range accumulates a score that halves every
    `half_life` seconds of simulated time, so a burst of aborts shows
    up immediately and ages out instead of pinning the table forever.
    Bounded at `max_entries` (lowest decayed score evicted); `top(k)`
    is the status/CLI surface and `rows(k)` the raw feed the CC pushes
    to the proxies' conflict predictors (server/scheduler.py).

    Half-life, capacity and top-K are LIVE-READ from the knobs when
    not pinned at construction — the Smoother discipline PR 6
    established; a construction-time read would freeze a SimCluster's
    later knob changes out of the decay math (satellite audit: the
    bug PR 6 fixed in Smoother was latent here too)."""

    __slots__ = ("_half_life", "_max_entries", "_entries")

    def __init__(self, half_life: float = None, max_entries: int = None):
        self._half_life = half_life      # None -> live knob read
        self._max_entries = max_entries  # None -> live knob read
        # (begin, end) -> [decayed score, raw total, last update time,
        #                  last attributed conflict version]
        self._entries: dict = {}

    @property
    def half_life(self) -> float:
        return (self._half_life if self._half_life is not None
                else SERVER_KNOBS.hot_spot_half_life)

    @property
    def max_entries(self) -> int:
        return int(self._max_entries if self._max_entries is not None
                   else SERVER_KNOBS.hot_spot_max_entries)

    def _decayed(self, score: float, since: float, now: float) -> float:
        if now <= since or self.half_life <= 0:
            return score
        return score * 0.5 ** ((now - since) / self.half_life)

    def record(self, begin: bytes, end: bytes, weight: float = 1.0,
               version: int = 0) -> None:
        now = flow.now()
        ent = self._entries.get((begin, end))
        if ent is None:
            self._entries[(begin, end)] = [float(weight), 1, now, version]
        else:
            ent[0] = self._decayed(ent[0], ent[2], now) + weight
            ent[1] += 1
            ent[2] = now
            ent[3] = max(ent[3], version)
        # while, not if: a live-shrunk capacity knob drains the excess
        # instead of hovering one-in-one-out above the new bound
        while len(self._entries) > self.max_entries:
            worst = min(self._entries,
                        key=lambda k: self._decayed(
                            self._entries[k][0], self._entries[k][2], now))
            del self._entries[worst]

    def rows(self, k: int = None) -> list:
        """Raw decayed rows, hottest first: (begin, end, score, total,
        last attributed conflict version) — the conflict predictor /
        GRV conflict-window feed (bytes, unrounded)."""
        now = flow.now()
        out = [(b, e, self._decayed(s, t, now), total, ver)
               for (b, e), (s, total, t, ver) in self._entries.items()]
        out.sort(key=lambda r: (-r[2], r[0], r[1]))
        return out if k is None else out[:k]

    def top(self, k: int = None) -> list:
        """Status-ready rows, hottest first: decayed rate score + raw
        total per attributed range."""
        if k is None:
            k = int(SERVER_KNOBS.hot_spot_top_k)
        return [{"begin": b.hex(), "end": e.hex(),
                 "score": round(score, 4), "total": total}
                for b, e, score, total, _v in self.rows(k)]


class Resolver:
    def __init__(self, process: SimProcess, backend: str = "python",
                 recovery_version: int = 0):
        self.process = process
        # device backends arrive wrapped in the failover controller
        # (models/failover.py): checkpoint cadence, replay-log rebuild
        # on device faults, CPU failover, sampled shadow validation —
        # the resolver role itself never sees a DeviceFaultError
        self.conflict_set = create_resilient_conflict_set(
            backend, recovery_version)
        # the MVCC window width (ref: Knobs.cpp:35; BUGGIFY shrinks it)
        self._mwtlv = SERVER_KNOBS.max_write_transaction_life_versions
        self.version = NotifiedVersion(recovery_version)
        self.resolves = RequestStream(process)
        # load accounting for resolutionBalancing (ref: the resolver's
        # iopsSample, Resolver.actor.cpp:277-283)
        self.work_units = 0
        self.key_hist = [0] * 256
        self.metrics = RequestStream(process)
        self.stats = flow.CounterCollection("resolver")
        # banded + sampled batch-resolve latency (the resolver stage of
        # the commit pipeline; ref: LatencyBands in status)
        self.resolve_bands = flow.RequestLatency("resolve")
        # critical-path split (ISSUE 18): version-ordering wait vs
        # actual resolve service, recorded per accepted first delivery
        # while CRITICAL_PATH is armed
        self.path = RolePathRecorder("resolver")
        # decaying top-K table of conflict-causing key ranges, fed by
        # the backend's attribution on every batch (ref: the conflict
        # telemetry report_conflicting_keys exists to provide; the
        # conflict-aware scheduling literature presupposes exactly this
        # per-range signal)
        self.hot_spots = ConflictHotSpots()
        # QoS saturation signals: the resolve pipeline's occupancy and
        # forced-drain counters (PR 4) smoothed into the telemetry
        # plane — the Ratekeeper's pipeline_occupancy throttle input.
        # Pull model: qos_sample() reads pipeline_stats() on demand
        self._qos_forced_rate = flow.SmoothedRate()
        self._qos_batch_rate = flow.SmoothedRate()
        self._qos_txn_rate = flow.SmoothedRate()
        self._pressure_traced = False
        self._actors = flow.ActorCollection()
        # reply cache for duplicate delivery (proxy retry after a broken
        # reply): version -> verdicts, evicted incrementally once a
        # bounded number of newer batches exist
        # (ref: outstandingBatches, Resolver.actor.cpp:159,:241-257)
        self._reply_cache: dict[int, list[int]] = {}
        self._reply_order: deque[int] = deque()
        # batches submitted to the conflict backend but not yet drained
        # (the resolve-pipeline window): version -> (ticket, want_report,
        # txns). A duplicate delivered in this window drains the SAME
        # ticket (idempotent) instead of falling to conflict-everything.
        self._inflight: dict[int, tuple] = {}
        # a tiny cache stresses the duplicate-delivery fallback path
        self._cache_cap = 2 if flow.buggify("resolver/small_reply_cache") \
            else int(SERVER_KNOBS.resolver_reply_cache_size)
        # split/merge state-handoff endpoint (ISSUE 15): the balance
        # loop checkpoints a donor's clipped interval state here and
        # grafts it into the recipient — live handoff instead of a
        # full-MVCC-window double-delivery wait
        self.handoffs = RequestStream(process)
        self.last_handoff: "dict | None" = None
        # wall-clock deadline pacer for the modeled service cost: in a
        # non-virtual scheduler each sleep overshoots by OS-timer slop,
        # so charging cost per batch as independent delays understates
        # capacity; tracking the server's next-free deadline absorbs the
        # overshoot (virtual schedulers keep the exact flow.delay path)
        self._pace_free = 0.0

    def start(self) -> None:
        self._actors.add(flow.spawn(self._resolve_loop(),
                                    TaskPriority.PROXY_RESOLVER_REPLY,
                                    name=f"{self.process.name}.resolve"))
        self._actors.add(flow.spawn(self._metrics_loop(),
                                    TaskPriority.RESOLUTION_METRICS,
                                    name=f"{self.process.name}.metrics"))
        self._actors.add(flow.spawn(self._handoff_loop(),
                                    TaskPriority.RESOLUTION_METRICS,
                                    name=f"{self.process.name}.handoff"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()
        self.resolves.close()
        self.metrics.close()
        self.handoffs.close()

    async def _metrics_loop(self):
        while True:
            _req, reply = await self.metrics.pop()
            reply.send(ResolutionMetricsReply(self.work_units,
                                              tuple(self.key_hist)))

    async def _handoff_loop(self):
        while True:
            req, reply = await self.handoffs.pop()
            flow.spawn(self._serve_handoff(req, reply),
                       TaskPriority.RESOLUTION_METRICS)

    async def _serve_handoff(self, req, reply):
        """One state-handoff RPC (ISSUE 15). Checkpoint: wait out the
        version chain to the move's effective version (every pre-move
        batch is then in backend state — checkpoint() drains the
        resolve pipeline), cut the full checkpoint, clip the span.
        Install: graft the piece into the live state with pointwise max
        (models/conflict_set.graft_checkpoint), so writes this resolver
        already recorded since the move survive. Both run between batch
        submissions on the single-threaded loop, so the state they read
        and replace is never half a batch."""
        try:
            if isinstance(req, ResolverCheckpointRequest):
                if req.min_version:
                    await self.version.when_at_least(req.min_version)
                ckpt = self.conflict_set.checkpoint()
                piece = clip_checkpoint(ckpt, req.begin, req.end)
                self.stats.counter("split_checkpoints").add(1)
                self.last_handoff = {
                    "op": "checkpoint", "begin": req.begin.hex(),
                    "end": req.end.hex() if req.end is not None else "",
                    "version": self.version.get(),
                    "rows": len(piece.keys)}
                reply.send(ResolverCheckpointReply(piece,
                                                   self.version.get()))
            elif isinstance(req, ResolverInstallRequest):
                base = self.conflict_set.checkpoint()
                self.conflict_set.restore(
                    graft_checkpoint(base, req.piece))
                self.stats.counter("range_installs").add(1)
                self.last_handoff = {
                    "op": "install", "begin": req.begin.hex(),
                    "end": req.end.hex() if req.end is not None else "",
                    "version": self.version.get(),
                    "rows": len(req.piece.keys)}
                reply.send(self.version.get())
            else:
                reply.send_error(flow.error("client_invalid_operation"))
        except flow.FdbError as e:
            if e.name == "operation_cancelled":
                raise
            reply.send_error(e)
        except Exception as e:  # noqa: BLE001 — a bad piece fails itself
            flow.TraceEvent("ResolverHandoffFailed", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Error=repr(e)).log()
            self.stats.counter("handoff_errors").add(1)
            reply.send_error(flow.error("internal_error"))

    @staticmethod
    def _mark(req, location):
        flow.g_trace_batch.add_events(getattr(req, "debug_ids", ()),
                                      "CommitDebug", location)

    async def _resolve_loop(self):
        while True:
            req, reply = await self.resolves.pop()
            flow.spawn(self._resolve_batch(req, reply),
                       TaskPriority.PROXY_RESOLVER_REPLY)

    async def _charge_cost(self, amount: float):
        """Charge modeled service time. Virtual scheduler: the exact
        historical flow.delay (byte-identical sim pins). Wall clock: a
        deadline pacer — the resolver is a serial server whose next-free
        instant advances by `amount` per batch; sleeping to the deadline
        (rather than for the amount) absorbs per-sleep OS overshoot, so
        measured capacity matches the model at 1/cost txn/s."""
        sched = flow.get_scheduler()
        if sched is not None and not sched.virtual:
            now = flow.now()
            self._pace_free = max(self._pace_free, now) + amount
            wait = self._pace_free - now
            if wait > 0:
                await flow.delay(wait, TaskPriority.PROXY_RESOLVER_REPLY)
            return
        await flow.delay(amount, TaskPriority.PROXY_RESOLVER_REPLY)

    async def _resolve_batch(self, req: ResolveRequest, reply):
        t0 = flow.now()
        # order batches by version, whatever the arrival order
        await self.version.when_at_least(req.prev_version)
        if self.version.get() >= req.version:
            # duplicate delivery (e.g. proxy retry): a batch still in
            # the resolve-pipeline window (submitted, version advanced,
            # verdicts not yet read back) drains the same ticket and
            # replies identically; otherwise replay the cached verdicts
            # so a retrying proxy cannot livelock
            # (ref: Resolver.actor.cpp:241-257). Conflict-everything only
            # if the entry aged out of the window.
            pend = self._inflight.get(req.version)
            if pend is not None:
                flow.cover("resolver.reply_cache.inflight_dup")
                ticket, want_report, txns = pend
                verdicts, attributions = \
                    self.conflict_set.drain_with_attribution(ticket)
                reply.send(self._build_payload(
                    txns, verdicts, attributions, want_report,
                    record_hot=False, version=req.version))
                return
            cached = self._reply_cache.get(req.version)
            flow.cover("resolver.reply_cache.hit", cached is not None)
            flow.cover("resolver.reply_cache.aged_out", cached is None)
            reply.send(cached if cached is not None
                       else [0] * len(req.transactions))
            return
        # resolver-leg stations + spans fire only on ACCEPTED first
        # deliveries (after the duplicate check): a proxy retry must
        # not file a phantom second resolver leg — or an unpaired
        # opening station — into the sampled stitching. Named for
        # where it sits (ref: the reference's post-version-ordering
        # AfterQueueSorted station) so a prev_version stall reads as
        # in-resolver ordering wait, not proxy->resolver network time.
        # Spans auto-parent onto the proxy's open commitBatch span.
        self._mark(req, "Resolver.resolveBatch.AfterQueueSorted")
        # wait segment closed: everything before this point was
        # version-ordering; everything after is service
        t_sorted = flow.now() if SERVER_KNOBS.critical_path else t0
        spans = flow.g_trace_batch.begin_spans(
            getattr(req, "debug_ids", ()), "Resolver.resolveBatch")
        try:
            txns = [ResolverTransaction(t.read_snapshot,
                                        t.read_conflict_ranges,
                                        t.write_conflict_ranges)
                    for t in req.transactions]
            for t in txns:
                for b, _e in t.read_ranges:
                    self.key_hist[b[0] if b else 0] += 1
                for b, _e in t.write_ranges:
                    self.key_hist[b[0] if b else 0] += 1
                self.work_units += len(t.read_ranges) + len(t.write_ranges)
            # repairable transactions need the cause mask at the proxy
            # even when the client never asked to SEE it — repair
            # (server/repair.py) keys off exactly the attributed reads.
            # Gated on the knob: with TXN_REPAIR off the declaration
            # rides the wire inert, costing no attribution payload
            repair_on = bool(SERVER_KNOBS.txn_repair)
            want_report = any(
                getattr(t, "report_conflicting_keys", False)
                or (repair_on and getattr(t, "repairable", False))
                for t in req.transactions)
            # modeled resolution service time (SIM_RESOLVE_COST_PER_TXN,
            # default 0 = off): charged BEFORE the version chain
            # advances, so the resolver is a genuine serial server at
            # 1/cost txn/s — the system bench's saturation model
            # (tools/clusterbench.py; resolution cost is the quantity
            # the source paper scales against, arXiv:1804.00947). Only
            # first-delivery batches with transactions pay.
            cost = float(SERVER_KNOBS.sim_resolve_cost_per_txn)
            if cost > 0 and txns:
                await self._charge_cost(cost * len(txns))
            new_oldest = max(0, req.version - self._mwtlv)
            attributions = None
            verdicts = None
            try:
                # split submit/drain: the dispatch is queued WITHOUT
                # blocking on any result, the version chain advances at
                # submit time, and this actor yields once — so successor
                # batches submit while this one's verdict D2H is still
                # in flight. Up to RESOLVE_PIPELINE_DEPTH batches
                # overlap end to end with the proxy's
                # batch_resolving/batch_logging interlocks.
                ticket = self.conflict_set.submit(
                    txns, req.version, new_oldest, attribute=True)
            except (ValueError, OverflowError) as e:
                # A malformed batch (e.g. a key wider than the backend's key
                # bucket) must not wedge the pipeline: conflict the whole
                # batch — clients see not_committed and retry — and still
                # advance the version so later batches proceed.
                flow.cover("resolver.batch.rejected")
                flow.TraceEvent("ResolverBatchRejected", self.process.name,
                                severity=flow.trace.SevWarnAlways).detail(
                    Version=req.version, Error=str(e)).log()
                verdicts = [0] * len(req.transactions)
                self.conflict_set.resolve([], req.version, new_oldest)
                self.version.set(req.version)
            if verdicts is None:
                self._inflight[req.version] = (ticket, want_report, txns)
                self.version.set(req.version)
                await flow.delay(0, TaskPriority.PROXY_RESOLVER_REPLY)
                verdicts, attributions = \
                    self.conflict_set.drain_with_attribution(ticket)
            payload = self._build_payload(txns, verdicts, attributions,
                                          want_report, record_hot=True,
                                          version=req.version)
            self._reply_cache[req.version] = payload
            self._reply_order.append(req.version)
            while len(self._reply_order) > self._cache_cap:
                self._reply_cache.pop(self._reply_order.popleft(), None)
            self._mark(req, "Resolver.resolveBatch.After")
            self.stats.counter("batches_resolved").add(1)
            self.stats.counter("transactions_resolved").add(len(txns))
            done = flow.now()
            self.resolve_bands.record(done - t0)
            if SERVER_KNOBS.critical_path:
                self.path.record(t_sorted - t0, done - t_sorted)
            reply.send(payload)
            self._check_state_pressure(req.version)
        finally:
            self._inflight.pop(req.version, None)
            flow.g_trace_batch.finish_spans(spans)

    def _build_payload(self, txns, verdicts, attributions, want_report,
                       record_hot: bool, version: int = 0):
        """Attribution -> actual key ranges: feed the hot-spot table
        (first delivery only — a duplicate must not double-count; the
        batch version rides along as the range's last-conflict
        version, the client conflict windows' staleness anchor) and
        build the per-txn reply payload when some txn asked for
        report_conflicting_keys."""
        ranges_per_txn = [()] * len(txns)
        if attributions is not None:
            n_attr = 0
            for t, idxs in enumerate(attributions):
                if not idxs:
                    continue
                rs = tuple(txns[t].read_ranges[i] for i in idxs)
                ranges_per_txn[t] = rs
                if record_hot:
                    n_attr += len(rs)
                    for b, e in rs:
                        self.hot_spots.record(b, e, version=version)
            if record_hot and n_attr:
                self.stats.counter("conflict_ranges_attributed").add(n_attr)
        return (ResolveReply(tuple(verdicts), tuple(ranges_per_txn))
                if want_report else verdicts)

    def kernel_stats(self) -> dict:
        """The conflict backend's device-kernel profile (occupancy,
        compile/execute accounting) for the status document; {} for
        host-only backends."""
        return self.conflict_set.kernel_stats()

    def pipeline_stats(self) -> dict:
        """The resolve pipeline's window accounting (in-flight depth,
        queue occupancy, submit-vs-drain latency bands) — every backend
        has it, so a stalled pipeline is visible in status without a
        bench run."""
        return self.conflict_set.pipeline_stats()

    def failover_stats(self) -> dict:
        """Backend fault-tolerance accounting (checkpoints, device
        faults/recoveries, failovers, replay, shadow validation) —
        populated only when the backend runs under the failover
        controller; {} for bare host backends."""
        fn = getattr(self.conflict_set, "failover_stats", None)
        return fn() if fn is not None else {}

    def qos_sample(self, now: float) -> "QosSample":
        """Saturation-signal snapshot: the resolve pipeline's window
        accounting as smoothed QoS signals — occupancy (mean in-flight
        over depth), in-flight now, the forced-drain rate (submits that
        hit the depth backpressure — the 'device is not draining fast
        enough' signal), batch/txn rates, and the history row count."""
        from .types import QosSample
        pipe = self.pipeline_stats()
        snap = self.stats.snapshot()
        return QosSample("resolver", self.process.name, now, {
            "pipeline_occupancy": pipe.get("occupancy") or 0.0,
            "pipeline_in_flight": pipe.get("in_flight", 0),
            "pipeline_depth": pipe.get("depth", 1),
            "forced_drain_rate": round(self._qos_forced_rate.sample_total(
                pipe.get("forced_drains", 0), now), 2),
            "batch_rate": round(self._qos_batch_rate.sample_total(
                snap.get("batches_resolved", 0), now), 2),
            "txn_rate": round(self._qos_txn_rate.sample_total(
                snap.get("transactions_resolved", 0), now), 2),
            "state_rows": self.state_size(),
        })

    def state_size(self) -> int:
        """Conflict-history row estimate across backends (boundary rows
        for interval backends; a bisect-list length for the Python
        baseline)."""
        cs = self.conflict_set
        ic = getattr(cs, "interval_count", None)
        if ic is not None:
            # a method on the native backend, a property on the device
            # backends (incl. tpu-point) — support both
            return int(ic() if callable(ic) else ic)
        return len(getattr(cs, "_keys", ()))

    def _check_state_pressure(self, version: int) -> None:
        """(ref: the resolver memory back-pressure, Resolver.actor.cpp
        :91-98 — state beyond RESOLVER_STATE_MEMORY_LIMIT is a red
        flag: the window GC is not keeping up with the write rate.
        Interpreted here as a row count; surfaced via trace + counter
        so ratekeeper/status consumers can see it.)"""
        size = self.state_size()
        self.stats.counter("state_rows").set(size)
        limit = flow.SERVER_KNOBS.resolver_state_memory_limit
        if size > limit and not self._pressure_traced:
            self._pressure_traced = True
            flow.TraceEvent("ResolverStatePressure", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Rows=size, Limit=limit, Version=version).log()
        elif size <= limit:
            self._pressure_traced = False
