"""Proxy role: GRV path + the pipelined commit batcher.

Reference: fdbserver/MasterProxyServer.actor.cpp —
  - commitBatcher (:344): group commit requests by time window / count;
  - commitBatch (:410), five phases kept as distinct awaits here:
      1 order via latestLocalCommitBatchResolving + master.getVersion
      2 resolver.resolve (key-range split when sharded — the TPU
        sharded backend does that split on-device instead)
      3 verdict combine + mutation assembly
      4 log push, sequenced via latestLocalCommitBatchLogging
      5 per-txn replies: committed / not_committed / too_old
  - transactionStarter / getLiveCommittedVersion (:1102/:1019): GRV
    returns the proxy's committed version (single-proxy slice of the
    all-proxies confirmation).
Batches overlap: while one batch waits on the log fsync, the next can
already be resolving — the NotifiedVersion pair is the software
pipeline's interlock, exactly the reference's structure.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import List

from .. import flow
from ..flow import SERVER_KNOBS, NotifiedVersion, TaskPriority, error
from ..models import COMMITTED, CONFLICT, TOO_OLD
from ..rpc import NetworkRef, RequestStream, SimProcess
from .admission import GrvAdmissionQueues
from .chaos import fire_station
from .critical_path import ProxyPathRecorder
from .repair import RepairManager
from .scheduler import AdmissionScheduler
from .types import (ATOMIC_OPS, CLEAR_RANGE, INERT_OPS, PRIORITY_BATCH,
                    PRIORITY_DEFAULT, PRIORITY_IMMEDIATE, SET_VALUE,
                    SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE,
                    CommitConflictReply, CommitReply, CommitRequest,
                    GetReadVersionReply, MetadataMutations, MutationRef,
                    DURABLE_FRONTIER_REQUEST, GET_RATE_REQUEST,
                    RAW_COMMITTED_REQUEST, ResolveReply, ResolveRequest,
                    TLogCommitRequest, TaggedMutation, mutation_bytes)

from .systemkeys import is_management_mutation as _is_management_mutation

# the mutation types a transaction may carry (ref: the commit path
# asserting isValidMutationType — AvailableForReuse and the
# LogProtocolMessage escape are never legal in a transaction)
LEGAL_MUTATIONS = (frozenset({SET_VALUE, CLEAR_RANGE,
                              SET_VERSIONSTAMPED_KEY,
                              SET_VERSIONSTAMPED_VALUE})
                   | ATOMIC_OPS | INERT_OPS)


def make_versionstamp(version: int, batch_index: int) -> bytes:
    """10-byte versionstamp: 8B big-endian commit version + 2B big-endian
    batch index (ref: Versionstamp encoding, CommitTransaction.h /
    design/tuple.md)."""
    return version.to_bytes(8, "big") + batch_index.to_bytes(2, "big")


def _apply_versionstamp(m: MutationRef, stamp: bytes) -> MutationRef:
    """Rewrite a versionstamped mutation into a plain set (ref:
    MasterProxyServer commitBatch applying transformations before
    logging). The operand's trailing 4 bytes are the little-endian
    offset of the 10-byte placeholder."""
    if m.type == SET_VERSIONSTAMPED_KEY:
        off = int.from_bytes(m.param1[-4:], "little")
        key = m.param1[:-4]
        return MutationRef(SET_VALUE, key[:off] + stamp + key[off + 10:],
                           m.param2)
    off = int.from_bytes(m.param2[-4:], "little")
    val = m.param2[:-4]
    return MutationRef(SET_VALUE, m.param1,
                       val[:off] + stamp + val[off + 10:])


MWTLV = 5_000_000  # fallback window (ref: MAX_WRITE_TRANSACTION_LIFE_VERSIONS)

# every mutation is ALSO routed here while a continuous backup is
# active (ref: the backup mutation-log tags — a single stream preserves
# exact intra-version mutation order for point-in-time restore)
BACKUP_TAG = 0xFFFF
# ...and here while a remote region is attached (ref: the log-router
# tags of a fearless configuration; see server/region.py)
REGION_TAG = 0xFFFE


class KeyResolverMap:
    """keyResolvers: key ranges -> resolver owner HISTORY (newest
    first). After a move, ranges keep routing to the former owner too
    until a full MVCC window has passed — both resolvers then hold
    complete write history for the range, so no conflict can be missed
    across the transition (ref: the keyResolvers
    KeyRangeMap<vector<pair<Version,int>>> in
    MasterProxyServer.actor.cpp:204 and its double-delivery window)."""

    def __init__(self, splits, n_resolvers: int, window: int = None):
        self.bounds = [b""] + list(splits)   # range i = [bounds[i], next)
        self.owners = [[(0, i)] for i in range(n_resolvers)]
        # retention window must track the resolvers' knob-configured
        # MVCC window or a move could drop a former owner while stale
        # snapshots are still resolvable (code review r3)
        self.window = (window if window is not None
                       else SERVER_KNOBS.max_write_transaction_life_versions)

    def _split_at(self, key: bytes) -> int:
        i = bisect_right(self.bounds, key) - 1
        if self.bounds[i] == key:
            return i
        self.bounds.insert(i + 1, key)
        self.owners.insert(i + 1, list(self.owners[i]))
        return i + 1

    def move(self, begin: bytes, end, to_idx: int, at_version: int) -> None:
        """Reassign [begin, end) to `to_idx` from `at_version` on; the
        former owners stay live for one MVCC window."""
        i = self._split_at(begin)
        j = self._split_at(end) if end is not None else len(self.bounds)
        for k in range(i, j):
            if self.owners[k][0][1] != to_idx:
                self.owners[k] = [(at_version, to_idx)] + self.owners[k]

    def expire(self, oldest_version: int) -> None:
        """Drop former owners whose move predates the MVCC window floor
        (the resolver GC watermark: any still-resolvable snapshot is
        >= oldest, so a range whose move landed before it has complete
        write history at the NEW owner). The canonical trim — `prune`
        derives its commit-version form from this — and explicitly
        invokable outside the commit path, so a long-idle map does not
        retain owner history forever (ISSUE 15 satellite: the GRV serve
        path calls this with the confirmed committed version's
        watermark)."""
        for ow in self.owners:
            while len(ow) > 1 and ow[-2][0] < oldest_version:
                ow.pop()

    def prune(self, commit_version: int) -> None:
        """Drop former owners once one full MVCC window has passed the
        move. No skew slack is needed: moves are versioned through the
        commit stream (Master.register_move), so every proxy applies a
        move at the same effective version."""
        self.expire(commit_version - self.window)

    def release(self, begin: bytes, end, idx: int) -> None:
        """Retire `idx` as a FORMER owner of [begin, end) ahead of the
        window — the live-handoff fast path (ISSUE 15): once the
        donor's clipped state is installed on the new owner, the master
        registers a release through the version chain and double
        delivery stops immediately instead of after a full MVCC window.
        The CURRENT owner is never dropped (a release racing a newer
        move must not orphan the range)."""
        i = self._split_at(begin)
        j = self._split_at(end) if end is not None else len(self.bounds)
        for k in range(i, j):
            ow = self.owners[k]
            if len(ow) > 1:
                kept = [ow[0]] + [t for t in ow[1:] if t[1] != idx]
                if len(kept) != len(ow):
                    self.owners[k] = kept

    def apply(self, entry) -> None:
        """Apply one version-stamped balance entry off the master's
        move log: 4-tuples are moves (the original vocabulary),
        5-tuples carry an op — "move" or "release"."""
        eff, mb, me, idx = entry[:4]
        if len(entry) > 4 and entry[4] == "release":
            self.release(mb, me, idx)
        else:
            self.move(mb, me, idx, eff)

    def live_owners(self, k: int):
        return [idx for _v, idx in self.owners[k]]

    def owner_of(self, key: bytes) -> int:
        """CURRENT owner of `key` (newest history entry)."""
        k = max(0, bisect_right(self.bounds, key) - 1)
        return self.owners[k][0][1]

    def owned_buckets(self, idx: int) -> list:
        """First-byte buckets whose bucket-start key `idx` currently
        owns — the balance loop's pick set (its moves are whole
        buckets, so bucket starts are ownership-representative)."""
        return [b for b in range(256)
                if self.owner_of(bytes([b])) == idx]

    def owned_ranges(self, n_resolvers: int) -> list:
        """Per-resolver count of ranges currently OWNED (newest entry)
        — the skew surface status/exporter/cli show before and after
        the balancer acts."""
        out = [0] * n_resolvers
        for ow in self.owners:
            if 0 <= ow[0][1] < n_resolvers:
                out[ow[0][1]] += 1
        return out

    def clip_per_resolver(self, txn_ranges, n_resolvers: int):
        """For each resolver, the pieces of `txn_ranges` it must see
        (current + windowed former owners). Bisects to the overlapped
        span — the map can grow toward 257 entries as balancing splits
        buckets, and this sits on the hot commit path."""
        out = [[] for _ in range(n_resolvers)]
        nb = len(self.bounds)
        for b, e in txn_ranges:
            k = max(0, bisect_right(self.bounds, b) - 1)
            while k < nb and self.bounds[k] < e:
                lo = self.bounds[k]
                hi = self.bounds[k + 1] if k + 1 < nb else None
                b2 = max(b, lo)
                e2 = e if hi is None else min(e, hi)
                if b2 < e2:
                    for idx in self.live_owners(k):
                        out[idx].append((b2, e2))
                k += 1
        return out


PRIORITY_NAMES = {PRIORITY_BATCH: "batch", PRIORITY_DEFAULT: "default",
                  PRIORITY_IMMEDIATE: "immediate"}


class TransactionTagCounter:
    """Bounded decaying table of per-tag transaction traffic (ref:
    fdbserver/TransactionTagCounter — the busiest-tag tracking behind
    tag throttling; same decay/eviction shape as ConflictHotSpots).

    Each client-supplied tag accumulates a busyness score that halves
    every QOS_TAG_HALF_LIFE seconds, plus raw started / committed /
    conflicted totals. Bounded at QOS_TAG_MAX_ENTRIES (lowest decayed
    score evicted); `top(k)` is the status/CLI/exporter surface, and
    the throttling PR that follows (ROADMAP item 3) reads the same
    rows to pick which tags to push back on."""

    __slots__ = ("half_life", "max_entries", "_entries")

    def __init__(self, half_life: float = None, max_entries: int = None):
        self.half_life = (half_life if half_life is not None
                          else SERVER_KNOBS.qos_tag_half_life)
        self.max_entries = (max_entries if max_entries is not None
                            else int(SERVER_KNOBS.qos_tag_max_entries))
        # tag -> [decayed score, started, committed, conflicted, last t]
        self._entries: dict = {}

    def _decayed(self, score: float, since: float, now: float) -> float:
        if now <= since or self.half_life <= 0:
            return score
        return score * 0.5 ** ((now - since) / self.half_life)

    def record(self, tag: bytes, outcome: str, now: float,
               weight: float = 1.0) -> None:
        ent = self._entries.get(tag)
        if ent is None:
            ent = self._entries[tag] = [0.0, 0, 0, 0, now]
        ent[0] = self._decayed(ent[0], ent[4], now) + weight
        ent[4] = now
        if outcome == "started":
            ent[1] += 1
        elif outcome == "committed":
            ent[2] += 1
        elif outcome == "conflicted":
            ent[3] += 1
        if len(self._entries) > self.max_entries:
            worst = min(self._entries,
                        key=lambda k: self._decayed(
                            self._entries[k][0], self._entries[k][4], now))
            del self._entries[worst]

    def top(self, k: int = None) -> list:
        """Status-ready rows, busiest first: decayed rate score plus
        the raw per-outcome totals per tag."""
        if k is None:
            k = int(SERVER_KNOBS.qos_tag_top_k)
        now = flow.now()
        rows = [(self._decayed(s, t, now), st, cm, cf, tag)
                for tag, (s, st, cm, cf, t) in self._entries.items()]
        rows.sort(key=lambda r: (-r[0], r[4]))
        return [{"tag": tag.hex(), "busyness": round(score, 4),
                 "started": st, "committed": cm, "conflicted": cf}
                for score, st, cm, cf, tag in rows[:k]]


class Proxy:
    def __init__(self, process: SimProcess, master_ref: NetworkRef,
                 resolver_refs, tlog_refs,
                 resolver_splits=(), storage_splits=(), storage_tags=None,
                 recovery_version: int = 0,
                 batch_window: float = 0.001, max_batch: int = 512,
                 ratekeeper_ref: NetworkRef = None,
                 management_ref: NetworkRef = None,
                 dbinfo=None):
        if not isinstance(resolver_refs, (list, tuple)):
            resolver_refs = [resolver_refs]
        if not isinstance(tlog_refs, (list, tuple)):
            tlog_refs = [tlog_refs]
        assert len(resolver_splits) == len(resolver_refs) - 1
        self.process = process
        self.master_ref = master_ref
        self.resolver_refs = list(resolver_refs)
        # keyResolvers: versioned range -> owner-history map (rebalanced
        # at runtime by the master's resolutionBalancing)
        self.key_resolvers = KeyResolverMap(resolver_splits,
                                            len(resolver_refs))
        # keyServers boundaries: range i = [sbounds[i], sbounds[i+1]),
        # owned by storage tag _stags[i]. Tags are EXPLICIT, not
        # positional: shard splits mint fresh tags mid-keyspace (ref:
        # the keyServers map carrying Tag values, fdbclient/SystemData)
        self._sbounds = [b""] + list(storage_splits) + [None]
        if storage_tags is None:
            raise ValueError(
                "storage_tags is required: tags are not positional once "
                "splits mint fresh tags mid-keyspace")
        self._stags = list(storage_tags)
        assert len(self._stags) == len(self._sbounds) - 1
        self._moving: list = []   # (begin, end, extra_tag) dual-tag ranges
        self.backup_active = False
        self.region_active = False
        self.tlog_refs = list(tlog_refs)
        batch_window = min(
            max(batch_window,
                SERVER_KNOBS.commit_transaction_batch_interval_min),
            SERVER_KNOBS.max_commit_batch_interval)
        max_batch = min(max_batch,
                        SERVER_KNOBS.commit_transaction_batch_count_max)
        if flow.buggify("proxy/small_batch_window"):
            # shrink the batcher to one-or-two txn batches: stresses the
            # pipeline interlocks and resolver ordering under load
            batch_window, max_batch = 1e-5, 2
        self.batch_window = batch_window
        self.max_batch = max_batch
        self.committed_version = NotifiedVersion(recovery_version)
        # the epoch's version floor: batches chaining from it are the
        # resolvers' first, when their GC watermark is still 0 (the
        # split path's proxy-side tooOld decision needs this)
        self._recovery_version = recovery_version
        # pipeline interlocks sequence THIS proxy's batches by local
        # batch number (ref: localBatchNumber + latestLocalCommitBatch*
        # NotifiedVersions, MasterProxyServer.actor.cpp:453,:517); the
        # global version chain is enforced downstream by the resolver
        # and TLog, so local numbering keeps multiple proxies from
        # deadlocking on each other's versions.
        self.batch_resolving = NotifiedVersion(0)
        self.batch_logging = NotifiedVersion(0)
        # wall-clock deadline pacer for SIM_COMMIT_COST_PER_TXN (the
        # proxy-side modeled service time, role-per-process bench):
        # next-free instant of this proxy as a serial commit server
        self._pace_free = 0.0
        self._local_batch = 0
        self._peers = []               # other proxies' raw-committed refs
        self._ratekeeper_ref = ratekeeper_ref
        # CC management stream: committed \xff/conf//\xff/excluded
        # mutations are forwarded there (applyMetadataMutation seam)
        self._management_ref = management_ref
        self._rate = 1e9               # tps budget (ratekeeper-fed)
        self._batch_rate = 1e9         # batch-priority budget (<= _rate)
        self._grv_queue = []           # waiting GRV replies
        self._grv_queue_dirty = False  # new arrivals since last sort
        self._grv_inflight = []        # batch being confirmed right now
        self._admission_inflight = []  # ...and the admission loop's own
        self._suspect_peers = {}       # id(ref) -> suspect-until time
        # (ref: ProxyStats — txn admission/commit counters for status)
        self.stats = flow.CounterCollection("proxy")
        # batches between batch_resolving release and verdict arrival:
        # >1 means the resolver-side pipeline actually overlaps this
        # proxy's batches end to end (the whole point of the split
        # submit/drain resolve path)
        self._resolving_now = 0
        self._resolving_peak = 0
        # banded request latencies + recent-latency reservoirs (ref:
        # LatencyBandConfig applied to GRV and commit in status, plus
        # the LatencySample percentile surface)
        self.grv_bands = flow.RequestLatency("grv")
        self.commit_bands = flow.RequestLatency("commit")
        # commit critical-path decomposition (ISSUE 18): per-station
        # latency split for EVERY batch while CRITICAL_PATH is armed;
        # off, the commit path pays one knob read per batch
        self.path = ProxyPathRecorder()
        # per-tag / per-priority traffic accounting (ref:
        # TransactionTagCounter + the per-class started counters in
        # ProxyStats); gated by QOS_TAG_ACCOUNTING — off, the commit
        # path pays one knob read per batch and nothing else
        self.tag_counter = TransactionTagCounter()
        # QoS saturation signals (ref: GRV queue depth + batch
        # occupancy feeding the reference's GrvProxyMetrics). Pull
        # model: qos_sample() reads raw state at the collection cadence
        self._qos_grv_queue = flow.SmoothedQueue()
        self._qos_batch_rate = flow.SmoothedRate()
        self._qos_txn_rate = flow.SmoothedRate()
        self._qos_started_rate = flow.SmoothedRate()
        self.commits = RequestStream(process)
        self.grvs = RequestStream(process)
        self.raw_committed = RequestStream(process)
        # count of keyResolvers moves already applied; sent with every
        # version request so the master's reply carries only the tail
        self._moves_seen = 0
        self._actors = flow.ActorCollection()
        # conflict prediction & transaction repair (server/scheduler.py
        # + server/repair.py, ROADMAP item 2): the admission scheduler
        # defers predicted-conflict commits into per-hot-range queues
        # (released back through the commit stream), the repair manager
        # re-executes invalidated reads and resubmits instead of
        # aborting, and the CC-pushed hot rows double as the GRV
        # conflict-window piggyback. All knob-gated off by default.
        self.scheduler = AdmissionScheduler(process, self.stats,
                                            self._sched_release)
        self.repair = RepairManager(process, dbinfo, self.commits,
                                    self.stats, self._actors,
                                    committed_version=self.committed_version,
                                    account=self._repair_fallback_account)
        self._conflict_windows: tuple = ()
        # enforced admission control (server/admission.py, ROADMAP item
        # 3): per-priority GRV token buckets fed by the ratekeeper's
        # per-proxy budget share, per-tag throttle buckets fed by the
        # \xff\x02/throttledTags/ poll, bounded queues with retryable
        # rejection. Knob-gated off: with GRV_ADMISSION_CONTROL and
        # TAG_THROTTLING both 0 no request ever routes through it.
        self._dbinfo = dbinfo
        self.admission = GrvAdmissionQueues(process, self.stats)
        # timer-band diet (ISSUE 12): the GRV-side periodic loops —
        # batcher, admission ticker, rate poll, tag-throttle poll —
        # used to poll fixed intervals through empty queues, making
        # proxy_grv_timer the sim's top run-loop band. They now park on
        # these signals while idle: `_grv_wake` is touched by every GRV
        # arrival, `_admission_wake` by every admission submission, so
        # an idle proxy costs ZERO timer events and the first arrival
        # restores the exact old cadence.
        self._grv_wake = flow.WakeSignal()
        self._admission_wake = flow.WakeSignal()

    def set_peers(self, raw_refs) -> None:
        """Raw-committed-version endpoints of the OTHER proxies (ref:
        getLiveCommittedVersion asking all proxies)."""
        self._peers = list(raw_refs)
        self._suspect_peers.clear()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._batcher(),
                                    TaskPriority.PROXY_COMMIT_BATCHER,
                                    name=f"{self.process.name}.batcher"))
        self._actors.add(flow.spawn(self._grv_loop(),
                                    TaskPriority.PROXY_GET_CONSISTENT_READ_VERSION,
                                    name=f"{self.process.name}.grv"))
        self._actors.add(flow.spawn(self._grv_batcher(),
                                    TaskPriority.PROXY_GRV_TIMER,
                                    name=f"{self.process.name}.grvBatcher"))
        self._actors.add(flow.spawn(self._admission_loop(),
                                    TaskPriority.PROXY_GRV_TIMER,
                                    name=f"{self.process.name}.admission"))
        self._actors.add(flow.spawn(self._tag_throttle_loop(),
                                    TaskPriority.PROXY_GRV_TIMER,
                                    name=f"{self.process.name}.tagThrottle"))
        self._actors.add(flow.spawn(self._raw_committed_loop(),
                                    TaskPriority.PROXY_GET_RAW_COMMITTED_VERSION,
                                    name=f"{self.process.name}.rawCommitted"))
        if self._ratekeeper_ref is not None:
            self._actors.add(flow.spawn(self._rate_loop(),
                                        TaskPriority.PROXY_GRV_TIMER,
                                        name=f"{self.process.name}.rate"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        """Epoch over: stop serving and break queued/future requests so
        stale clients fail over instead of hanging (ref: the proxy's
        actors dying with the master's lifetime)."""
        self._actors.cancel_all()
        self.commits.close()
        self.grvs.close()
        self.raw_committed.close()
        # a stop mid-confirmation must fail the popped batch too, or
        # those clients wait out the full request timeout (code review)
        for entry in (self._grv_queue + self._grv_inflight
                      + self._admission_inflight):
            try:
                entry[0].send_error(error("broken_promise"))
            except Exception:
                pass  # already answered
        self._grv_queue = []
        self._grv_inflight = []
        self._admission_inflight = []
        # deferred commits held by the admission scheduler fail over
        # the same way (repair actors ride self._actors and answer
        # their replies from their cancellation path), and so do GRVs
        # queued in the enforced-admission plane
        self.scheduler.shutdown()
        self.admission.shutdown()

    # -- GRV ------------------------------------------------------------
    async def _grv_loop(self):
        """Queue GRV requests for the batcher (ref: transactionStarter
        :1102 — requests are batched on a timer and released at the
        ratekeeper's rate). Client-batched requests carry how many
        transactions they admit. With the enforced-admission plane
        armed (GRV_ADMISSION_CONTROL / TAG_THROTTLING), requests route
        through server/admission.py's bounded per-priority/per-tag
        queues instead of the legacy unbounded list."""
        while True:
            req, reply = await self.grvs.pop()
            count = getattr(req, "transaction_count", None) or 1
            prio = getattr(req, "priority", PRIORITY_DEFAULT)
            tags = tuple(getattr(req, "tags", ()) or ())
            self.stats.counter("grv_wire_requests").add(1)
            entry = (reply, count, prio, flow.now(), tags)
            k = SERVER_KNOBS
            if k.grv_admission_control or k.tag_throttling:
                self.admission.submit(entry, flow.now())
                self._admission_wake.touch()
            else:
                self._grv_queue.append(entry)
                self._grv_queue_dirty = True
            self._grv_wake.touch()   # unpark the idle GRV-side loops

    async def _grv_batcher(self):
        """Release queued GRVs in rate-gated batches; one causal
        confirmation round-trip serves the whole batch (ref:
        GRV batching in transactionStarter + getLiveCommittedVersion)."""
        interval = SERVER_KNOBS.grv_batch_interval
        tokens = 0.0
        btokens = 0.0     # batch-priority bucket (always <= the default)
        last = flow.now()
        wake = self._grv_wake
        while True:
            if not self._grv_queue:
                # timer diet: nothing queued — park until the next GRV
                # arrival instead of burning a timer event per interval
                # on an empty queue (token math is unaffected: refill
                # below is elapsed-time-based and burst-capped, exactly
                # what idle ticking converged to)
                await wake.wait_beyond(wake.count)
            await flow.delay(interval, TaskPriority.PROXY_GRV_TIMER)
            now = flow.now()
            # token buckets with a bounded burst allowance; a ZERO
            # rate is a full stop (emergency throttle), not a trickle
            if self._rate <= 0:
                tokens = 0.0
            else:
                tokens = min(
                    tokens + self._rate * (now - last),
                    max(1.0, self._rate
                        * SERVER_KNOBS.grv_burst_intervals * interval))
            if self._batch_rate <= 0:
                btokens = 0.0
            else:
                btokens = min(
                    btokens + self._batch_rate * (now - last),
                    max(1.0, self._batch_rate
                        * SERVER_KNOBS.grv_burst_intervals * interval))
            last = now
            if not self._grv_queue:
                continue
            # priority classes (ref: TransactionPriority): IMMEDIATE
            # bypasses the gate and pays no tokens; DEFAULT pays the
            # default bucket; BATCH sorts last and must afford BOTH
            # buckets, so batch traffic throttles first (ref: the
            # separate batchTransactions limit in GetRateInfoReply).
            # Sorted ONLY when arrivals were appended since the last
            # pass: the post-slice tail is already ordered, and under
            # a throttled backlog the former every-tick sort was
            # O(n log n) per 0.5ms on a queue that hadn't changed
            if self._grv_queue_dirty:
                self._grv_queue.sort(key=lambda e: -e[2])
                self._grv_queue_dirty = False
            take = 0
            charged = 0
            bcharged = 0
            while take < len(self._grv_queue):
                _r, cnt, prio, _t, _tags = self._grv_queue[take]
                if prio < PRIORITY_IMMEDIATE:
                    if charged + cnt > tokens:
                        break
                    if prio <= PRIORITY_BATCH:
                        if bcharged + cnt > btokens:
                            break
                        bcharged += cnt
                    charged += cnt
                take += 1
            if take == 0:
                if tokens < 1:
                    continue
                first = self._grv_queue[0]
                if first[2] <= PRIORITY_BATCH and btokens < 1:
                    continue   # batch head throttled; wait for budget
                # a batch bigger than the burst cap still admits by
                # running the bucket into debt, or it would starve
                charged = first[1]
                bcharged = first[1] if first[2] <= PRIORITY_BATCH else 0
                take = 1
            tokens -= charged
            btokens -= bcharged
            self._grv_inflight, self._grv_queue = (self._grv_queue[:take],
                                                   self._grv_queue[take:])
            try:
                await self._serve_grv_batch(self._grv_inflight)
            finally:
                self._grv_inflight = []

    async def _admission_loop(self):
        """The enforced-admission release ticker (ref: the
        transactionStarter loop of GrvProxyServer): one tick per
        GRV_BATCH_INTERVAL window refills the class buckets from this
        proxy's budget SHARE, releases tag-parked requests at their
        commanded pace, sheds wait-bound violations, and serves the
        whole admitted batch with ONE causal-confirmation round trip —
        the GRV batching coalesce (`grv_confirm_rounds` vs
        `transactions_started` is the measured request-rate drop).
        Costs one knob read per tick while the plane is off."""
        interval = SERVER_KNOBS.grv_batch_interval
        wake = self._admission_wake
        while True:
            if not self.admission.depth():
                # park until something is submitted: with the plane off
                # this loop costs nothing at all, and with it armed an
                # idle window (queues drained, no tag-parked requests)
                # skips straight to the next submission — bucket refill
                # is lazy/elapsed-time-based and row expiry is enforced
                # by the table on read, so skipped ticks change nothing
                await wake.wait_beyond(wake.count)
            await flow.delay(interval, TaskPriority.PROXY_GRV_TIMER)
            k = SERVER_KNOBS
            if not (k.grv_admission_control or k.tag_throttling) and \
                    not self.admission.depth():
                continue
            batch = self.admission.tick(flow.now(), self._rate,
                                        self._batch_rate, interval)
            if not batch:
                continue
            # a separate in-flight list: during a knob flip both
            # serving loops can be mid-confirmation at once, and
            # sharing the legacy list would let one finally clear the
            # other's entries out of the stop() drain set
            self._admission_inflight = batch
            try:
                await self._serve_grv_batch(batch)
            finally:
                self._admission_inflight = []

    async def _tag_throttle_loop(self):
        """Watch \\xff\\x02/throttledTags/ and install the rows into
        the admission plane's enforcement table (ref: the GRV proxies
        monitoring the tag-throttle keyspace). A failed read (storage
        mid-recovery) keeps the last installed rows and retries next
        poll; row expiry is enforced by the table itself, so a stale
        poll can never extend a throttle."""
        from .tag_throttler import read_throttle_rows
        wake = self._grv_wake
        seen = -1
        while True:
            if seen == wake.count and not self._grv_queue and \
                    not self.admission.depth():
                # no GRV traffic since the last poll: throttle rows
                # have nobody to apply to — park until a client shows
                # up (row expiry is enforced by the table on read, so
                # a stale poll can never extend a throttle)
                await wake.wait_beyond(wake.count)
            seen = wake.count
            interval = float(SERVER_KNOBS.tag_throttle_poll_interval)
            await flow.delay(interval if interval > 0 else 1.0,
                             TaskPriority.PROXY_GRV_TIMER)
            if not SERVER_KNOBS.tag_throttling:
                continue
            info = self._dbinfo.get() if self._dbinfo is not None else None
            try:
                rows = await flow.timeout_error(
                    flow.spawn(read_throttle_rows(
                        info, self.process, self.committed_version.get()),
                        TaskPriority.PROXY_GRV_TIMER), 1.0)
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                continue
            now = flow.now()
            for entry in self.admission.tags.install(rows, now):
                # a vanished row (manual `throttle off`) frees its
                # parked requests into the ordinary class queues
                self.admission.submit(entry, now)
                self._admission_wake.touch()
            self.stats.counter("throttle_rows").set(
                len(self.admission.tags.rows))

    async def _serve_grv_batch(self, batch):
        """Causally-correct GRV with multiple proxies: the read version
        is the max committed version across ALL of them, so a client
        never reads below its own acknowledged commit through a
        different proxy (ref: getLiveCommittedVersion,
        MasterProxyServer.actor.cpp:1019 — asks all other proxies).

        A dead peer must NOT error the batch: the reference degrades by
        recruitment, not by failing clients. When a peer times out we
        mark it suspect (skipped for GRV_PEER_SUSPECT_DURATION) and fall
        back to the TLogs' durable frontier: a proxy only acks a commit
        once ALL logs hold it durably, so min(frontier) across logs is
        >= every acknowledged commit from every proxy — and, unlike the
        master's last-assigned version, it is a version the storage
        servers can actually reach (an assigned-but-never-pushed version
        would leave readers blocked for the rest of the epoch). Clients
        pay one frontier round-trip during the window until recovery
        rotates the peer set, instead of seeing errors."""
        try:
            # one confirmation round serves the whole batch: the GRV
            # coalescing factor is transactions_started / these rounds
            self.stats.counter("grv_confirm_rounds").add(1)
            version = self.committed_version.get()
            if self._peers:
                now = flow.now()
                live = [p for p in self._peers
                        if self._suspect_peers.get(id(p), 0.0) <= now]
                degraded = len(live) < len(self._peers)
                futs = [flow.timeout_error(
                    p.get_reply(RAW_COMMITTED_REQUEST, self.process),
                    SERVER_KNOBS.grv_confirm_timeout)
                        for p in live]
                for p, f in zip(live, futs):
                    try:
                        version = max(version, await f)
                    except flow.FdbError as e:
                        if e.name == "operation_cancelled":
                            raise
                        degraded = True
                        self._suspect_peers[id(p)] = (
                            flow.now()
                            + SERVER_KNOBS.grv_peer_suspect_duration)
                if degraded:
                    self.stats.counter("grv_degraded").add(1)
                    # individual probe failures are tolerated like
                    # suspect peers (ADVICE r5: one timed-out frontier
                    # — or an empty tlog_refs mid-recovery — used to
                    # fail the whole GRV batch the fallback exists to
                    # save). min() over the ANSWERED frontiers is still
                    # safe: a commit is acked only once ALL logs hold
                    # it durably, so every log's frontier bounds every
                    # acknowledged commit from below. At least one
                    # answer is required — with none, causality cannot
                    # be proven and clients must retry.
                    futs = [flow.timeout_error(
                        ref.get_reply(DURABLE_FRONTIER_REQUEST,
                                      self.process),
                        SERVER_KNOBS.grv_confirm_timeout)
                        for ref in self.tlog_refs]
                    frontiers = []
                    for f in futs:
                        try:
                            frontiers.append(await f)
                        except flow.FdbError as fe:
                            if fe.name == "operation_cancelled":
                                raise
                            flow.cover("proxy.grv.frontier_probe_failed")
                    if not frontiers:
                        flow.cover("proxy.grv.no_frontier")
                        raise error("broken_promise")
                    version = max(version, min(frontiers))
            self.stats.counter("transactions_started").add(
                sum(e[1] for e in batch))
            if SERVER_KNOBS.qos_tag_accounting:
                # per-priority admission accounting (ref: the per-class
                # txn counters in ProxyStats feeding GetRateInfo)
                for _r, cnt, prio, _t, _tags in batch:
                    self.stats.counter(
                        "transactions_started_"
                        + PRIORITY_NAMES.get(prio, "default")).add(cnt)
            now = flow.now()
            # keyResolvers retention (ISSUE 15 satellite): trim former
            # owners from the GC watermark here too, so a long-idle
            # commit path (moves applied, then traffic stopped) does
            # not retain owner history until the NEXT commit batch —
            # O(owned ranges), and a no-op on the single-resolver map
            self.key_resolvers.expire(version - self.key_resolvers.window)
            # chaos station: "GRV handed out" — the kill-mid-commit
            # scenarios arm role deaths here (server/chaos.py)
            fire_station("MasterProxyServer.GRV.AfterReply")
            # hot-key conflict windows ride the GRV reply into the
            # client-side early-abort cache (server/scheduler.py);
            # empty and free while CLIENT_CONFLICT_WINDOWS is off
            windows = (self._conflict_windows
                       if SERVER_KNOBS.client_conflict_windows else ())
            # tag-throttle info rides the reply per entry so throttled
            # clients back off locally (server/tag_throttler.py);
            # empty and free while TAG_THROTTLING is off
            throttling = bool(SERVER_KNOBS.tag_throttling)
            for entry in batch:
                self.grv_bands.record(now - entry[3])
                throttles = (self.admission.reply_throttles(entry[4], now)
                             if throttling and entry[4] else ())
                entry[0].send(GetReadVersionReply(version, windows,
                                                  throttles))
        except flow.FdbError as e:
            cancelled = e.name == "operation_cancelled"
            if cancelled:
                # cancelled mid-confirmation (the epoch ended): stale
                # clients must see a retryable failure and refresh —
                # never the server's own cancellation
                e = error("broken_promise")
            for entry in batch:
                try:
                    entry[0].send_error(e)
                except Exception:
                    pass  # already answered
            if cancelled:
                raise flow.ActorCancelled()
        except BaseException:
            for entry in batch:
                try:
                    entry[0].send_error(error("broken_promise"))
                except Exception:
                    pass
            raise

    async def _rate_loop(self):
        """(ref: proxies polling GetRateInfo from the ratekeeper).

        Event-driven (ISSUE 12): the budget only matters while GRV
        traffic flows, so an idle proxy parks instead of polling the
        ratekeeper every interval forever — the first arrival after an
        idle period triggers an immediate poll (fresher than the old
        fixed grid), and sustained traffic restores the old cadence.

        Known, accepted staleness window: the wake-up poll costs one
        network round trip while the batcher only waits one
        GRV_BATCH_INTERVAL, so the FIRST post-idle batch may be
        admitted against the pre-idle rate (the always-polling loop
        bounded staleness at one poll interval instead). One
        burst-capped batch per idle period is the worst case; the
        ratekeeper's next reply corrects the very next window, and
        armed-admission storms never park (traffic keeps the loop
        hot), so the enforcement measurements are unaffected."""
        wake = self._grv_wake
        seen = -1
        while True:
            if seen == wake.count and not self._grv_queue and \
                    not self.admission.depth():
                await wake.wait_beyond(wake.count)
            seen = wake.count
            try:
                r = await flow.timeout_error(
                    self._ratekeeper_ref.get_reply(GET_RATE_REQUEST,
                                                   self.process),
                    SERVER_KNOBS.ratekeeper_poll_timeout)
                self._rate = r.tps
                bt = getattr(r, "batch_tps", -1.0)
                self._batch_rate = r.tps if bt < 0 else min(bt, r.tps)
            except flow.FdbError:
                pass  # keep the last known rate
            await flow.delay(SERVER_KNOBS.grv_rate_poll_interval,
                             TaskPriority.PROXY_GRV_TIMER)

    async def _raw_committed_loop(self):
        while True:
            _req, reply = await self.raw_committed.pop()
            reply.send(self.committed_version.get())

    def _tags_for(self, m: MutationRef):
        """Destination storage tags for a mutation (ref: LogPushData tag
        routing via the keyServers map). A point mutation goes to its
        shard's tag(s); a clear goes to every shard it overlaps. A range
        being moved is DUAL-TAGGED so both source and destination logs
        see its mutations throughout the transition (ref: keyServers
        holding both teams during moveKeys); an active backup adds the
        backup tag to everything."""
        n = len(self._sbounds) - 1
        if n == 1 and not self._moving and not self.region_active:
            return ((self._stags[0], BACKUP_TAG) if self.backup_active
                    else (self._stags[0],))
        if m.type == CLEAR_RANGE:
            tags = set()
            for i in range(n):
                lo, hi = self._sbounds[i], self._sbounds[i + 1]
                if (hi is None or m.param1 < hi) and lo < m.param2:
                    tags.add(self._stags[i])
            for mb, me, extra in self._moving:
                if (me is None or m.param1 < me) and mb < m.param2:
                    tags.add(extra)
            if self.backup_active:
                tags.add(BACKUP_TAG)
            if self.region_active:
                tags.add(REGION_TAG)
            return tuple(sorted(tags))
        tags = {self._shard_of(m.param1)}
        for mb, me, extra in self._moving:
            if mb <= m.param1 and (me is None or m.param1 < me):
                tags.add(extra)
        if self.backup_active:
            tags.add(BACKUP_TAG)
        if self.region_active:
            tags.add(REGION_TAG)
        return tuple(sorted(tags))

    def _shard_of(self, key: bytes) -> int:
        n = len(self._sbounds) - 1
        for i in range(n - 1, -1, -1):
            if key >= self._sbounds[i]:
                return self._stags[i]
        return self._stags[0]

    def start_move(self, begin: bytes, end, extra_tag: int) -> None:
        """Dual-tag [begin, end) with `extra_tag` while a shard move is
        in flight (ref: moveKeys startMoveKeys)."""
        self._moving.append((begin, end, extra_tag))

    def finish_move(self, begin: bytes, end, extra_tag: int,
                    new_splits, new_tags) -> None:
        """Adopt the new shard boundaries/tags and drop the dual tag
        (ref: finishMoveKeys). Tags are explicit — a positional
        fallback would silently misroute after a split."""
        self._moving = [mv for mv in self._moving
                        if mv != (begin, end, extra_tag)]
        self._sbounds = [b""] + list(new_splits) + [None]
        self._stags = list(new_tags)
        assert len(self._stags) == len(self._sbounds) - 1

    # -- commit pipeline ------------------------------------------------
    @staticmethod
    def _req_bytes(req) -> int:
        """Mutations AND conflict ranges: both ship to the resolver/log,
        so both count toward the batch's byte budget."""
        return (sum(mutation_bytes(m) for m in req.mutations)
                + sum(len(b) + len(e) + 16
                      for b, e in (tuple(req.read_conflict_ranges)
                                   + tuple(req.write_conflict_ranges))))

    def _sched_release(self, req, reply) -> None:
        """A deferred commit re-enters the commit stream locally (no
        wire hop): the batcher picks it up like any fresh arrival, and
        the scheduler's released-marker keeps it from re-deferring."""
        self.commits.stream.send((req, reply))

    async def _batcher(self):
        """(ref: commitBatcher :344 — batch by window / count / BYTES:
        a batch closes early once its mutation payload reaches
        COMMIT_TRANSACTION_BATCH_BYTES_MAX, bounding resolver/log
        request sizes). Arrivals first pass the admission scheduler:
        a commit whose predicted conflict probability crosses the
        threshold is captured into a per-hot-range queue instead of
        racing this batch (server/scheduler.py; no-op while
        CONFLICT_SCHEDULING is off)."""
        bytes_max = SERVER_KNOBS.commit_transaction_batch_bytes_max
        while True:
            req, reply = await self.commits.pop()
            if SERVER_KNOBS.critical_path:
                # queue-entry stamp, keyed by the reply promise (it
                # survives scheduler deferral; setdefault keeps the
                # FIRST arrival so deferral time counts as batcher wait)
                self.path.note_arrival(reply, flow.now())
                if getattr(req, "debug_id", None) is not None:
                    # bare add_event: no fire_station — the armed-only
                    # extra station must not interact with chaos kills
                    flow.g_trace_batch.add_event(
                        "CommitDebug", req.debug_id,
                        "MasterProxyServer.batcher.Arrived")
            if self.scheduler.consider(req, reply):
                continue
            batch: List = [(req, reply)]
            nbytes = self._req_bytes(req)
            deadline = flow.delay(self.batch_window,
                                  TaskPriority.PROXY_COMMIT_BATCHER)
            while len(batch) < self.max_batch and nbytes < bytes_max:
                nxt = self.commits.pop()
                got = await flow.first_of(nxt, deadline)
                if got[0] == 1:  # window expired
                    break
                r2, p2 = got[1]
                if SERVER_KNOBS.critical_path:
                    self.path.note_arrival(p2, flow.now())
                    if getattr(r2, "debug_id", None) is not None:
                        flow.g_trace_batch.add_event(
                            "CommitDebug", r2.debug_id,
                            "MasterProxyServer.batcher.Arrived")
                if self.scheduler.consider(r2, p2):
                    continue
                batch.append((r2, p2))
                nbytes += self._req_bytes(r2)
            deadline.cancel()
            self._local_batch += 1
            flow.spawn(self._commit_batch(batch, self._local_batch),
                       TaskPriority.PROXY_COMMIT)

    @staticmethod
    def _debug_ids(reqs):
        return tuple(r.debug_id for r in reqs
                     if getattr(r, "debug_id", None) is not None)

    @staticmethod
    def _mark(ids, location):
        flow.g_trace_batch.add_events(ids, "CommitDebug", location)
        # the commit-debug stations double as chaos kill points: the
        # kill-mid-commit scenarios arm one-shot role deaths at exact
        # pipeline stations (server/chaos.py; no-op while unarmed)
        fire_station(location)

    async def _charge_commit_cost(self, amount: float):
        """Charge modeled commit service time. Wall-clock schedulers use
        a deadline pacer (the proxy as a serial server whose next-free
        instant advances by `amount` per batch — sleeping to the
        deadline absorbs per-sleep OS overshoot); virtual schedulers
        charge a plain delay. Knob default 0 means this never runs in
        the pinned posture."""
        sched = flow.get_scheduler()
        if sched is not None and not sched.virtual:
            now = flow.now()
            self._pace_free = max(self._pace_free, now) + amount
            wait = self._pace_free - now
            if wait > 0:
                await flow.delay(wait, TaskPriority.PROXY_COMMIT)
            return
        await flow.delay(amount, TaskPriority.PROXY_COMMIT)

    async def _commit_batch(self, batch, local: int):
        t0 = flow.now()
        reqs = [r for r, _ in batch]
        replies = [p for _, p in batch]
        # critical-path decomposition (ISSUE 18): consecutive clock
        # reads at the phase boundaries below telescope to the batch's
        # end-to-end latency, so per-station segments sum to the
        # measured total by construction
        path_armed = bool(SERVER_KNOBS.critical_path)
        t_ver = t_res = t_push = t0
        dbg = self._debug_ids(reqs)
        self._mark(dbg, "MasterProxyServer.commitBatch.Before")
        # span per sampled txn: the proxy leg of the commit tree; the
        # resolver/tlog legs opened downstream auto-parent onto it
        # while it stays open (ref: Span commit tracing, flow/Tracing.h)
        spans = flow.g_trace_batch.begin_spans(
            dbg, "MasterProxyServer.commitBatch")
        try:
            # phase 1: version assignment, ordered with this proxy's
            # earlier batches by local batch number (the finally below
            # always advances the interlocks so a failed batch can never
            # wedge its successors)
            await self.batch_resolving.when_at_least(local - 1)
            # reject illegal mutation types BEFORE resolution: an
            # illegal txn must not register write-conflict ranges the
            # pipeline will never log (phantom aborts for others)
            illegal = set()
            for idx, req in enumerate(reqs):
                if any(m.type not in LEGAL_MUTATIONS
                       for m in req.mutations):
                    flow.cover("proxy.commit.illegal_mutation")
                    illegal.add(idx)
            if illegal:
                reqs = [r._replace(read_conflict_ranges=(),
                                   write_conflict_ranges=(), mutations=())
                        if i in illegal else r
                        for i, r in enumerate(reqs)]
            ver = await self.master_ref.get_reply(self._moves_seen,
                                                  self.process)
            # apply version-stamped keyResolvers moves BEFORE routing:
            # this batch's version is at/above every carried move's
            # effective version, and every other proxy applies the same
            # move before ITS first batch at/above that version — the
            # apply point is a property of the version chain, not of
            # per-proxy delivery timing (ref: keyResolvers riding the
            # commit stream, MasterProxyServer.actor.cpp:204)
            for entry in ver.moves:
                self.key_resolvers.apply(entry)
            self._moves_seen += len(ver.moves)
            if path_armed:
                t_ver = flow.now()
            self._mark(dbg,
                       "MasterProxyServer.commitBatch.GotCommitVersion")

            # phase 2: conflict resolution — single resolver fast path, or
            # key-range split across resolvers with min-combined verdicts
            # (ref: ResolutionRequestBuilder :265-341, combine :585-592).
            # The interlock releases once the requests are IN FLIGHT, so
            # successive batches resolve concurrently and the resolver
            # orders them by the global version chain (ref: commitBatch
            # sets latestLocalCommitBatchResolving before awaiting).
            if len(self.resolver_refs) == 1:
                vf = self.resolver_refs[0].get_reply(
                    ResolveRequest(ver.prev_version, ver.version,
                                   tuple(reqs), debug_ids=dbg),
                    self.process)
            else:
                vf = flow.spawn(self._resolve_split(ver, reqs),
                                TaskPriority.PROXY_COMMIT)
            self._advance(self.batch_resolving, local)
            self._note_resolving(+1)
            try:
                verdicts, conflict_ranges = self._norm_verdicts(
                    await vf, len(reqs))
            finally:
                self._note_resolving(-1)
            if path_armed:
                t_res = flow.now()
            self._mark(dbg,
                       "MasterProxyServer.commitBatch.AfterResolution")
            # modeled proxy commit-pipeline service time
            # (SIM_COMMIT_COST_PER_TXN, default 0 = off): the proxy-side
            # twin of the resolver's modeled cost, charged per
            # transaction after resolution — mutation assembly + push
            # are the proxy's own CPU in the role-per-process capacity
            # model min(R/resolve_cost, P/commit_cost)
            ccost = float(SERVER_KNOBS.sim_commit_cost_per_txn)
            if ccost > 0 and reqs:
                await self._charge_commit_cost(ccost * len(reqs))

            # phase 3: assemble mutations of committed transactions with
            # their destination storage tags, resolving versionstamped
            # operations with the commit version (ref: commitBatch phase 3
            # — tag assignment per mutation via keyServers)
            mutations = []
            for idx, (req, verdict) in enumerate(zip(reqs, verdicts)):
                if verdict != COMMITTED or idx in illegal:
                    continue
                stamp = None
                for m in req.mutations:
                    if m.type in (SET_VERSIONSTAMPED_KEY,
                                  SET_VERSIONSTAMPED_VALUE):
                        if stamp is None:
                            stamp = make_versionstamp(ver.version, idx)
                        m = _apply_versionstamp(m, stamp)
                    mutations.append(TaggedMutation(self._tags_for(m), m))

            # phase 4: log push to the whole log set, ordered (ref:
            # latestLocalCommitBatchLogging + TagPartitionedLogSystem push
            # :404 — a commit is acked only when EVERY log in the set has
            # made it durable, so any single survivor carries all acked
            # data at recovery). The interlock is released at PUSH time,
            # not at fsync ack — the TLog itself sequences commits via
            # queue_version — so successive batches' fsyncs overlap (ref:
            # commitBatch releases logging order before waiting, :910-937).
            await self.batch_logging.when_at_least(local - 1)
            creq = TLogCommitRequest(ver.prev_version, ver.version,
                                     tuple(mutations),
                                     self.committed_version.get(),
                                     debug_ids=dbg)
            log_done = flow.all_of([ref.get_reply(creq, self.process)
                                    for ref in self.tlog_refs])
            self._advance(self.batch_logging, local)
            await log_done
            if path_armed:
                t_push = flow.now()
            self._mark(dbg, "MasterProxyServer.commitBatch.AfterLogPush")
            if self.committed_version.get() < ver.version:
                self.committed_version.set(ver.version)
            # applyMetadataMutation analogue: committed management-key
            # mutations are forwarded to the CC, which reacts (config
            # change -> epoch recovery, exclusion updates). One-way and
            # AFTER the log push: the keys are durable before anyone
            # acts on them (ref: ApplyMetadataMutation.h — the proxy is
            # where system mutations gain meaning)
            if self._management_ref is not None:
                meta = tuple(tm.mutation for tm in mutations
                             if _is_management_mutation(tm.mutation))
                if meta:
                    self._management_ref.send(
                        MetadataMutations(ver.version, meta), self.process)

            # breach-drill injection (COMMIT_LATENCY_INJECTION, ISSUE
            # 17): a directed soak arms this to prove the burn-rate SLO
            # pages — 0 (the default) is one knob read, no delay, no
            # schedule change
            inj = SERVER_KNOBS.commit_latency_injection
            if inj:
                await flow.delay(inj)

            # phase 5: per-transaction replies
            st = self.stats
            st.counter("commit_batches").add(1)
            st.counter("commit_batch_txns").add(len(reqs))
            account = bool(SERVER_KNOBS.qos_tag_accounting)
            now_acct = flow.now() if account else 0.0
            elapsed = flow.now() - t0
            t_end = t0 + elapsed
            for idx, (verdict, reply) in enumerate(zip(verdicts, replies)):
                self.commit_bands.record(elapsed)
                if path_armed:
                    # per-txn decomposition: batcher wait is THIS txn's
                    # (from its arrival stamp), the downstream segments
                    # are the batch's shared phase boundaries
                    arr = self.path.take_arrival(reply, t0)
                    self.path.record(
                        {"proxy_batcher": t0 - arr,
                         "commit_version": t_ver - t0,
                         "resolve": t_res - t_ver,
                         "tlog_fsync": t_push - t_res,
                         "reply": t_end - t_push},
                        t_end - arr)
                # server-side repair first (server/repair.py): a
                # conflicted-but-repairable transaction is re-executed
                # at THIS batch's version and resubmitted instead of
                # aborting — its reply (and its tag/priority
                # accounting) settles with the resubmission's outcome
                # only FIRST-attempt conflicts are captured: a repair
                # RESUBMISSION that conflicts again reports back to
                # the repair actor that owns it (which holds the
                # range's serialization lock and loops) — capturing it
                # here would nest a second actor behind that same lock
                attempt = getattr(reqs[idx], "repair_attempt", 0)
                repairing = (verdict not in (COMMITTED, TOO_OLD)
                             and idx not in illegal
                             and attempt == 0
                             and self.repair.try_repair(
                                 reqs[idx], reply, ver.version,
                                 conflict_ranges[idx]))
                # a resubmission that conflicts with budget left will
                # be retried by its repair actor — account only the
                # TERMINAL outcome, or one client txn counts N times
                interim = (attempt > 0
                           and verdict not in (COMMITTED, TOO_OLD)
                           and attempt < int(
                               SERVER_KNOBS.repair_max_attempts))
                if account and not repairing and not interim:
                    self._account(reqs[idx], verdict, idx in illegal,
                                  now_acct)
                if repairing:
                    flow.cover("proxy.commit.repair_pending")
                elif idx in illegal:
                    reply.send_error(error("client_invalid_operation"))
                elif verdict == COMMITTED:
                    st.counter("transactions_committed").add(1)
                    reply.send(CommitReply(ver.version, idx))
                elif verdict == TOO_OLD:
                    flow.cover("proxy.commit.too_old")
                    st.counter("transactions_too_old").add(1)
                    reply.send_error(error("transaction_too_old"))
                else:
                    flow.cover("proxy.commit.conflict")
                    if not interim:
                        # interim repair rounds must not inflate the
                        # conflict rate: one client txn, one terminal
                        # outcome (same invariant as _account above)
                        st.counter("transactions_conflicted").add(1)
                    if getattr(reqs[idx], "report_conflicting_keys",
                               False):
                        # a reporting client gets the attributed key
                        # ranges as a VALUE reply and raises
                        # not_committed itself (errors carry no payload
                        # across the wire)
                        flow.cover("proxy.commit.report_conflicting")
                        reply.send(CommitConflictReply(
                            tuple(conflict_ranges[idx])))
                    else:
                        reply.send_error(error("not_committed"))
        except flow.FdbError as e:
            # a dead or locked downstream role means this proxy's epoch
            # is over; the batch may or may not have reached a log, so
            # clients get commit_unknown_result and retry through a
            # refreshed proxy (ref: the proxy dying with its epoch and
            # NativeAPI mapping broken connections to
            # commit_unknown_result)
            if e.name in ("tlog_stopped", "broken_promise",
                          "operation_cancelled"):
                # operation_cancelled = this proxy's actors were torn
                # down mid-batch (epoch over): same unknown outcome as
                # a broken downstream
                e = error("commit_unknown_result")
            for reply in replies:
                reply.send_error(e)
        finally:
            flow.g_trace_batch.finish_spans(spans)
            self._advance(self.batch_resolving, local)
            self._advance(self.batch_logging, local)
            if path_armed:
                # error paths skip phase 5: drop their arrival stamps
                # so the bounded map never carries dead replies
                for reply in replies:
                    self.path.take_arrival(reply, 0.0)

    @staticmethod
    def _advance(nv: NotifiedVersion, to: int) -> None:
        if nv.get() < to:
            nv.set(to)

    def _account(self, req, verdict: int, illegal: bool,
                 now: float) -> None:
        """Per-priority / per-tag outcome accounting (QOS_TAG_ACCOUNTING
        gated at the caller): priority classes ride plain counters (the
        metric sampler and trace-counters rollup pick them up for
        free); client tags go through the bounded decaying table."""
        prio = PRIORITY_NAMES.get(
            getattr(req, "priority", PRIORITY_DEFAULT), "default")
        if illegal:
            outcome = "illegal"
        elif verdict == COMMITTED:
            outcome = "committed"
        elif verdict == TOO_OLD:
            outcome = "too_old"
        else:
            outcome = "conflicted"
        if outcome in ("committed", "conflicted"):
            self.stats.counter(
                f"transactions_{outcome}_{prio}").add(1)
        for tag in getattr(req, "tags", ()) or ():
            self.tag_counter.record(tag, "started", now)
            if outcome in ("committed", "conflicted"):
                self.tag_counter.record(tag, outcome, now, weight=0.0)

    def qos_sample(self, now: float) -> "QosSample":
        """Saturation-signal snapshot (ref: the GRV queue depth /
        batch-occupancy surface of GrvProxyMetrics): smoothed GRV queue
        depth, commit-batch occupancy (mean txns per batch over the
        window — a full batcher means the proxy, not the clients, sets
        the pace), resolve in-flight, and admission/commit rates."""
        from .types import QosSample
        snap = self.stats.snapshot()
        batch_rate = self._qos_batch_rate.sample_total(
            snap.get("commit_batches", 0), now)
        txn_rate = self._qos_txn_rate.sample_total(
            snap.get("commit_batch_txns", 0), now)
        return QosSample("proxy", self.process.name, now, {
            "grv_queue_depth": round(self._qos_grv_queue.sample(
                len(self._grv_queue) + self.admission.depth(), now), 2),
            "commit_batch_occupancy": round(
                txn_rate / batch_rate, 2) if batch_rate > 0 else 0.0,
            "resolve_in_flight": self._resolving_now,
            "grv_rate": round(self._qos_started_rate.sample_total(
                snap.get("transactions_started", 0), now), 2),
            "commit_rate": round(txn_rate, 2),
            "tps_budget": self._rate,
        })

    def _repair_fallback_account(self, req) -> None:
        """A terminal abort delivered by the repair engine itself
        (re-read failure and friends): restore the conflict accounting
        phase 5 skipped when it captured this transaction — the txn
        DID conflict, and tag/priority QoS rates must not undercount
        exactly when the cluster is degraded."""
        self.stats.counter("transactions_conflicted").add(1)
        if SERVER_KNOBS.qos_tag_accounting:
            self._account(req, CONFLICT, False, flow.now())

    def update_hot_spots(self, rows) -> None:
        """CC-pushed cluster-merged hot-spot rows -> the admission
        scheduler's predictor AND the GRV conflict-window piggyback
        (rows arrive hottest-first: (begin, end, score, total,
        last_conflict_version))."""
        self.scheduler.update_hot_spots(rows, flow.now())
        k = SERVER_KNOBS
        min_score = float(k.conflict_window_score_min)
        top = int(k.conflict_window_top_k)
        self._conflict_windows = tuple(
            (b, e, v) for b, e, s, _t, v in rows[:top] if s >= min_score)

    def scheduler_status(self) -> dict:
        """Admission-scheduler decision counters for status/cli/
        exporter."""
        return self.scheduler.status()

    def repair_status(self) -> dict:
        """Transaction-repair decision counters for status/cli/
        exporter."""
        return self.repair.status()

    def admission_status(self) -> dict:
        """Enforced-admission decision counters + the live tag-throttle
        rows this proxy enforces, for status/cli/exporter."""
        return self.admission.status()

    def _note_resolving(self, delta: int) -> None:
        """Concurrently-resolving batch gauge + high-water mark."""
        self._resolving_now += delta
        self.stats.counter("resolve_in_flight").set(self._resolving_now)
        if self._resolving_now > self._resolving_peak:
            self._resolving_peak = self._resolving_now
            self.stats.counter("resolve_in_flight_peak").set(
                self._resolving_peak)

    @staticmethod
    def _norm_verdicts(r, n):
        """Resolver replies are a bare verdict list on the common path,
        a ResolveReply (verdicts + attributed ranges) when some txn in
        the batch asked for report_conflicting_keys — normalize to
        (verdicts, ranges_per_txn)."""
        if isinstance(r, ResolveReply):
            return list(r.verdicts), list(r.conflicting_ranges)
        return list(r), [()] * n

    async def _resolve_split(self, ver, reqs):
        """Send each transaction's ranges clipped per resolver via the
        keyResolvers map (current + windowed former owners after a
        move); every resolver sees every batch version (possibly with
        no transactions) so its NotifiedVersion ordering advances; a
        transaction's verdict is the min over the resolvers that saw it
        (ref: ResolutionRequestBuilder :265-341, combine :585-592).

        tooOld is decided HERE, not per-slice (ISSUE 15): a resolver
        whose clip holds only a tooOld transaction's WRITES would see
        no read ranges, verdict it committed, and merge phantom writes
        into its history — writes the unsplit oracle never records (a
        tooOld txn contributes no ranges at all). The proxy can decide
        it exactly: resolvers process the gapless version chain in
        order, so at batch (prev -> v) every resolver's GC watermark is
        precisely max(0, prev - MWTLV) — or 0 before the epoch's first
        batch — and all resolvers agree. A tooOld transaction is
        withheld from every resolver and combined as TOO_OLD."""
        n_res = len(self.resolver_refs)
        self.key_resolvers.prune(ver.version)
        window = self.key_resolvers.window
        res_oldest = 0 if ver.prev_version <= self._recovery_version \
            else max(0, ver.prev_version - window)
        per = [[] for _ in range(n_res)]   # [(orig_idx, clipped_req)]
        too_old = set()
        for idx, req in enumerate(reqs):
            if req.read_conflict_ranges and \
                    req.read_snapshot < res_oldest:
                flow.cover("proxy.resolve_split.too_old_withheld")
                too_old.add(idx)
                continue
            rr_per = self.key_resolvers.clip_per_resolver(
                req.read_conflict_ranges, n_res)
            wr_per = self.key_resolvers.clip_per_resolver(
                req.write_conflict_ranges, n_res)
            placed = False
            for i in range(n_res):
                if rr_per[i] or wr_per[i]:
                    per[i].append((idx, req._replace(
                        read_conflict_ranges=tuple(rr_per[i]),
                        write_conflict_ranges=tuple(wr_per[i]),
                        mutations=())))
                    placed = True
            if not placed:  # no conflict ranges at all -> resolver 0
                per[0].append((idx, req._replace(mutations=())))
        futs = [ref.get_reply(
            ResolveRequest(ver.prev_version, ver.version,
                           tuple(r for _, r in plist),
                           debug_ids=self._debug_ids(
                               [r for _, r in plist])), self.process)
            for ref, plist in zip(self.resolver_refs, per)]
        results = await flow.all_of(futs)
        combined = [TOO_OLD if i in too_old else COMMITTED
                    for i in range(len(reqs))]
        ranges: list = [()] * len(reqs)
        for plist, result in zip(per, results):
            verdicts, rngs = self._norm_verdicts(result, len(plist))
            for (idx, _), v, rs in zip(plist, verdicts, rngs):
                combined[idx] = min(combined[idx], v)
                if rs:
                    # union of each resolver's attribution: the clipped
                    # pieces are disjoint per resolver, dedup only the
                    # double-delivery window after a move
                    seen = set(ranges[idx])
                    ranges[idx] = tuple(ranges[idx]) + tuple(
                        r for r in rs if r not in seen)
        return ResolveReply(tuple(combined), tuple(ranges))

