"""Proxy role: GRV path + the pipelined commit batcher.

Reference: fdbserver/MasterProxyServer.actor.cpp —
  - commitBatcher (:344): group commit requests by time window / count;
  - commitBatch (:410), five phases kept as distinct awaits here:
      1 order via latestLocalCommitBatchResolving + master.getVersion
      2 resolver.resolve (key-range split when sharded — the TPU
        sharded backend does that split on-device instead)
      3 verdict combine + mutation assembly
      4 log push, sequenced via latestLocalCommitBatchLogging
      5 per-txn replies: committed / not_committed / too_old
  - transactionStarter / getLiveCommittedVersion (:1102/:1019): GRV
    returns the proxy's committed version (single-proxy slice of the
    all-proxies confirmation).
Batches overlap: while one batch waits on the log fsync, the next can
already be resolving — the NotifiedVersion pair is the software
pipeline's interlock, exactly the reference's structure.
"""

from __future__ import annotations

from typing import List

from .. import flow
from ..flow import NotifiedVersion, TaskPriority, error
from ..models import COMMITTED, CONFLICT, TOO_OLD
from ..rpc import NetworkRef, RequestStream, SimProcess
from .types import (CommitReply, CommitRequest, GetReadVersionReply,
                    ResolveRequest, TLogCommitRequest)


class Proxy:
    def __init__(self, process: SimProcess, master_ref: NetworkRef,
                 resolver_ref: NetworkRef, tlog_ref: NetworkRef,
                 recovery_version: int = 0,
                 batch_window: float = 0.001, max_batch: int = 512):
        self.process = process
        self.master_ref = master_ref
        self.resolver_ref = resolver_ref
        self.tlog_ref = tlog_ref
        self.batch_window = batch_window
        self.max_batch = max_batch
        self.committed_version = NotifiedVersion(recovery_version)
        self.batch_resolving = NotifiedVersion(recovery_version)
        self.batch_logging = NotifiedVersion(recovery_version)
        self.commits = RequestStream(process)
        self.grvs = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._batcher(),
                                    TaskPriority.PROXY_COMMIT_BATCHER,
                                    name=f"{self.process.name}.batcher"))
        self._actors.add(flow.spawn(self._grv_loop(),
                                    TaskPriority.PROXY_GET_CONSISTENT_READ_VERSION,
                                    name=f"{self.process.name}.grv"))
        self.process.on_kill(self._actors.cancel_all)

    # -- GRV ------------------------------------------------------------
    async def _grv_loop(self):
        while True:
            _req, reply = await self.grvs.pop()
            reply.send(GetReadVersionReply(self.committed_version.get()))

    # -- commit pipeline ------------------------------------------------
    async def _batcher(self):
        """(ref: commitBatcher :344 — batch by window/count)"""
        while True:
            req, reply = await self.commits.pop()
            batch: List = [(req, reply)]
            deadline = flow.delay(self.batch_window,
                                  TaskPriority.PROXY_COMMIT_BATCHER)
            while len(batch) < self.max_batch:
                nxt = self.commits.pop()
                got = await flow.first_of(nxt, deadline)
                if got[0] == 1:  # window expired
                    break
                batch.append(got[1])
            deadline.cancel()
            flow.spawn(self._commit_batch(batch), TaskPriority.PROXY_COMMIT)

    async def _commit_batch(self, batch):
        reqs = [r for r, _ in batch]
        replies = [p for _, p in batch]
        try:
            # phase 1: version assignment, ordered with earlier batches
            ver = await self.master_ref.get_reply(None, self.process)
            await self.batch_resolving.when_at_least(ver.prev_version)

            # phase 2: conflict resolution
            verdicts = await self.resolver_ref.get_reply(
                ResolveRequest(ver.prev_version, ver.version, tuple(reqs)),
                self.process)
            self.batch_resolving.set(ver.version)

            # phase 3: assemble mutations of committed transactions
            mutations = []
            for req, verdict in zip(reqs, verdicts):
                if verdict == COMMITTED:
                    mutations.extend(req.mutations)

            # phase 4: log push, ordered (ref: latestLocalCommitBatchLogging)
            await self.batch_logging.when_at_least(ver.prev_version)
            await self.tlog_ref.get_reply(
                TLogCommitRequest(ver.prev_version, ver.version,
                                  tuple(mutations)), self.process)
            self.batch_logging.set(ver.version)
            if self.committed_version.get() < ver.version:
                self.committed_version.set(ver.version)

            # phase 5: per-transaction replies
            for verdict, reply in zip(verdicts, replies):
                if verdict == COMMITTED:
                    reply.send(CommitReply(ver.version))
                elif verdict == TOO_OLD:
                    reply.send_error(error("transaction_too_old"))
                else:
                    reply.send_error(error("not_committed"))
        except flow.FdbError as e:
            for reply in replies:
                reply.send_error(e)
