"""Transaction log role (durable over a DiskQueue).

Reference: fdbserver/TLogServer.actor.cpp — `tLogCommit` (:1468) appends
versioned mutation sets in strict version order (commits carrying
prev_version sequence via NotifiedVersion) and acks after the queue
commit becomes durable (doQueueCommit :1382 — a DiskQueue push+sync on
the machine's simulated disk, or a plain fsync delay in memory mode);
`tLogPeekMessages` (:1138) long-polls readers from a version (served by
bisect over the in-memory index, not a rescan); `tLogPop` (:1050)
discards acked prefixes from memory AND reclaims DiskQueue space; on
reboot the log recovers every acked entry from disk (ref: TLog restart
via initPersistentState/restorePersistentState). Tag partitioning
arrives with multi-storage; this slice logs one tag.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Optional

from .. import flow
from ..flow import FlowLock, NotifiedVersion, TaskPriority
from ..rpc import RequestStream, SimProcess
from ..rpc.disk import SimDisk
from .diskqueue import DiskQueue
from .types import (TLogCommitRequest, TLogPeekReply, TLogPeekRequest,
                    TLogPopRequest)
from .wire import decode_log_entry, encode_log_entry


class TLog:
    def __init__(self, process: SimProcess, disk: Optional[SimDisk] = None,
                 name: str = "tlog", fsync_delay: float = 0.0005):
        self.process = process
        self.fsync_delay = fsync_delay
        self._dq = (DiskQueue(disk, name, owner=process)
                    if disk is not None else None)
        self.entries: list = []  # [(version, mutations, seq)] sorted
        self._versions: list = []  # parallel sorted version index
        self.version = NotifiedVersion(0)   # highest durable version
        self.queue_version = NotifiedVersion(0)  # highest accepted version
        self.popped = 0
        self.commits = RequestStream(process)
        self.peeks = RequestStream(process)
        self.pops = RequestStream(process)
        self._dq_lock = FlowLock()
        self._recovered = flow.Future()
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._run(), TaskPriority.TLOG_COMMIT,
                                    name=f"{self.process.name}.run"))
        self.process.on_kill(self._actors.cancel_all)

    async def _run(self) -> None:
        await self._recover()
        self._actors.add(flow.spawn(self._commit_loop(),
                                    TaskPriority.TLOG_COMMIT,
                                    name=f"{self.process.name}.commit"))
        self._actors.add(flow.spawn(self._peek_loop(), TaskPriority.TLOG_PEEK,
                                    name=f"{self.process.name}.peek"))
        self._actors.add(flow.spawn(self._pop_loop(), TaskPriority.TLOG_POP,
                                    name=f"{self.process.name}.pop"))

    async def _recover(self) -> None:
        """Rebuild the in-memory index from whatever the DiskQueue's
        committed prefix preserved; versions resume from the last
        durable entry."""
        if self._dq is not None:
            payloads = await self._dq.recover()
            seq0 = self._dq.next_seq - len(payloads)
            for i, payload in enumerate(payloads):
                version, mutations = decode_log_entry(payload)
                self.entries.append((version, mutations, seq0 + i))
                self._versions.append(version)
            if self.entries:
                last = self.entries[-1][0]
                self.version.set(last)
                self.queue_version.set(last)
        if not self._recovered.is_ready:
            self._recovered.send(None)

    def recovered(self) -> flow.Future:
        return self._recovered

    async def _commit_loop(self):
        # spawn per request: pushes from successive proxy batches are in
        # flight concurrently (the proxy releases its logging interlock at
        # push time) and the network can deliver them out of order; a
        # serial loop awaiting prev_version would wedge behind a
        # reordered pair (same per-request tolerance as the resolver).
        while True:
            req, reply = await self.commits.pop()
            assert isinstance(req, TLogCommitRequest)
            flow.spawn(self._handle_commit(req, reply),
                       TaskPriority.TLOG_COMMIT)

    async def _handle_commit(self, req: TLogCommitRequest, reply):
        # strict version ordering (ref: tLogCommit waits for
        # logData->version == req.prevVersion)
        await self.queue_version.when_at_least(req.prev_version)
        if self.queue_version.get() >= req.version:
            # duplicate delivery: the entry is already queued (possibly
            # not yet fsynced) — ack only once it IS durable, never
            # append twice (ADVICE r1: comparing against the durable
            # version raced the in-flight fsync)
            await self._ack_when_durable(req.version, reply)
            return
        self.queue_version.set(req.version)
        self.entries.append((req.version, req.mutations, -1))
        self._versions.append(req.version)
        flow.spawn(self._make_durable(req, reply),
                   TaskPriority.TLOG_COMMIT_REPLY)

    async def _make_durable(self, req: TLogCommitRequest, reply):
        """Durability: DiskQueue push+commit (ref: doQueueCommit), or the
        simulated fsync delay in memory mode. The FlowLock is FIFO and
        durable actors are spawned in version order, so log records land
        on disk in version order."""
        version = req.version
        if self._dq is None:
            await flow.delay(self.fsync_delay, TaskPriority.TLOG_COMMIT_REPLY)
        else:
            await self._dq_lock.take()
            try:
                seq = await self._dq.push(
                    encode_log_entry(version, req.mutations))
                await self._dq.commit()
            finally:
                self._dq_lock.release()
            i = bisect_left(self._versions, version)
            if i < len(self._versions) and self._versions[i] == version:
                e = self.entries[i]
                self.entries[i] = (e[0], e[1], seq)
        if self.version.get() < version:
            self.version.set(version)
        reply.send(version)

    async def _ack_when_durable(self, version, reply):
        await self.version.when_at_least(version)
        reply.send(self.version.get())

    async def _peek_loop(self):
        while True:
            req, reply = await self.peeks.pop()
            assert isinstance(req, TLogPeekRequest)
            flow.spawn(self._serve_peek(req, reply), TaskPriority.TLOG_PEEK_REPLY)

    async def _serve_peek(self, req: TLogPeekRequest, reply):
        # long-poll: wait until something at/after begin_version is durable
        await self.version.when_at_least(req.begin_version)
        lo = bisect_left(self._versions, req.begin_version)
        durable = self.version.get()
        hi = bisect_right(self._versions, durable)
        out = tuple((v, m) for v, m, _s in self.entries[lo:hi])
        reply.send(TLogPeekReply(out, durable))

    async def _pop_loop(self):
        while True:
            req, _reply = await self.pops.pop()
            assert isinstance(req, TLogPopRequest)
            self.pop(req.version)

    def pop(self, version: int) -> None:
        """Discard entries at or below `version` from memory and disk
        (ref: tLogPop driven by storage durability)."""
        if version <= self.popped:
            return
        self.popped = version
        hi = bisect_right(self._versions, version)
        if hi == 0:
            return
        max_seq = max((s for _v, _m, s in self.entries[:hi]), default=-1)
        del self.entries[:hi]
        del self._versions[:hi]
        if self._dq is not None and max_seq >= 0:
            self._dq.pop(max_seq)
