"""Transaction log role: tag-partitioned, durable over a DiskQueue,
lockable for epoch recovery.

Reference: fdbserver/TLogServer.actor.cpp — `tLogCommit` (:1468) appends
versioned tagged mutation sets in strict version order (commits carrying
prev_version sequence via NotifiedVersion) and acks after the queue
commit becomes durable (doQueueCommit :1382 — a DiskQueue push+sync on
the machine's simulated disk, or a plain fsync delay in memory mode);
`tLogPeekMessages` (:1138) long-polls readers *per tag* from a version;
`tLogPop` (:1050) discards a tag's acked prefix from memory and reclaims
DiskQueue space once every tag has popped past a record; `TLogLock`
(epochEnd, TagPartitionedLogSystem.actor.cpp:1265) stops the log — it
rejects further commits with tlog_stopped but keeps serving peeks so the
next generation and the storage servers can drain it. On reboot the log
recovers every acked entry from disk (ref: restorePersistentState).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Dict, List, Optional

from .. import flow
from ..flow import FlowLock, NotifiedVersion, TaskPriority, error
from ..rpc import RequestStream, SimProcess
from ..rpc.disk import SimDisk
from .chaos import fire_station
from .critical_path import RolePathRecorder
from .diskqueue import DiskQueue
from .types import (DurableFrontierRequest,
                    TLogCommitRequest, TLogLockReply, TLogLockRequest,
                    TLogPeekReply, TLogPeekRequest, TLogPopRequest,
                    mutation_bytes)
from .wire import decode_log_entry, encode_log_entry


def _tag_set(tagged) -> frozenset:
    tags = set()
    for tm in tagged:
        tags.update(tm.tags)
    return frozenset(tags)


def _payload_bytes(tagged) -> int:
    return sum(mutation_bytes(tm.mutation) for tm in tagged)


class TLog:
    def __init__(self, process: SimProcess, disk: Optional[SimDisk] = None,
                 name: str = "tlog", fsync_delay: Optional[float] = None,
                 recovery_version: int = 0):
        self.process = process
        self.name = name
        self.fsync_delay = (fsync_delay if fsync_delay is not None
                            else flow.SERVER_KNOBS.tlog_fsync_delay)
        self._dq = (DiskQueue(disk, name, owner=process)
                    if disk is not None else None)
        # [(version, tagged_mutations, seq)] sorted by version; a
        # SPILLED entry's tagged_mutations is None — its payload lives
        # only in the DiskQueue, re-read at peek (ref: TLog spill,
        # TLogServer.actor.cpp updatePersistentData — memory stays
        # bounded by TLOG_SPILL_THRESHOLD while a lagging reader can
        # still drain the log)
        self.entries: list = []
        self._versions: list = []  # parallel sorted version index
        self._entry_tags: list = []  # parallel per-record tag sets
        self._entry_bytes: list = []  # parallel payload-size estimates
        self.mem_bytes = 0            # total un-spilled payload bytes
        self._spill_floor = 0         # first possibly-unspilled index
        self.version = NotifiedVersion(recovery_version)  # highest durable
        self.queue_version = NotifiedVersion(recovery_version)  # accepted
        self.known_committed = recovery_version  # replicated log-set-wide
        # per-tag, per-replica popped versions; a tag's effective pop
        # is the min across its EXPECTED replicas — a replica that has
        # never popped holds the tag's records (code review r3: min over
        # seen-only would free data a clogged/rebooting replica needs)
        self.popped: Dict[int, Dict[str, int]] = {}
        self.expected_replicas: Dict[int, tuple] = {}
        self.stopped = False                     # locked by recovery
        self._stop_future = flow.Future()        # fires when locked
        self.commits = RequestStream(process)
        self.peeks = RequestStream(process)
        self.pops = RequestStream(process)
        self.locks = RequestStream(process)
        self._dq_lock = FlowLock()
        # (ref: TLogData counters: commits/bytes for status + ratekeeper)
        self.stats = flow.CounterCollection("tlog")
        # banded + sampled commit durability latency (accept -> fsync ack)
        self.commit_bands = flow.RequestLatency("commit")
        # critical-path split (ISSUE 18): version-ordering wait in
        # _handle_commit vs fsync service in _make_durable, bridged by
        # a per-request enter stamp; armed via CRITICAL_PATH only
        self.path = RolePathRecorder("tlog")
        # QoS saturation signals (ref: TLogQueuingMetricsReply — the
        # smoothed queue surface the Ratekeeper polls). Pull model:
        # qos_sample() reads raw state at the collection cadence; the
        # commit/peek hot paths never update these
        self._qos_queue = flow.SmoothedQueue()
        self._qos_backlog = flow.SmoothedQueue()
        self._qos_commit_rate = flow.SmoothedRate()
        self._recovered = flow.Future()
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._run(), TaskPriority.TLOG_COMMIT,
                                    name=f"{self.process.name}.run"))
        self.process.on_kill(self._actors.cancel_all)

    async def _run(self) -> None:
        try:
            await self._recover()
        except flow.FdbError:
            return   # corrupt store: recovered() carries the error
        for coro, prio, name in (
                (self._commit_loop(), TaskPriority.TLOG_COMMIT, "commit"),
                (self._peek_loop(), TaskPriority.TLOG_PEEK, "peek"),
                (self._pop_loop(), TaskPriority.TLOG_POP, "pop"),
                (self._lock_loop(), TaskPriority.TLOG_COMMIT, "lock")):
            self._actors.add(flow.spawn(coro, prio,
                                        name=f"{self.process.name}.{name}"))

    async def _recover(self) -> None:
        """Rebuild the in-memory index from whatever the DiskQueue's
        committed prefix preserved; versions resume from the last
        durable entry."""
        if self._dq is not None:
            try:
                payloads = await self._dq.recover()
            except flow.FdbError as e:
                # detected on-disk corruption: this store is LOST — the
                # waiter (worker boot) learns through the recovered()
                # future and treats it as a dead store; the role's other
                # actors never start (ref: a tlog failing its recovery)
                if not self._recovered.is_ready:
                    self._recovered.send_error(e)
                raise
            seq0 = self._dq.next_seq - len(payloads)
            for i, payload in enumerate(payloads):
                version, tagged = decode_log_entry(payload)
                self.entries.append((version, tagged, seq0 + i))
                self._versions.append(version)
                self._entry_tags.append(_tag_set(tagged))
                nb = _payload_bytes(tagged)
                self._entry_bytes.append(nb)
                self.mem_bytes += nb
            if self.entries:
                last = self.entries[-1][0]
                self.version.set(last)
                self.queue_version.set(last)
        # re-apply the memory bound: recovery decoded the whole durable
        # queue into memory, which may far exceed the spill threshold
        self._maybe_spill()
        if not self._recovered.is_ready:
            self._recovered.send(None)

    def recovered(self) -> flow.Future:
        return self._recovered

    async def _commit_loop(self):
        # spawn per request: pushes from successive proxy batches are in
        # flight concurrently (the proxy releases its logging interlock at
        # push time) and the network can deliver them out of order; a
        # serial loop awaiting prev_version would wedge behind a
        # reordered pair (same per-request tolerance as the resolver).
        while True:
            req, reply = await self.commits.pop()
            if type(req) is DurableFrontierRequest:
                # durable-frontier probe (degraded GRV): every commit a
                # proxy has EVER acked is durable on all logs, so the
                # min of these frontiers across logs is a committed,
                # readable read-version floor. Answers even while
                # stopped — a locked log still knows what it holds.
                reply.send(self.version.get())
                continue
            assert isinstance(req, TLogCommitRequest)
            flow.spawn(self._handle_commit(req, reply),
                       TaskPriority.TLOG_COMMIT)

    async def _handle_commit(self, req: TLogCommitRequest, reply):
        path_armed = bool(flow.SERVER_KNOBS.critical_path)
        if path_armed:
            # queue-entry stamp: the gap to _make_durable's start is
            # this commit's version-ordering wait (popped by every
            # early-return path so the bounded map never leaks)
            self.path.note_enter(req, flow.now())
        if self.stopped:
            flow.cover("tlog.commit.stopped")
            reply.send_error(error("tlog_stopped"))
            self.path.take_enter(req, 0.0)
            return
        # strict version ordering (ref: tLogCommit waits for
        # logData->version == req.prevVersion). A lock wakes parked
        # waiters: their gap will never be filled by a dead proxy, so
        # they must fail out instead of wedging the batch forever.
        await flow.first_of(
            self.queue_version.when_at_least(req.prev_version),
            self._stop_future)
        if self.stopped and self.queue_version.get() < req.prev_version:
            reply.send_error(error("tlog_stopped"))
            self.path.take_enter(req, 0.0)
            return
        if req.known_committed > self.known_committed:
            self.known_committed = req.known_committed
        if self.queue_version.get() >= req.version:
            # duplicate delivery: the entry is already queued (possibly
            # not yet fsynced) — ack only once it IS durable, never
            # append twice (ADVICE r1: comparing against the durable
            # version raced the in-flight fsync)
            self.path.take_enter(req, 0.0)
            await self._ack_when_durable(req.version, reply)
            return
        if self.stopped:
            flow.cover("tlog.commit.stopped")
            reply.send_error(error("tlog_stopped"))
            self.path.take_enter(req, 0.0)
            return
        # the log-leg stations fire only on ACCEPTED first deliveries:
        # a stopped rejection or a duplicate proxy retry must not file
        # a phantom extra tlog leg into a sampled commit's stitching
        # (same invariant as the resolver's duplicate-delivery guard).
        # Named for where it actually sits — after the version-ordering
        # wait, before the fsync — so a stitched timeline attributes a
        # prev_version stall to the gap before this station, not to
        # the fsync leg
        flow.g_trace_batch.add_events(
            getattr(req, "debug_ids", ()), "CommitDebug",
            "TLog.tLogCommit.AfterWaitForVersion")
        fire_station("TLog.tLogCommit.AfterWaitForVersion")
        self.queue_version.set(req.version)
        self.stats.counter("commits").add(1)
        self.stats.counter("mutations").add(len(req.mutations))
        self.entries.append((req.version, req.mutations, -1))
        self._versions.append(req.version)
        self._entry_tags.append(_tag_set(req.mutations))
        nb = _payload_bytes(req.mutations)
        self._entry_bytes.append(nb)
        self.mem_bytes += nb
        flow.spawn(self._make_durable(req, reply),
                   TaskPriority.TLOG_COMMIT_REPLY)

    async def _make_durable(self, req: TLogCommitRequest, reply):
        t0 = flow.now()
        dbg = getattr(req, "debug_ids", ())
        # the log leg of the commit span tree: spans open at fsync
        # start and close at the durability ack, parented onto the
        # proxy's still-open commitBatch span for each sampled txn
        spans = flow.g_trace_batch.begin_spans(dbg, "TLog.tLogCommit")
        try:
            await self._do_durable(req)
        finally:
            flow.g_trace_batch.finish_spans(spans)
        version = req.version
        if self.version.get() < version:
            self.version.set(version)
        flow.g_trace_batch.add_events(
            dbg, "CommitDebug", "TLog.tLogCommit.AfterTLogCommit")
        fire_station("TLog.tLogCommit.AfterTLogCommit")
        done = flow.now()
        self.commit_bands.record(done - t0)
        if flow.SERVER_KNOBS.critical_path:
            enter = self.path.take_enter(req, t0)
            self.path.record(t0 - enter, done - t0)
        reply.send(version)

    async def _do_durable(self, req: TLogCommitRequest):
        """Durability: DiskQueue push+commit (ref: doQueueCommit), or the
        simulated fsync delay in memory mode. The FlowLock is FIFO and
        durable actors are spawned in version order, so log records land
        on disk in version order. The caller (_make_durable) advances
        the durable version and acks."""
        version = req.version
        if self._dq is None:
            if flow.buggify("tlog/slow_fsync"):
                await flow.delay(flow.g_random.random01()
                           * flow.SERVER_KNOBS.buggify_tlog_commit_delay_max,
                                 TaskPriority.TLOG_COMMIT_REPLY)
            await flow.delay(self.fsync_delay, TaskPriority.TLOG_COMMIT_REPLY)
            # directed fsync-stall injection (ISSUE 18): the tlog twin
            # of COMMIT_LATENCY_INJECTION — a path drill arms this to
            # prove tlog_fsync shows up dominant in the decomposition.
            # 0 (the default) is one knob read, no delay
            inj = flow.SERVER_KNOBS.tlog_fsync_injection
            if inj:
                await flow.delay(inj, TaskPriority.TLOG_COMMIT_REPLY)
            # variable delays must not reorder durability acks
            await self.version.when_at_least(req.prev_version)
        else:
            await self._dq_lock.take()
            try:
                if flow.buggify("tlog/slow_fsync"):
                    # a straggling disk: widens the accepted-but-not-
                    # durable window (stresses lock + recovery races).
                    # INSIDE the FIFO lock: records must still land on
                    # disk in version order (code review r3)
                    await flow.delay(flow.g_random.random01()
                           * flow.SERVER_KNOBS.buggify_tlog_commit_delay_max,
                                     TaskPriority.TLOG_COMMIT_REPLY)
                seq = await self._dq.push(
                    encode_log_entry(version, req.mutations))
                await self._dq.commit()
                # fsync-stall injection INSIDE the FIFO lock: a real
                # stalled disk serializes everything behind it, and the
                # drill must reproduce that shape (ISSUE 18)
                inj = flow.SERVER_KNOBS.tlog_fsync_injection
                if inj:
                    await flow.delay(inj, TaskPriority.TLOG_COMMIT_REPLY)
            finally:
                self._dq_lock.release()
            i = bisect_left(self._versions, version)
            if i < len(self._versions) and self._versions[i] == version:
                e = self.entries[i]
                self.entries[i] = (e[0], e[1], seq)
            self._maybe_spill()

    def _maybe_spill(self) -> None:
        """Spill the oldest durable entries once in-memory payload bytes
        exceed TLOG_SPILL_THRESHOLD: memory keeps only the position; a
        peek re-reads the payload from the DiskQueue (ref:
        updatePersistentData's spill-by-reference)."""
        from ..flow import SERVER_KNOBS
        limit = SERVER_KNOBS.tlog_spill_threshold
        if self._dq is None or self.mem_bytes <= limit:
            return
        spilled_to = -1
        for i in range(self._spill_floor, len(self.entries)):
            if self.mem_bytes <= limit:
                break
            v, tagged, s = self.entries[i]
            if tagged is None:
                self._spill_floor = i + 1
                continue
            if s < 0:
                break   # not yet durable: spill is a strict prefix
            self.entries[i] = (v, None, s)
            self.mem_bytes -= self._entry_bytes[i]
            self._entry_bytes[i] = 0
            self._spill_floor = i + 1
            spilled_to = max(spilled_to, s)
        if spilled_to >= 0:
            flow.cover("tlog.spilled")
            self.stats.counter("spills").add(1)
            self._dq.spill(spilled_to)

    async def _ack_when_durable(self, version, reply):
        await self.version.when_at_least(version)
        reply.send(self.version.get())

    def qos_sample(self, now: float) -> "QosSample":
        """Saturation-signal snapshot (ref: TLogQueuingMetricsReply):
        smoothed unpopped queue bytes, the fsync backlog (accepted but
        not yet durable — versions still inside the durability window),
        queue length, and the commit rate."""
        from .types import QosSample
        backlog = max(0, self.queue_version.get() - self.version.get())
        return QosSample("tlog", self.name, now, {
            "queue_bytes": round(
                self._qos_queue.sample(self.mem_bytes, now), 1),
            "queue_entries": len(self.entries),
            "fsync_backlog_versions": round(
                self._qos_backlog.sample(backlog, now), 1),
            "commit_rate": round(self._qos_commit_rate.sample_total(
                self.stats.counter("commits").value, now), 2),
        })

    # -- lock (epoch end) ----------------------------------------------
    async def _lock_loop(self):
        while True:
            req, reply = await self.locks.pop()
            assert isinstance(req, TLogLockRequest)
            flow.spawn(self._serve_lock(reply), TaskPriority.TLOG_COMMIT)

    async def _serve_lock(self, reply):
        if not self.stopped:
            self.stopped = True
            self._stop_future.send(None)  # wake parked commit/peek waiters
        # accepted-but-unfsynced commits are still in flight; the end
        # version must cover them or a commit could be acked to a client
        # AFTER recovery chose a lower end (acked-data loss). Wait for
        # the fsyncs to drain (ref: TLogServer lock waits for the queue
        # to catch up before replying).
        await self.version.when_at_least(self.queue_version.get())
        reply.send(TLogLockReply(self.version.get(), self.known_committed))

    # -- peek / pop -----------------------------------------------------
    async def _peek_loop(self):
        while True:
            req, reply = await self.peeks.pop()
            assert isinstance(req, TLogPeekRequest)
            flow.spawn(self._serve_peek(req, reply),
                       TaskPriority.TLOG_PEEK_REPLY)

    async def _serve_peek(self, req: TLogPeekRequest, reply):
        # long-poll: wait until something at/after begin_version is
        # durable. A locked log replies immediately — there will never be
        # more (the reader fails over to the next generation) — and a
        # lock arriving mid-wait wakes the parked poll the same way.
        if not self.stopped:
            await flow.first_of(
                self.version.when_at_least(req.begin_version),
                self._stop_future)
        lo = bisect_left(self._versions, req.begin_version)
        durable = self.version.get()
        hi = bisect_right(self._versions, durable)
        # peeking at/below the tag's freed floor means pin bookkeeping
        # let records this reader still needs be discarded — scream and
        # stall the reader at the hole instead of silently losing data
        # (ref: the TLog's popped-version check in tLogPeekMessages)
        popped_floor = self._tag_popped(req.tag)
        if popped_floor >= req.begin_version:
            flow.TraceEvent("TLogPeekBelowPopped", self.name,
                            severity=flow.trace.SevError).detail(
                Tag=req.tag, Begin=req.begin_version,
                Popped=popped_floor).log()
            # throttle: the reader will re-peek the same version forever
            # (no progress is possible); don't let that become a hot
            # RPC loop that floods the scheduler and the trace file
            await flow.delay(flow.SERVER_KNOBS.tlog_stalled_peek_delay,
                             TaskPriority.LOW_PRIORITY)
            reply.send(TLogPeekReply((), req.begin_version - 1,
                                     self.known_committed))
            return
        out = []
        # snapshot: spilled reads await the disk, and a concurrent pop
        # may shift the live lists under us. The tag index answers
        # "does this record even carry my tag" without touching disk.
        # Replies are SIZE-BOUNDED (ref: DESIRED_TOTAL_BYTES chunking in
        # tLogPeekMessages) — a far-behind reader drains in chunks; its
        # next poll continues past the last delivered version, and the
        # reply's `durable` watermark is clamped to what was actually
        # delivered so the reader cannot skip the truncated remainder.
        snap = list(zip(self.entries[lo:hi], self._entry_tags[lo:hi]))
        limit_bytes = flow.SERVER_KNOBS.desired_total_bytes
        sent_bytes = 0
        truncated_at = None
        for (v, tagged, s), etags in snap:
            if req.tag not in etags:
                continue
            if sent_bytes >= limit_bytes:
                truncated_at = v
                break
            if tagged is None:
                payload = await self._dq.read(s)
                if payload is None:
                    # popped while we read: records this reader still
                    # needs were freed mid-peek. Scream, and clamp the
                    # watermark below v UNFLOORED so the reader cannot
                    # advance past the hole even when v == begin (the
                    # byte-limit floor would swallow exactly that case).
                    flow.TraceEvent("TLogPeekRecordFreed", self.name,
                                    severity=flow.trace.SevError).detail(
                        Tag=req.tag, Version=v).log()
                    await flow.delay(flow.SERVER_KNOBS.tlog_stalled_peek_delay,
                                     TaskPriority.LOW_PRIORITY)
                    reply.send(TLogPeekReply(
                        tuple(out), max(0, v - 1), self.known_committed))
                    return
                _v, tagged = decode_log_entry(payload)
            ms = tuple(tm for tm in tagged if req.tag in tm.tags)
            if ms:
                # with_tags keeps the full tag vectors (the region log
                # router re-partitions by them); plain peeks get bare
                # mutations
                out.append((v, ms if getattr(req, "with_tags", False)
                            else tuple(tm.mutation for tm in ms)))
                sent_bytes += sum(mutation_bytes(tm.mutation)
                                  for tm in ms)
        if truncated_at is not None:
            durable = min(durable, max(req.begin_version,
                                       truncated_at - 1))
        reply.send(TLogPeekReply(tuple(out), durable, self.known_committed))

    async def _pop_loop(self):
        while True:
            req, _reply = await self.pops.pop()
            assert isinstance(req, TLogPopRequest)
            self.pop(req.version, req.tag, getattr(req, "replica", ""))

    def set_expected_replicas(self, mapping: Dict[int, tuple]) -> None:
        """Tag -> replica names that must pop before records free (ref:
        the log system knowing each tag's team)."""
        self.expected_replicas = dict(mapping)

    def _tag_popped(self, tag: int) -> int:
        reps = self.popped.get(tag, {})
        expected = self.expected_replicas.get(tag)
        if expected:
            return min((reps.get(name, -1) for name in expected),
                       default=-1)
        if not reps:
            return -1
        return min(reps.values())

    def pop(self, version: int, tag: int = 0, replica: str = "") -> None:
        """Record that `replica` of `tag` no longer needs entries at or
        below `version`; free memory and disk once *every* tag with
        data in a record has popped past it on ALL its replicas
        (ref: tLogPop + popDiskQueue)."""
        reps = self.popped.setdefault(tag, {})
        if version <= reps.get(replica, -1):
            return
        reps[replica] = version
        # free the poppable prefix: walk until the first record some tag
        # still needs (per-record tag sets are precomputed at append, so
        # the scan costs O(records freed + 1))
        hi = 0
        for i, v in enumerate(self._versions):
            tags = self._entry_tags[i]
            if tags and any(self._tag_popped(t) < v for t in tags):
                break
            hi = i + 1
        if hi == 0:
            return
        max_seq = max((s for _v, _m, s in self.entries[:hi]), default=-1)
        self.mem_bytes -= sum(self._entry_bytes[:hi])
        del self.entries[:hi]
        del self._versions[:hi]
        del self._entry_tags[:hi]
        del self._entry_bytes[:hi]
        self._spill_floor = max(0, self._spill_floor - hi)
        if self._dq is not None and max_seq >= 0:
            self._dq.pop(max_seq)
