"""In-memory transaction log role.

Reference: fdbserver/TLogServer.actor.cpp — `tLogCommit` (:1468) appends
versioned mutation sets in strict version order (commits carrying
prev_version sequence via NotifiedVersion) and acks after the queue
commit becomes durable (doQueueCommit :1382 — here a simulated fsync
delay); `tLogPeekMessages` (:1138) long-polls readers from a version;
`tLogPop` (:1050) discards acked prefixes. Tag partitioning arrives with
multi-storage; this slice logs one tag.
"""

from __future__ import annotations

from .. import flow
from ..flow import NotifiedVersion, TaskPriority
from ..rpc import RequestStream, SimProcess
from .types import TLogCommitRequest, TLogPeekReply, TLogPeekRequest


class TLog:
    def __init__(self, process: SimProcess, fsync_delay: float = 0.0005):
        self.process = process
        self.fsync_delay = fsync_delay
        self.entries: list = []  # [(version, mutations)] sorted
        self.version = NotifiedVersion(0)   # highest durable version
        self.queue_version = NotifiedVersion(0)  # highest accepted version
        self.popped = 0
        self.commits = RequestStream(process)
        self.peeks = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._commit_loop(), TaskPriority.TLOG_COMMIT,
                                    name=f"{self.process.name}.commit"))
        self._actors.add(flow.spawn(self._peek_loop(), TaskPriority.TLOG_PEEK,
                                    name=f"{self.process.name}.peek"))
        self.process.on_kill(self._actors.cancel_all)

    async def _commit_loop(self):
        # spawn per request: pushes from successive proxy batches are in
        # flight concurrently (the proxy releases its logging interlock at
        # push time) and the network can deliver them out of order; a
        # serial loop awaiting prev_version would wedge behind a
        # reordered pair (same per-request tolerance as the resolver).
        while True:
            req, reply = await self.commits.pop()
            assert isinstance(req, TLogCommitRequest)
            flow.spawn(self._handle_commit(req, reply),
                       TaskPriority.TLOG_COMMIT)

    async def _handle_commit(self, req: TLogCommitRequest, reply):
        # strict version ordering (ref: tLogCommit waits for
        # logData->version == req.prevVersion)
        await self.queue_version.when_at_least(req.prev_version)
        if self.queue_version.get() >= req.version:
            # duplicate delivery: the entry is already queued (possibly
            # not yet fsynced) — ack only once it IS durable, never
            # append twice (ADVICE r1: comparing against the durable
            # version raced the in-flight fsync)
            await self._ack_when_durable(req.version, reply)
            return
        self.queue_version.set(req.version)
        self.entries.append((req.version, req.mutations))
        # durability: simulated fsync before ack
        flow.spawn(self._make_durable(req.version, reply),
                   TaskPriority.TLOG_COMMIT_REPLY)

    async def _make_durable(self, version, reply):
        await flow.delay(self.fsync_delay, TaskPriority.TLOG_COMMIT_REPLY)
        if self.version.get() < version:
            self.version.set(version)
        reply.send(version)

    async def _ack_when_durable(self, version, reply):
        await self.version.when_at_least(version)
        reply.send(self.version.get())

    async def _peek_loop(self):
        while True:
            req, reply = await self.peeks.pop()
            assert isinstance(req, TLogPeekRequest)
            flow.spawn(self._serve_peek(req, reply), TaskPriority.TLOG_PEEK_REPLY)

    async def _serve_peek(self, req: TLogPeekRequest, reply):
        # long-poll: wait until something at/after begin_version is durable
        await self.version.when_at_least(req.begin_version)
        out = tuple((v, m) for v, m in self.entries
                    if v >= req.begin_version)
        reply.send(TLogPeekReply(out, self.version.get()))

    def pop(self, version: int) -> None:
        """Discard entries at or below `version` (ref: tLogPop)."""
        self.popped = max(self.popped, version)
        self.entries = [(v, m) for v, m in self.entries if v > version]
