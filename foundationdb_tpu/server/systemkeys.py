"""The \\xff system keyspace schema — single source of truth.

Reference: fdbclient/SystemData.cpp (keyServers/, conf/, excluded/
prefixes and key names). Everything in [\\xff\\x02, \\xff\\xff) is real
stored data committed through the ordinary pipeline EXCEPT
\\xff/keyServers/, which is materialized from the broadcast shard map;
\\xff\\xff is engine metadata and never surfaces. The management rows
(\\xff/conf/, \\xff/excluded/) are the coordination medium: the proxy
forwards committed mutations there to the CC
(ref: ApplyMetadataMutation.h), and the CC also reconciles from the
stored rows so the keys — not the RPC — are authoritative.
"""

SYSTEM_PREFIX = b"\xff"
ENGINE_PREFIX = b"\xff\xff"
# the stored region starts at the \xff\x02 latencyProbe/client rows
STORED_SYSTEM_PREFIX = b"\xff\x02"

KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"

CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"
EXCLUDED_PREFIX = b"\xff/excluded/"
EXCLUDED_END = b"\xff/excluded0"

MGMT_RANGES = ((CONF_PREFIX, CONF_END), (EXCLUDED_PREFIX, EXCLUDED_END))

# \xff\x02/backup/ — the backup CONTROL rows (ref: the backup layer's
# config/state subspaces under \xff\x02, FileBackupAgent.actor.cpp
# config keyspace): fdbtpu-backup writes them through ordinary
# transactions; the cluster-side BackupDriver watches them and runs
# the agent. Rows: dest (container URL), state (see BACKUP_STATE_*),
# base_version, restorable_version, error.
BACKUP_PREFIX = STORED_SYSTEM_PREFIX + b"/backup/"
BACKUP_END = STORED_SYSTEM_PREFIX + b"/backup0"
BACKUP_STATE_SUBMITTED = b"submitted"
BACKUP_STATE_RUNNING = b"running"
BACKUP_STATE_ABORT = b"abort"          # requested by the tool
BACKUP_STATE_STOPPED = b"stopped"
BACKUP_STATE_ERROR = b"error"

# \xff/conf/<row> -> ClusterConfig field. The first four are
# operator-mutable (what `configure` accepts); the rest are seeded
# informational rows.
CONF_ROWS = {"proxies": "n_proxies", "resolvers": "n_resolvers",
             "logs": "n_logs", "conflict_backend": "conflict_backend",
             "usable_regions": "usable_regions",
             "storage_shards": "n_storage", "durable": "durable",
             "storage_replicas": "storage_replicas",
             "storage_engine": "storage_engine"}
CONF_MUTABLE = ("proxies", "resolvers", "logs", "conflict_backend",
                "usable_regions")
CONF_ROW_BY_FIELD = {f: row for row, f in CONF_ROWS.items()
                     if row in CONF_MUTABLE}


def is_stored_system(key: bytes) -> bool:
    """True when a \\xff key is backed by real storage rows (vs the
    materialized keyServers view)."""
    return (STORED_SYSTEM_PREFIX <= key < ENGINE_PREFIX
            and not (KEY_SERVERS_PREFIX <= key < KEY_SERVERS_END))


def is_management_mutation(m) -> bool:
    """Does this mutation touch \\xff/conf/ or \\xff/excluded/?"""
    from .types import CLEAR_RANGE
    if m.type == CLEAR_RANGE:
        return any(m.param1 < e and m.param2 > b for b, e in MGMT_RANGES)
    return any(b <= m.param1 < e for b, e in MGMT_RANGES)
