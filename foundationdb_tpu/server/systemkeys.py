"""The \\xff system keyspace schema — single source of truth.

Reference: fdbclient/SystemData.cpp (keyServers/, conf/, excluded/
prefixes and key names). Everything in [\\xff\\x02, \\xff\\xff) is real
stored data committed through the ordinary pipeline EXCEPT
\\xff/keyServers/, which is materialized from the broadcast shard map;
\\xff\\xff is engine metadata and never surfaces. The management rows
(\\xff/conf/, \\xff/excluded/) are the coordination medium: the proxy
forwards committed mutations there to the CC
(ref: ApplyMetadataMutation.h), and the CC also reconciles from the
stored rows so the keys — not the RPC — are authoritative.
"""

SYSTEM_PREFIX = b"\xff"
ENGINE_PREFIX = b"\xff\xff"
# the stored region starts at the \xff\x02 latencyProbe/client rows
STORED_SYSTEM_PREFIX = b"\xff\x02"

KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"

CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"
EXCLUDED_PREFIX = b"\xff/excluded/"
EXCLUDED_END = b"\xff/excluded0"

MGMT_RANGES = ((CONF_PREFIX, CONF_END), (EXCLUDED_PREFIX, EXCLUDED_END))

# \xff\x02/backup/ — the backup CONTROL rows (ref: the backup layer's
# config/state subspaces under \xff\x02, FileBackupAgent.actor.cpp
# config keyspace): fdbtpu-backup writes them through ordinary
# transactions; the cluster-side BackupDriver watches them and runs
# the agent. Rows: dest (container URL), state (see BACKUP_STATE_*),
# base_version, restorable_version, error.
BACKUP_PREFIX = STORED_SYSTEM_PREFIX + b"/backup/"
BACKUP_END = STORED_SYSTEM_PREFIX + b"/backup0"
BACKUP_STATE_SUBMITTED = b"submitted"
BACKUP_STATE_RUNNING = b"running"
BACKUP_STATE_ABORT = b"abort"          # requested by the tool
BACKUP_STATE_STOPPED = b"stopped"
BACKUP_STATE_ERROR = b"error"

# \xff\x02/fdbClientInfo/client_latency/ — sampled client transaction
# profiling records (ref: fdbClientInfoPrefixRange in SystemData.cpp +
# the client_latency key contract contrib/transaction_profiling_analyzer
# parses). Each sampled transaction's ClientLogEvent stream is wire-
# serialized and written in size-limited chunks:
#
#   <prefix><version>/<start_ts 16-hex us>/<rec_id 32-hex>/<chunk 4-dec>/<num 4-dec>
#
# Fixed-width ascii fields keep the keys ordered by (start time, record)
# so retention trimming is one clear_range and the analyzer's range scan
# reassembles chunk runs without sorting. `version` guards the record
# encoding: an analyzer must skip versions it does not understand.
CLIENT_LATENCY_PREFIX = STORED_SYSTEM_PREFIX + b"/fdbClientInfo/client_latency/"
CLIENT_LATENCY_END = STORED_SYSTEM_PREFIX + b"/fdbClientInfo/client_latency0"
CLIENT_LATENCY_VERSION = 1


def client_latency_key(start_ts_us: int, rec_id: str, chunk: int,
                       num_chunks: int,
                       version: int = CLIENT_LATENCY_VERSION) -> bytes:
    """One chunk's key. `chunk` is 1-based (like the reference's
    chunk-number/num-chunks suffix pair)."""
    return CLIENT_LATENCY_PREFIX + (
        b"%d/%016x/%s/%04d/%04d"
        % (version, start_ts_us, rec_id.encode(), chunk, num_chunks))


def parse_client_latency_key(key: bytes):
    """-> (version, start_ts_us, rec_id, chunk, num_chunks), or None for
    a key that is not a well-formed client_latency chunk key (the
    analyzer skips those rather than crashing on foreign rows)."""
    if not key.startswith(CLIENT_LATENCY_PREFIX):
        return None
    parts = key[len(CLIENT_LATENCY_PREFIX):].split(b"/")
    if len(parts) != 5:
        return None
    try:
        return (int(parts[0]), int(parts[1], 16), parts[2].decode(),
                int(parts[3]), int(parts[4]))
    except (ValueError, UnicodeDecodeError):
        return None


def client_latency_cutoff_key(start_ts_us: int,
                              version: int = CLIENT_LATENCY_VERSION) -> bytes:
    """First possible key at `start_ts_us` — the janitor's trim bound:
    clear_range(CLIENT_LATENCY_PREFIX + version row, this) removes every
    record that STARTED before the cutoff."""
    return CLIENT_LATENCY_PREFIX + b"%d/%016x/" % (version, start_ts_us)


# \xff\x02/throttledTags/<tag> — the tag-throttle table (ref:
# tagThrottleKeys / TagThrottleValue in fdbclient/TagThrottle.actor.cpp:
# the ratekeeper writes AUTO rows for busy tags, operators write MANUAL
# rows through `fdbcli throttle`, and every GRV proxy watches the range
# and enforces the rates). Rows are real stored data committed through
# the ordinary pipeline, so manual and automatic throttles round-trip
# through the SAME keys. Value fields (ascii, '|'-separated so `cli
# throttle list` stays greppable): tps rate, expiry (absolute cluster
# seconds), priority class throttled AT AND BELOW (0=batch, 1=default;
# immediate traffic is never tag-throttled), auto flag (1 = written by
# the ratekeeper's TagThrottler, 0 = manual).
THROTTLED_TAGS_PREFIX = STORED_SYSTEM_PREFIX + b"/throttledTags/"
THROTTLED_TAGS_END = STORED_SYSTEM_PREFIX + b"/throttledTags0"
TAG_THROTTLE_VALUE_VERSION = 1


def throttled_tag_key(tag: bytes) -> bytes:
    return THROTTLED_TAGS_PREFIX + tag


def parse_throttled_tag_key(key: bytes):
    """-> the raw tag bytes, or None for a foreign key."""
    if not (THROTTLED_TAGS_PREFIX <= key < THROTTLED_TAGS_END):
        return None
    return key[len(THROTTLED_TAGS_PREFIX):]


def encode_tag_throttle_value(tps: float, expiry: float, priority: int,
                              auto: bool) -> bytes:
    return b"%d|%.17g|%.17g|%d|%d" % (TAG_THROTTLE_VALUE_VERSION,
                                      float(tps), float(expiry),
                                      int(priority), int(bool(auto)))


def parse_tag_throttle_value(value: bytes):
    """-> (tps, expiry, priority, auto) or None for an unparseable or
    unknown-version row (readers must skip foreign encodings, the same
    contract as the client_latency records)."""
    try:
        parts = value.split(b"|")
        if len(parts) != 5 or int(parts[0]) != TAG_THROTTLE_VALUE_VERSION:
            return None
        return (float(parts[1]), float(parts[2]), int(parts[3]),
                bool(int(parts[4])))
    except (ValueError, TypeError):
        return None


# \xff\x02/timeKeeper/ — the version<->wallclock map (ref:
# fdbserver/TimeKeeper.actor.cpp writing timeKeeperPrefixRange: a CC
# actor periodically commits (time -> read version) rows through the
# ordinary pipeline so tools can translate between the two axes).
# Keys are ordered by wallclock:
#
#   <prefix><version>/<ts_ms 16-hex>
#
# with the commit version as an ascii decimal value. Fixed-width hex
# keeps the rows range-scannable in time order so `version_at_time`
# is one bounded range read and retention trimming is one clear_range.
TIMEKEEPER_PREFIX = STORED_SYSTEM_PREFIX + b"/timeKeeper/"
TIMEKEEPER_END = STORED_SYSTEM_PREFIX + b"/timeKeeper0"
TIMEKEEPER_VERSION = 1


def timekeeper_key(ts_ms: int, version: int = TIMEKEEPER_VERSION) -> bytes:
    return TIMEKEEPER_PREFIX + b"%d/%016x" % (version, ts_ms)


def parse_timekeeper_key(key: bytes):
    """-> (version, ts_ms) or None for a foreign key."""
    if not key.startswith(TIMEKEEPER_PREFIX):
        return None
    parts = key[len(TIMEKEEPER_PREFIX):].split(b"/")
    if len(parts) != 2:
        return None
    try:
        return (int(parts[0]), int(parts[1], 16))
    except ValueError:
        return None


def timekeeper_cutoff_key(ts_ms: int,
                          version: int = TIMEKEEPER_VERSION) -> bytes:
    """First possible key at `ts_ms` — clear_range(PREFIX + version row,
    this) removes every map entry older than the cutoff."""
    return TIMEKEEPER_PREFIX + b"%d/%016x" % (version, ts_ms)


# \xff\x02/metrics/<signal>/<ts> — persisted metric history (the
# longitudinal twin of the status doc: the CC's recorder samples the
# signals status already computes and commits them through the
# ordinary pipeline, the same "metrics keyspace" idiom the reference
# uses for latency-band and DD metrics). Series rows are CHUNKED like
# the client_latency records — each row holds METRIC_HISTORY_CHUNK
# consecutive samples delta-encoded against the chunk's base — and
# each chunk is self-contained, so retention trimming stays one
# clear_range per signal and a partial read still decodes.
#
#   <prefix><version>/<signal ascii>/<first_ts_ms 16-hex>
#
# Value (ascii, '|'-separated like the tag-throttle rows):
#
#   <version>|<base_ts_ms>|<base_value>|<dt:dv,dt:dv,...>
#
# where (dt, dv) are per-sample deltas against the PREVIOUS sample.
# Values are integers (fixed-point: float signals are stored x1000).
METRIC_HISTORY_PREFIX = STORED_SYSTEM_PREFIX + b"/metrics/"
METRIC_HISTORY_END = STORED_SYSTEM_PREFIX + b"/metrics0"
METRIC_HISTORY_VERSION = 1


def metric_history_key(signal: str, first_ts_ms: int,
                       version: int = METRIC_HISTORY_VERSION) -> bytes:
    return METRIC_HISTORY_PREFIX + (
        b"%d/%s/%016x" % (version, signal.encode(), first_ts_ms))


def parse_metric_history_key(key: bytes):
    """-> (version, signal, first_ts_ms) or None for a foreign key.
    Signals may themselves contain '/' (e.g. latency/commit/p99_ms), so
    the timestamp is split off the RIGHT."""
    if not key.startswith(METRIC_HISTORY_PREFIX):
        return None
    rest = key[len(METRIC_HISTORY_PREFIX):]
    head, sep, ts = rest.rpartition(b"/")
    ver, sep2, signal = head.partition(b"/")
    if not sep or not sep2 or not signal:
        return None
    try:
        return (int(ver), signal.decode(), int(ts, 16))
    except (ValueError, UnicodeDecodeError):
        return None


def metric_history_signal_prefix(signal: str,
                                 version: int = METRIC_HISTORY_VERSION) -> bytes:
    return METRIC_HISTORY_PREFIX + b"%d/%s/" % (version, signal.encode())


def metric_history_cutoff_key(signal: str, first_ts_ms: int,
                              version: int = METRIC_HISTORY_VERSION) -> bytes:
    """Trim bound for one signal's series: clear_range(signal prefix,
    this) removes every chunk whose FIRST sample is older than the
    cutoff (a chunk straddling the cutoff survives whole — chunks are
    self-contained, so readers just filter samples by timestamp)."""
    return metric_history_key(signal, first_ts_ms, version)


def encode_metric_chunk(samples) -> bytes:
    """samples: non-empty [(ts_ms, int_value), ...] in time order."""
    base_ts, base_v = samples[0]
    deltas = []
    prev_ts, prev_v = base_ts, base_v
    for ts, v in samples[1:]:
        deltas.append(b"%d:%d" % (ts - prev_ts, v - prev_v))
        prev_ts, prev_v = ts, v
    return b"%d|%d|%d|%s" % (METRIC_HISTORY_VERSION, base_ts, base_v,
                             b",".join(deltas))


def decode_metric_chunk(value: bytes):
    """-> [(ts_ms, int_value), ...] or None for a foreign/unknown-version
    row (readers skip those — the client_latency contract)."""
    try:
        parts = value.split(b"|")
        if len(parts) != 4 or int(parts[0]) != METRIC_HISTORY_VERSION:
            return None
        ts, v = int(parts[1]), int(parts[2])
        out = [(ts, v)]
        if parts[3]:
            for pair in parts[3].split(b","):
                dt, dv = pair.split(b":")
                ts += int(dt)
                v += int(dv)
                out.append((ts, v))
        return out
    except (ValueError, TypeError):
        return None


# \xff/conf/<row> -> ClusterConfig field. The first four are
# operator-mutable (what `configure` accepts); the rest are seeded
# informational rows.
CONF_ROWS = {"proxies": "n_proxies", "resolvers": "n_resolvers",
             "logs": "n_logs", "conflict_backend": "conflict_backend",
             "usable_regions": "usable_regions",
             "storage_shards": "n_storage", "durable": "durable",
             "storage_replicas": "storage_replicas",
             "storage_engine": "storage_engine"}
CONF_MUTABLE = ("proxies", "resolvers", "logs", "conflict_backend",
                "usable_regions")
# every recruitable conflict-set backend — defined ONCE next to its
# authority (models.create_conflict_set) and re-exported here for the
# server-side config validators; the client's configure validation
# imports the same tuple, so a new backend cannot be half-supported
from ..models.native_backend import CONFLICT_BACKENDS  # noqa: F401,E402
CONF_ROW_BY_FIELD = {f: row for row, f in CONF_ROWS.items()
                     if row in CONF_MUTABLE}


def is_stored_system(key: bytes) -> bool:
    """True when a \\xff key is backed by real storage rows (vs the
    materialized keyServers view)."""
    return (STORED_SYSTEM_PREFIX <= key < ENGINE_PREFIX
            and not (KEY_SERVERS_PREFIX <= key < KEY_SERVERS_END))


def is_management_mutation(m) -> bool:
    """Does this mutation touch \\xff/conf/ or \\xff/excluded/?"""
    from .types import CLEAR_RANGE
    if m.type == CLEAR_RANGE:
        return any(m.param1 < e and m.param2 > b for b, e in MGMT_RANGES)
    return any(b <= m.param1 < e for b, e in MGMT_RANGES)
