"""Simulation-time invariant validation.

Reference: fdbserver/sim_validation.cpp — debug hooks the simulator
checks continuously (committed-version monotonicity, recovery
uniqueness), failing the run the moment an invariant breaks rather
than when a workload later trips over the damage. Here a validator
actor rides every SimCluster, re-checking the published cluster
picture on each broadcast; the checks run in EVERY simulation test by
default, so a regression anywhere in recovery/DD/recruitment surfaces
at its source.
"""

from __future__ import annotations

from .. import flow


def validate_dbinfo(info, seen_state: dict) -> None:
    """Invariants of one published ServerDBInfo; `seen_state` carries
    cross-broadcast state (monotone sequences). Raises AssertionError
    with a precise message on violation."""
    # broadcast sequence strictly increases
    last_seq = seen_state.get("seq", -1)
    assert info.seq > last_seq, (
        f"dbinfo seq went backwards: {last_seq} -> {info.seq}")
    seen_state["seq"] = info.seq
    # epochs never regress
    last_epoch = seen_state.get("epoch", -1)
    assert info.epoch >= last_epoch, (
        f"epoch went backwards: {last_epoch} -> {info.epoch}")
    seen_state["epoch"] = info.epoch

    if info.storages:
        # the shard map covers the keyspace contiguously
        assert info.storages[0].begin == b"", (
            f"shard map does not start at empty key: "
            f"{info.storages[0].begin!r}")
        assert info.storages[-1].end is None, (
            f"shard map does not end at +inf: {info.storages[-1].end!r}")
        for i in range(len(info.storages) - 1):
            assert info.storages[i].end == info.storages[i + 1].begin, (
                f"shard map gap/overlap at {i}: "
                f"{info.storages[i].end!r} vs "
                f"{info.storages[i + 1].begin!r}")
        # tags are unique; every shard has at least one replica whose
        # advertised bounds match the shard's
        tags = [s.tag for s in info.storages]
        assert len(set(tags)) == len(tags), f"duplicate shard tags: {tags}"
        for s in info.storages:
            assert s.replicas, f"shard tag {s.tag} has no replicas"
            for rep in s.replicas:
                assert rep.begin == s.begin and rep.end == s.end, (
                    f"replica {rep.name} bounds {rep.begin!r}..{rep.end!r}"
                    f" diverge from shard {s.begin!r}..{s.end!r}")

    # old generations precede the current one and are properly closed
    for gen in info.old_logs:
        assert gen.epoch < info.logs.epoch, (
            f"old generation {gen.epoch} not before current "
            f"{info.logs.epoch}")
        assert gen.end_version >= 0, (
            f"old generation {gen.epoch} still open")


async def validator(dbinfo_var, seen: dict) -> None:
    """Actor: re-validate on every broadcast (attach via SimCluster).
    `seen` is caller-owned so tests can assert THIS validator observed
    their broadcasts; a violation error is surfaced by SimCluster.run,
    not swallowed in the detached task."""
    while True:
        info = dbinfo_var.get()
        if info.seq > seen.get("seq", -1):
            validate_dbinfo(info, seen)
            seen["checked"] = seen.get("checked", 0) + 1
            flow.cover("sim_validation.checked")
        await dbinfo_var.on_change()
