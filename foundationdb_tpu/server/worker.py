"""Worker: hosts role actors on a process, recovers disk stores on
reboot, registers with the ClusterController.

Reference: fdbserver/worker.actor.cpp — `workerServer` (:613) scans the
data folder on boot and re-creates roles from surviving disk stores
(tlog queues come back *stopped*, ready to be locked and drained by the
next recovery; storage servers rejoin and resume pulling), then
registers with the CC (registrationClient :347) and serves recruitment
requests. Role construction here is a direct method call guarded by a
liveness check — the simulated stand-in for the recruitment RPC; the
registration itself travels over the simulated network so a rebooted
worker re-appears the same way a real one would.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .. import flow
from ..flow import TaskPriority, error
from ..rpc import RequestStream, SimProcess
from .dbinfo import LogRefs, ProxyRefs, StorageRefs
from .kvstore import EphemeralKeyValueStore, KeyValueStoreMemory
from .master import Master
from .proxy import Proxy
from .resolver_role import Resolver
from .storage import (SHARD_META_KEY, StorageServer,
                      decode_shard_meta)
from .tlog import TLog

class RegisterWorkerRequest(NamedTuple):
    name: str
    machine: str
    worker: object                      # the Worker (sim recruitment seam)
    recovered_logs: Tuple[LogRefs, ...]
    recovered_storages: Tuple[StorageRefs, ...] = ()


class Worker:
    def __init__(self, process: SimProcess, net, durable: bool = False,
                 dbinfo=None, conflict_backend: str = "python",
                 storage_lag_versions: Optional[int] = None,
                 storage_engine: str = "memory"):
        self.process = process
        self.net = net
        self.durable = durable
        self.dbinfo = dbinfo            # AsyncVar[ServerDBInfo]
        self.conflict_backend = conflict_backend
        self.storage_lag_versions = storage_lag_versions
        self.storage_engine = storage_engine
        self.roles: dict = {}           # name -> role object
        self.pings = RequestStream(process)
        self._actors = flow.ActorCollection()

    # -- liveness --------------------------------------------------------
    def _check_alive(self) -> None:
        if not self.process.alive:
            raise error("broken_promise")

    def start(self) -> None:
        self._actors.add(flow.spawn(self._ping_loop(), TaskPriority.CLUSTER_CONTROLLER,
                                    name=f"{self.process.name}.ping"))
        self.process.on_kill(self._actors.cancel_all)

    async def _ping_loop(self):
        while True:
            _req, reply = await self.pings.pop()
            reply.send(None)

    # -- boot-time disk-store recovery ----------------------------------
    async def recover_stores(self):
        """Re-create roles from surviving disk stores (ref: worker boot
        store scan). TLogs come back stopped; storage servers rejoin
        live. Returns (recovered_logs, recovered_storages).

        A store whose recovery DETECTS corruption (checksum_failed /
        io_error) is treated as lost, not fatal: the files are removed
        so the next reboot cannot trip on them again, and the worker
        registers without it — replication heals the hole (DD rebuilds
        the replica; a log generation recovers from its surviving
        peers). Detected corruption is thus a recoverable role death;
        UNDETECTED corruption is check_consistency's job."""
        recovered_logs = []
        recovered_storages = []
        if self.durable:
            disk = self.net.disk(self.process.machine)
            for store in sorted(disk.files):
                try:
                    if store.startswith("tlog-") and store.endswith(".dq0"):
                        name = store[:-4]
                        tlog = self._make_tlog(name)
                        tlog.stopped = True      # old-generation data only
                        tlog.start()
                        await tlog.recovered()
                        recovered_logs.append(self._log_refs(name, tlog))
                    elif store.startswith("storage-") and \
                            store.endswith(".dq0"):
                        refs = await self._recover_storage(store[:-4],
                                                           "memory")
                        if refs is not None:
                            recovered_storages.append(refs)
                    elif store.startswith("storage-") and \
                            store.endswith(".btree"):
                        refs = await self._recover_storage(store[:-6],
                                                           "btree")
                        if refs is not None:
                            recovered_storages.append(refs)
                except flow.FdbError as e:
                    if e.name not in ("checksum_failed", "io_error"):
                        raise
                    self._drop_corrupt_store(disk, store, e)
        return tuple(recovered_logs), tuple(recovered_storages)

    def _drop_corrupt_store(self, disk, store: str, e) -> None:
        """Detected on-disk corruption: destroy the store and carry on
        (the recoverable-role-death contract of the chaos plane)."""
        base = store.rsplit(".", 1)[0]
        flow.cover("worker.corrupt_store_dropped")
        flow.TraceEvent("WorkerCorruptStoreLost", self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
            Store=base, Error=e.name).log()
        self.net.chaos_note("corrupt_store_lost", store=base,
                            machine=self.process.machine)
        role = self.roles.pop(base, None)
        if role is not None:
            role._actors.cancel_all()
        for f in [f for f in disk.files if f.startswith(base + ".")]:
            disk.remove(f)

    async def _recover_storage(self, name: str, engine: str):
        kv = self._make_engine(name, engine)
        await kv.recover()
        meta = kv.get(SHARD_META_KEY)
        if meta is None:
            return None
        tag, begin, end, floors = decode_shard_meta(meta)
        return self.recruit_storage(name, tag, begin, end, kv=kv,
                                    floors=floors)

    # -- recruitment (CC-driven) ----------------------------------------
    def _make_tlog(self, store: str, recovery_version: int = 0) -> TLog:
        disk = self.net.disk(self.process.machine) if self.durable else None
        return TLog(self.process, disk=disk, name=store,
                    recovery_version=recovery_version)

    def _log_refs(self, store: str, tlog: TLog) -> LogRefs:
        return LogRefs(store, self.process.machine, tlog.commits.ref(),
                       tlog.peeks.ref(), tlog.pops.ref(), tlog.locks.ref())

    def recruit_tlog(self, store: str, recovery_version: int = 0) -> LogRefs:
        """(ref: InitializeTLogRequest handling in workerServer)"""
        self._check_alive()
        tlog = self._make_tlog(store, recovery_version)
        tlog.start()
        self.roles[store] = tlog
        return self._log_refs(store, tlog)

    def recruit_resolver(self, name: str, recovery_version: int,
                         backend: Optional[str] = None):
        """Returns (resolves_ref, metrics_ref, handoffs_ref)."""
        self._check_alive()
        r = Resolver(self.process, backend=backend or self.conflict_backend,
                     recovery_version=recovery_version)
        r.start()
        self.roles[name] = r
        return r.resolves.ref(), r.metrics.ref(), r.handoffs.ref()

    def recruit_proxy(self, name: str, master_ref, resolver_refs, tlog_refs,
                      resolver_splits, storage_splits,
                      recovery_version: int,
                      ratekeeper_ref=None, storage_tags=None,
                      management_ref=None) -> ProxyRefs:
        self._check_alive()
        p = Proxy(self.process, master_ref, resolver_refs, tlog_refs,
                  resolver_splits=resolver_splits,
                  storage_splits=storage_splits,
                  storage_tags=storage_tags,
                  recovery_version=recovery_version,
                  ratekeeper_ref=ratekeeper_ref,
                  management_ref=management_ref,
                  # transaction repair re-reads invalidated ranges
                  # straight from storage via the broadcast shard map
                  dbinfo=self.dbinfo)
        p.start()
        self.roles[name] = p
        return ProxyRefs(name, p.grvs.ref(), p.commits.ref(),
                         p.raw_committed.ref())

    def recruit_ratekeeper(self, name: str, cc):
        """(ref: the CC recruiting the ratekeeper singleton)"""
        self._check_alive()
        from .ratekeeper import Ratekeeper
        rk = Ratekeeper(self.process, cc)
        rk.start()
        self.roles[name] = rk
        return rk.get_rate.ref()

    def recruit_master(self, name: str, recovery_version: int) -> Master:
        self._check_alive()
        m = Master(self.process, recovery_version=recovery_version)
        m.start()
        self.roles[name] = m
        return m

    def _make_engine(self, name: str, engine: Optional[str] = None):
        """(ref: the KeyValueStoreType choice in IKeyValueStore.h)"""
        engine = engine or self.storage_engine
        disk = self.net.disk(self.process.machine)
        if engine == "btree":
            from .btree import KeyValueStoreBTree
            return KeyValueStoreBTree(disk, name, owner=self.process)
        return KeyValueStoreMemory(disk, name, owner=self.process)

    def recruit_storage(self, name: str, tag: int, begin: bytes,
                        end: Optional[bytes], kv=None,
                        floors=()) -> StorageRefs:
        self._check_alive()
        if kv is None:
            if self.durable:
                kv = self._make_engine(name)
            else:
                kv = EphemeralKeyValueStore()
        s = StorageServer(self.process, None, kv=kv, tag=tag,
                          durability_lag_versions=self.storage_lag_versions,
                          dbinfo=self.dbinfo, shard_begin=begin,
                          shard_end=end, floors=floors, name=name)
        s.start()
        self.roles[name] = s
        refs = StorageRefs(name, tag, begin, end, s.gets.ref(),
                           s.ranges.ref(), s.get_keys.ref(), s.watches.ref())
        return refs

    def retire_storage(self, name: str) -> None:
        """Tear down a storage role whose data has been moved away
        (ref: the storage server removal path once DD vacates it —
        actors end and the store files are destroyed, so a reboot
        cannot resurrect the stale ownership)."""
        obj = self.roles.pop(name, None)
        if obj is not None:
            obj.retire()
        if self.durable:
            disk = self.net.disk(self.process.machine)
            for f in [f for f in disk.files
                      if f.startswith(name + ".")]:
                disk.remove(f)

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
RegisterWorkerRequest.__no_wire__ = True  # carries the recruitment seam
