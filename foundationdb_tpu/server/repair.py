"""Server-side transaction repair: partial re-execution of invalidated
reads at the proxy, committing a conflicted transaction without a
client round trip.

Reference: *Transaction Repair: Full Serializability Without Locks*
(arXiv:1403.5645) and *Repairing Conflicts among MVCC Transactions*
(PAPERS.md) — when a conflict check can say WHICH reads were
invalidated, a transaction whose writes do not depend on the read
values need not abort: re-execute only the invalidated reads at a
newer snapshot and revalidate, instead of throwing the whole
transaction away.

The repairability contract (client-declared via
`set_option("automatic_repair")`, enforced server-side where
verifiable):

- declared read-set: every read records a read-conflict range (the
  default for non-snapshot reads), so the resolver's per-read-slot
  cause mask (PR 2) names exactly the invalidated reads;
- value-independent writes: the mutation list must not be a function
  of the read values (atomic ops, blind sets/clears, versionstamped
  ops). The server verifies the mutation TYPES are in
  REPAIRABLE_MUTATIONS; value-independence of SET operands is the
  client's declaration — a client that computes a set value from a
  read must not arm the option.

Why the repaired commit is bit-exact with a from-scratch re-execution:
a repairable transaction's effects are exactly its (value-independent)
mutation list, so re-executing it at ANY fresh snapshot produces the
identical mutations — repair resubmits those mutations through the
ORDINARY commit path at a refreshed snapshot (the proxy's committed
version, i.e. what a client retry's GRV would return), with the
invalidated ranges re-read at that version server-side (evidence
recorded in `repair_reread_rows`) standing in for the retry's reads.
The resolver revalidates the full read set over (new_snapshot,
new_commit], so serializability is enforced by the same machinery as
any fresh transaction (and stays pinned by check_consistency and
PR 5's shadow validation under the new paths).

Repairs SERIALIZE per invalidated range (a FlowLock chain): when a
whole batch of rivals conflicts on one hot key, their repairs run one
at a time, each resubmitting only after its predecessor's outcome is
known and with a snapshot covering it — without this, the herd's
resubmissions land in one batch, re-race, and burn their attempt
budgets losing to each other (measured: 95% re-conflict).

Everything else — non-repairable transactions, missing attribution,
re-read failures, attempt/in-flight budget exhaustion — falls back to
the abort the client would have seen anyway. TXN_REPAIR=0 (default)
disables the whole plane.
"""

from __future__ import annotations

from .. import flow
from ..flow import SERVER_KNOBS, TaskPriority, error
from .types import (ATOMIC_OPS, CLEAR_RANGE, INERT_OPS, SET_VALUE,
                    SET_VERSIONSTAMPED_KEY, SET_VERSIONSTAMPED_VALUE,
                    CommitConflictReply, StorageGetRangeRequest)

# mutation types that cannot encode a read value the server can't see
# folded in is the CLIENT's promise; these are the types for which the
# promise is even coherent (versionstamped ops re-stamp at the new
# version exactly as a re-execution would)
REPAIRABLE_MUTATIONS = (ATOMIC_OPS | INERT_OPS
                        | frozenset({SET_VALUE, CLEAR_RANGE,
                                     SET_VERSIONSTAMPED_KEY,
                                     SET_VERSIONSTAMPED_VALUE}))


def repair_eligible(req, ranges) -> bool:
    """Can this conflicted transaction be repaired? Requires the client
    declaration, attribution naming the invalidated reads, a remaining
    attempt budget, and a verifiably value-independent mutation
    vocabulary."""
    if not getattr(req, "repairable", False):
        return False
    if getattr(req, "repair_attempt", 0) >= \
            int(SERVER_KNOBS.repair_max_attempts):
        return False
    if not ranges:
        return False     # no cause mask -> cause unknown -> abort
    if not req.mutations:
        return False
    return all(m.type in REPAIRABLE_MUTATIONS for m in req.mutations)


def _overlapping_shards(storages, begin: bytes, end: bytes):
    out = []
    for s in storages:
        if (s.end is None or begin < s.end) and s.begin < end:
            out.append(s)
    return out


class RepairManager:
    """The proxy's repair engine. `try_repair` captures a conflicted
    (req, reply) pair; the repair actor re-reads the invalidated
    ranges at the conflict version, bumps the read snapshot, and
    resubmits through the proxy's own commit stream — the client's
    reply future answers only with the FINAL outcome (a repaired
    CommitReply, or the abort it would have seen anyway). Counters
    live in the owning proxy's CounterCollection (`repair_*`)."""

    def __init__(self, process, dbinfo, commits, stats, actors,
                 committed_version=None, account=None):
        self.process = process
        self.dbinfo = dbinfo        # AsyncVar[ServerDBInfo] or None
        self._commits = commits     # the proxy's commit RequestStream
        self.stats = stats
        self._actors = actors       # the proxy's ActorCollection
        self._committed = committed_version   # proxy NotifiedVersion
        # conflict-accounting hook for terminal aborts WE deliver:
        # phase 5 skips accounting when it hands a conflict to repair,
        # so a fallback abort must restore it or QoS rates undercount
        self._account = account
        #: per-range repair chains: rivals conflicting on the same hot
        #: range repair ONE AT A TIME (see module docstring)
        self._locks: dict = {}
        self._in_flight = 0

    def try_repair(self, req, reply, version: int, ranges) -> bool:
        """True when the conflicted transaction was captured for
        repair (the caller must NOT answer the reply); False means
        fall back to the ordinary abort."""
        k = SERVER_KNOBS
        if not k.txn_repair:
            return False
        if not repair_eligible(req, ranges):
            return False
        if self._in_flight >= int(k.repair_max_inflight):
            flow.cover("repair.shed")
            self.stats.counter("repair_shed").add(1)
            return False
        flow.cover("repair.attempt")
        self._in_flight += 1
        self.stats.counter("repair_attempts").add(1)
        self.stats.counter("repair_in_flight").set(self._in_flight)
        self._actors.add(flow.spawn(
            self._repair(req, reply, version, tuple(ranges)),
            TaskPriority.PROXY_COMMIT,
            name=f"{self.process.name}.repair"))
        return True

    def _range_lock(self, key) -> "flow.FlowLock":
        lock = self._locks.get(key)
        if lock is None:
            lock = self._locks[key] = flow.FlowLock()
        return lock

    def _drop_lock_if_idle(self, key, lock) -> None:
        if lock.active == 0 and not lock._waiters:
            self._locks.pop(key, None)

    async def _repair(self, req, reply, version: int, ranges) -> None:
        submitted = False
        lock_key = None
        lock = None
        held = False
        try:
            budget = int(SERVER_KNOBS.repair_max_attempts)
            attempt = 0
            while True:
                attempt += 1
                # 0. serialize per hot range: resubmit only once the
                # predecessor's outcome is known (and below our
                # snapshot), or a conflicted batch's worth of rivals
                # re-races itself. THIS actor owns every retry round —
                # resubmissions are never re-captured by the proxy (a
                # nested repair would queue behind this very lock
                # while we await its outcome: deadlock until the
                # client timeout). A re-conflict on a DIFFERENT range
                # re-keys the chain (release-then-take, so there is no
                # hold-and-wait): serialization follows the range that
                # is actually aborting this round.
                if ranges[0] != lock_key:
                    if held:
                        lock.release()
                        self._drop_lock_if_idle(lock_key, lock)
                        held = False
                    lock_key = ranges[0]
                    lock = self._range_lock(lock_key)
                    await lock.take()
                    held = True
                # a client retry's GRV would return at least the
                # current committed version — the repaired
                # re-execution gets the same fresh snapshot (covers
                # every predecessor's commit)
                if self._committed is not None:
                    version = max(version, self._committed.get())
                # 1. partial re-execution: re-read ONLY the
                # invalidated ranges at the new snapshot (bounded; a
                # failure here is the designed fallback seam — nothing
                # was committed, so the ordinary abort is honest)
                try:
                    rows = await flow.timeout_error(
                        flow.spawn(self._reread(ranges, version),
                                   TaskPriority.PROXY_COMMIT),
                        float(SERVER_KNOBS.repair_read_timeout))
                except flow.FdbError as e:
                    if e.name == "operation_cancelled":
                        raise
                    flow.cover("repair.reread_failed")
                    self.stats.counter("repair_fallbacks").add(1)
                    self._send_abort(req, reply, ranges)
                    return
                self.stats.counter("repair_reread_rows").add(rows)
                # 2. revalidate + commit: resubmit at the fresh
                # snapshot. report_conflicting_keys is forced on so a
                # re-conflict comes back as a VALUE carrying the new
                # cause mask for the next round's re-read. The
                # resolver revalidates the whole read set past the new
                # snapshot — an ordinary commit of the equivalent
                # from-scratch re-execution.
                new_req = req._replace(
                    read_snapshot=version, repair_attempt=attempt,
                    report_conflicting_keys=True)
                submitted = True
                out = await flow.timeout_error(
                    self._commits.ref().get_reply(new_req, self.process),
                    float(SERVER_KNOBS.client_request_timeout))
                if not isinstance(out, CommitConflictReply):
                    flow.cover("repair.committed")
                    self.stats.counter("repair_committed").add(1)
                    reply.send(out)
                    return
                # conflicted again: next round re-reads the FRESH
                # attribution (falling back to the original mask when
                # the new one is empty), until the budget runs out
                flow.cover("repair.reconflicted")
                if attempt >= budget:
                    self.stats.counter("repair_conflicted").add(1)
                    if getattr(req, "report_conflicting_keys", False):
                        reply.send(out)
                    else:
                        reply.send_error(error("not_committed"))
                    return
                ranges = tuple(out.conflicting_ranges) or ranges
                submitted = False
        except flow.FdbError as e:
            if e.name == "operation_cancelled":
                # torn down mid-repair (epoch over): the client must
                # see a retryable failure, never our own cancellation
                self._fail(reply, submitted)
                raise
            if e.name in ("not_committed", "transaction_too_old"):
                # definite non-commits: forward as-is (both retryable;
                # masking a known outcome as commit_unknown_result
                # would force the client to settle a result we know)
                self.stats.counter("repair_conflicted").add(1)
                reply.send_error(e)
            elif submitted:
                # the resubmission's outcome is unknown (timeout /
                # broken downstream): the client must settle it, same
                # as any in-flight commit losing its proxy
                self.stats.counter("repair_failed").add(1)
                reply.send_error(error("commit_unknown_result"))
            else:
                self.stats.counter("repair_fallbacks").add(1)
                self._send_abort(req, reply, ranges)
        except BaseException:
            self._fail(reply, submitted)
            raise
        finally:
            if held:
                lock.release()
                self._drop_lock_if_idle(lock_key, lock)
            self._in_flight -= 1
            self.stats.counter("repair_in_flight").set(self._in_flight)

    def _send_abort(self, req, reply, ranges=()) -> None:
        """The abort the client would have seen without repair — a
        reporting client keeps the attributed ranges we already hold,
        and the conflict is accounted exactly as phase 5 would have."""
        if self._account is not None:
            self._account(req)
        try:
            if getattr(req, "report_conflicting_keys", False):
                reply.send(CommitConflictReply(tuple(ranges)))
            else:
                reply.send_error(error("not_committed"))
        except Exception:
            pass  # already answered

    @staticmethod
    def _fail(reply, submitted: bool) -> None:
        try:
            reply.send_error(error(
                "commit_unknown_result" if submitted
                else "broken_promise"))
        except Exception:
            pass

    async def _reread(self, ranges, version: int) -> int:
        """Re-read the invalidated read ranges at `version` straight
        from storage (bounded rows per range). The read waits for
        storage to reach the commit version, exactly like a client
        read at that snapshot. Returns the row count (the re-read is
        what makes the repaired commit a genuine partial re-execution
        rather than a blind resubmit; its failure path is the
        designed fall-back-to-abort seam)."""
        info = self.dbinfo.get() if self.dbinfo is not None else None
        if info is None or not info.storages:
            return 0
        limit = int(SERVER_KNOBS.repair_reread_rows)
        total = 0
        for b, e in ranges[:16]:    # bound work per repaired txn
            for s in _overlapping_shards(info.storages, b, e):
                b2 = max(b, s.begin)
                e2 = e if s.end is None else min(e, s.end)
                if b2 >= e2 or not s.replicas:
                    continue
                rep = s.replicas[0]
                rows = await rep.ranges.get_reply(
                    StorageGetRangeRequest(b2, e2, version, limit),
                    self.process)
                total += len(rows)
        return total

    def status(self) -> dict:
        snap = self.stats.snapshot()
        return {
            "enabled": int(bool(SERVER_KNOBS.txn_repair)),
            "attempts": snap.get("repair_attempts", 0),
            "committed": snap.get("repair_committed", 0),
            "conflicted": snap.get("repair_conflicted", 0),
            "failed": snap.get("repair_failed", 0),
            "fallbacks": snap.get("repair_fallbacks", 0),
            "shed": snap.get("repair_shed", 0),
            "reread_rows": snap.get("repair_reread_rows", 0),
            "in_flight": self._in_flight,
        }
