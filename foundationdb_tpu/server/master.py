"""Master role: commit-version authority + the epoch recovery state
machine.

Reference: fdbserver/masterserver.actor.cpp —
  - `getVersion` (:875-940): versions advance with real time
    (`version += VERSIONS_PER_SECOND * dt`, capped per request) so a
    version is also a coarse clock; each batch receives
    (prev_version, version) so downstream stages sequence without gaps.
  - `masterCore` (:1212): the recovery phases — read the coordinated
    state, end the previous epoch by locking its logs
    (TagPartitionedLogSystem.actor.cpp:1265 epochEnd), recruit a new
    transaction subsystem (recruitEverything :537), commit the new core
    state exclusively (a competing newer master makes the write fail
    with coordinated_state_conflict), broadcast the new ServerDBInfo,
    and prove the pipeline live with a recovery transaction before
    declaring FULLY_RECOVERED.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .. import flow
from ..flow import TaskPriority, error
from ..rpc import RequestStream, SimProcess
from . import dbinfo as dbi
from .dbinfo import LogSetInfo, ServerDBInfo
from .types import (RESOLUTION_METRICS_REQUEST, CommitRequest,
                    ResolverCheckpointRequest, ResolverInstallRequest,
                    TLogLockRequest)



class GetCommitVersionReply(NamedTuple):
    prev_version: int
    version: int
    # keyResolvers moves this proxy has not yet applied, each
    # (effective_version, begin, end_or_None, to_idx) — moves ride the
    # version chain, so every proxy applies a move at the SAME version
    # (ref: the reference versioning keyResolvers through the commit
    # stream, MasterProxyServer.actor.cpp:204 + ApplyMetadataMutation)
    moves: tuple = ()


class CoreState(NamedTuple):
    """What survives in the coordinated state (ref: DBCoreState,
    fdbserver/DBCoreState.h — enough to find and lock the previous
    epoch's logs after any set of failures)."""

    epoch: int
    recovery_version: int                 # first version of this epoch
    logs: Tuple[Tuple[str, str], ...]     # (store name, machine)
    old_logs: Tuple[Tuple[int, int, int, Tuple[Tuple[str, str], ...]], ...]
    # ^ (epoch, begin_version, end_version, stores) still draining
    # the attached remote region's log store (store, machine) — what an
    # explicitly promoted controller locks when no primary log survives
    # a region blackout (ref: DBCoreState's remote/satellite tLog sets
    # enabling epochEnd with remote logs,
    # TagPartitionedLogSystem.actor.cpp:1265)
    region_logs: Tuple[Tuple[str, str], ...] = ()


def initial_resolver_splits(n_resolvers: int) -> Tuple[bytes, ...]:
    """The recruitment-time keyspace partition across resolvers: even
    first-byte buckets. THE formula — recruitment and the gateway's
    peer-describe document (whose out-of-process proxies rebuild the
    live keyResolvers map by replaying the move log onto these splits)
    must agree, or remote proxies would clip conflict ranges against a
    different base map than in-cluster ones."""
    return tuple(bytes([(i * 256) // n_resolvers])
                 for i in range(1, n_resolvers))


class Master:
    """The version authority (one per epoch)."""

    def __init__(self, process: SimProcess, recovery_version: int = 0):
        self.process = process
        self.version = recovery_version
        self._last_time = None
        # keyResolvers move log for this epoch: every version reply
        # piggybacks the tail a proxy has not seen yet
        self.resolver_moves: list = []
        self.version_requests = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._version_loop(),
                                    TaskPriority.PROXY_GET_CONSISTENT_READ_VERSION,
                                    name=f"{self.process.name}.getVersion"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()

    def _next_version(self) -> GetCommitVersionReply:
        t = flow.now()
        if self._last_time is None:
            advance = 1
        else:
            advance = max(1, min(
                flow.SERVER_KNOBS.max_version_advance,
                int(flow.SERVER_KNOBS.versions_per_second
                    * (t - self._last_time))))
        self._last_time = t
        prev = self.version
        self.version = prev + advance
        return GetCommitVersionReply(prev, self.version)

    def register_move(self, begin: bytes, end, to_idx: int) -> int:
        """Stamp a keyResolvers move with the version chain: effective
        from the NEXT version this authority hands out, so every batch
        either wholly precedes or wholly follows the move on every
        proxy — no cross-proxy apply skew by construction."""
        effective = self.version + 1
        self.resolver_moves.append((effective, begin, end, to_idx))
        return effective

    def register_release(self, begin: bytes, end, from_idx: int) -> int:
        """Stamp an early FORMER-OWNER release (ISSUE 15's live
        handoff): once the donor's clipped state is installed on the
        new owner, the window's double delivery is redundant — every
        proxy drops `from_idx` from [begin, end)'s owner history for
        batches at/above the effective version. Safe because the
        effective version is > the donor version the piece was cut at
        (versions originate here, so the donor can never be ahead of
        this authority), and the recipient's grafted state is exact
        for every batch above that."""
        effective = self.version + 1
        self.resolver_moves.append((effective, begin, end, from_idx,
                                    "release"))
        return effective

    async def _version_loop(self):
        while True:
            req, reply = await self.version_requests.pop()
            # the request IS the caller's applied-move count; anything
            # else is protocol misuse and should fail loudly, not
            # silently re-deliver the whole move log
            assert isinstance(req, int), req
            seen = req
            ver = self._next_version()
            if len(self.resolver_moves) > seen:
                ver = ver._replace(
                    moves=tuple(self.resolver_moves[seen:]))
            reply.send(ver)


class MasterRecovery:
    """One epoch's recovery attempt + lifetime (ref: masterCore)."""

    def __init__(self, process: SimProcess, cc, cstate, config):
        self.process = process
        self.cc = cc                      # ClusterController (registry)
        self.cstate = cstate              # CoordinatedState client
        self.config = config
        self.master: Optional[Master] = None
        self.epoch = 0
        # processes whose death ends this epoch (ref: the master's
        # waitFailure clients on proxies/resolvers/tlogs)
        self.critical_procs: set = set()
        self.aux = flow.ActorCollection()  # epoch-lifetime helper actors

    def _trace(self, event: str, **details) -> None:
        flow.TraceEvent(event, self.process.name).detail(**details).log()

    async def run(self) -> None:
        """Drive recovery to FULLY_RECOVERED, then serve versions until
        cancelled (the CC cancels us and starts a successor on
        failure)."""
        cfg = self.config

        # Phase 1: read the coordinated state (ref: masterCore phase
        # READING_CSTATE via ReusableCoordinatedState)
        self._set_state(dbi.READING_CSTATE)
        prev: Optional[CoreState] = await self.cstate.read()

        # Phase 2: end the previous epoch — lock its logs and find the
        # recovery version (ref: epochEnd)
        recovery_version = 0
        old_log_sets: Tuple[LogSetInfo, ...] = ()
        if prev is not None and self.cc.takeover_from_region \
                and prev.region_logs:
            # explicit region failover (ref: forced recovery from the
            # remote log sets, TagPartitionedLogSystem.actor.cpp:1265 +
            # fdbcli force_recovery_with_data_loss): lock the REGION's
            # log instead of the (blacked-out) primary's. Everything the
            # router shipped recovers; the unshipped tail — bounded by
            # the advertised lag — is what an async region admits
            # losing. Older primary generations are abandoned with the
            # primary: their undrained remainder is part of that loss.
            self._set_state(dbi.LOCKING_CSTATE)
            recovery_version, locked = await self._epoch_end_region(prev)
            old_log_sets = (LogSetInfo(
                prev.epoch, 0, recovery_version, locked,
                stores=tuple(prev.region_logs) + tuple(prev.logs)),)
            # older generations may still matter: a router lagging
            # across an epoch boundary reads the gap from that epoch's
            # satellite replicas, which survive the blackout. A
            # generation with NO surviving store died whole with the
            # primary — in a takeover that is part of the admitted
            # loss, and carrying it would wedge every reader behind a
            # generation that can never answer (pre-attach data is
            # absent from the region by the attach contract anyway)
            for oe, ob, oend, stores in prev.old_logs:
                refs = tuple(r for r in (self.cc.log_stores.get(s)
                                         for s, _m in stores)
                             if r is not None)
                if not refs:
                    flow.TraceEvent(
                        "RegionTakeoverAbandonedGeneration",
                        self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
                        Epoch=oe, Begin=ob, End=oend,
                        Stores=",".join(s for s, _m in stores)).log()
                    continue
                old_log_sets += (LogSetInfo(oe, ob, oend, refs,
                                            stores=tuple(stores)),)
        elif prev is not None:
            self._set_state(dbi.LOCKING_CSTATE)
            recovery_version, locked = await self._epoch_end(prev)
            old_log_sets = (LogSetInfo(prev.epoch, prev.recovery_version,
                                       recovery_version, locked,
                                       stores=prev.logs),)
            # older generations still draining chain through. Store
            # NAMES are carried even when a store is unreachable right
            # now: its worker may still be rebooting, and dropping the
            # name would orphan the generation's records forever
            for oe, ob, oend, stores in prev.old_logs:
                refs = tuple(r for r in (self.cc.log_stores.get(s)
                                         for s, _m in stores)
                             if r is not None)
                old_log_sets += (LogSetInfo(oe, ob, oend, refs,
                                            stores=tuple(stores)),)
        self.epoch = (prev.epoch if prev is not None else 0) + 1

        # Phase 3: recruit the new transaction subsystem
        # (ref: recruitEverything :537)
        self._set_state(dbi.RECRUITING)
        self.master = Master(self.process, recovery_version=recovery_version)
        self.master.start()
        self.critical_procs = {self.process}
        # capture ONCE, before recruitment: the epoch is recruited
        # consistently even if the flags flip mid-recovery (the
        # config-dirty recovery after such a flip re-publishes)
        backup_on = self.cc.backup_active
        region = getattr(self.cc, "region", None)
        # the committed \xff/conf/usable_regions row is the operator
        # intent recruitment obeys: an attached region object with
        # usable_regions=1 is ignored (ref: DatabaseConfiguration
        # usable_regions gating the fearless log topology)
        if getattr(cfg, "usable_regions", 1) < 2:
            region = None
        # role-per-process deployment (ROADMAP item 2): a driver that
        # attached an ExternalRoles directory (tools/rolehost.py) hosts
        # resolvers/tlogs in their own OS processes — recruitment
        # becomes an init RPC and every ref below is a RetryingTcpRef.
        # With no directory attached (the default), this path adds
        # zero awaits and zero draws: the posture is byte-identical.
        ext = getattr(self.cc, "external_roles", None)
        new_logs = []
        new_log_stores = []
        log_recruits = []       # (worker, store) incl. satellites
        if ext is not None and ext.n_tlogs:
            assert region is None, \
                "external tlogs + region topologies are not supported"
            assert ext.n_tlogs == cfg.n_logs, (ext.n_tlogs, cfg.n_logs)
            for i in range(cfg.n_logs):
                store = f"tlog-e{self.epoch}-{i}"
                refs = await ext.recruit_tlog(i, store, recovery_version)
                self.cc.log_stores[store] = refs
                new_logs.append(refs)
                new_log_stores.append((store, refs.machine))
        else:
            log_workers = self.cc.pick_workers(cfg.n_logs, role="tlog")
            for i, w in enumerate(log_workers):
                store = f"tlog-e{self.epoch}-{i}"
                refs = w.recruit_tlog(store, recovery_version)
                self.cc.log_stores[store] = refs
                new_logs.append(refs)
                new_log_stores.append((store, w.process.machine))
                log_recruits.append((w, store))
                self.critical_procs.add(w.process)
        # satellite log replicas (ref: satelliteTagLocations — one more
        # full replica of the stream per satellite DC, so the acked
        # tail survives a primary-DC blackout). Full log-set members:
        # pushed to, locked, rotated onto, popped like any replica.
        if region is not None and region.satellite_workers:
            live_sats = [sw for sw in region.satellite_workers
                         if sw.process.alive]
            if not live_sats:
                flow.TraceEvent(
                    "RecoverySatellitesUnavailable", self.process.name,
                    severity=flow.trace.SevWarnAlways).detail(
                    Epoch=self.epoch).log()
            for i, sw in enumerate(region.satellite_workers):
                if not sw.process.alive:
                    continue
                store = f"tlog-e{self.epoch}-sat{i}"
                refs = sw.recruit_tlog(store, recovery_version)
                self.cc.log_stores[store] = refs
                new_logs.append(refs)
                new_log_stores.append((store, sw.process.machine))
                log_recruits.append((sw, store))
                self.critical_procs.add(sw.process)
        resolver_refs = []
        resolver_metrics = []
        resolver_handoffs = []
        if ext is not None and ext.n_resolvers:
            assert ext.n_resolvers == cfg.n_resolvers, \
                (ext.n_resolvers, cfg.n_resolvers)
            for i in range(cfg.n_resolvers):
                rref, mref, href = await ext.recruit_resolver(
                    i, f"resolver-e{self.epoch}-{i}", recovery_version,
                    cfg.conflict_backend)
                resolver_refs.append(rref)
                resolver_metrics.append(mref)
                resolver_handoffs.append(href)
        else:
            res_workers = self.cc.pick_workers(cfg.n_resolvers,
                                               role="resolver")
            for i, w in enumerate(res_workers):
                rref, mref, href = w.recruit_resolver(
                    f"resolver-e{self.epoch}-{i}", recovery_version,
                    backend=cfg.conflict_backend)
                resolver_refs.append(rref)
                resolver_metrics.append(mref)
                resolver_handoffs.append(href)
                self.critical_procs.add(w.process)
        # addr-carrying peer descriptors for the TcpGateway's PEER
        # describe: worker proxies connect DIRECTLY to external role
        # processes instead of trombone-ing through the gateway
        self.peer_resolvers = (ext.resolver_descriptors()
                               if ext is not None and ext.n_resolvers
                               else None)
        self.peer_tlogs = (ext.tlog_descriptors()
                           if ext is not None and ext.n_tlogs else None)
        resolver_splits = initial_resolver_splits(cfg.n_resolvers)
        self.cc.recruit_initial_storages()
        # every tag's records are held until ALL of its replicas pop
        expected = {}
        for name, (tag, _b, _e) in self.cc.shard_map.items():
            expected.setdefault(tag, []).append(name)
        expected = {t: tuple(ns) for t, ns in expected.items()}
        if backup_on:
            from .proxy import BACKUP_TAG
            from ..layers.backup_agent import AGENT_NAME
            expected[BACKUP_TAG] = (AGENT_NAME,)
        if region is not None:
            from .proxy import REGION_TAG
            expected[REGION_TAG] = (region.router_name,)
        if ext is not None and ext.n_tlogs:
            # external tlogs take the replica expectation over their
            # control token (in-process recruitment's direct method
            # call, made an RPC)
            for i in range(cfg.n_logs):
                await ext.set_expected_replicas(i, expected)
        for w, store in log_recruits:
            w.roles[store].set_expected_replicas(expected)
        storage_splits = self.cc.storage_splits()
        rk_worker = self.cc.pick_workers(1, role="ratekeeper")[0]
        rk_ref = rk_worker.recruit_ratekeeper(
            f"ratekeeper-e{self.epoch}", self.cc)
        proxy_workers = self.cc.pick_workers(cfg.n_proxies, role="proxy")
        proxies = []
        for i, w in enumerate(proxy_workers):
            proxies.append(w.recruit_proxy(
                f"proxy-e{self.epoch}-{i}",
                self.master.version_requests.ref(),
                resolver_refs, [r.commits for r in new_logs],
                resolver_splits, storage_splits,
                recovery_version, ratekeeper_ref=rk_ref,
                storage_tags=self.cc.storage_tags(),
                management_ref=self.cc.management.ref()))
            if backup_on:
                w.roles[f"proxy-e{self.epoch}-{i}"].backup_active = True
            if region is not None:
                w.roles[f"proxy-e{self.epoch}-{i}"].region_active = True
            self.critical_procs.add(w.process)
        proxies = tuple(proxies)
        # each proxy confirms GRVs with every other proxy (ref:
        # getLiveCommittedVersion)
        for i, w in enumerate(proxy_workers):
            w.roles[f"proxy-e{self.epoch}-{i}"].set_peers(
                [p.raw_committed for j, p in enumerate(proxies) if j != i])

        # Phase 4: commit the new core state; a conflict means a newer
        # master exists and this one must die (ref: trackTlogRecovery /
        # cstate.write exclusivity)
        # persist every member store's NAME, reachable or not — the
        # cstate must preserve the rejoin-by-name invariant across
        # back-to-back recoveries or a down store's generation would be
        # orphaned forever (readers would then wait on it forever)
        old_for_cstate = tuple(
            (ls.epoch, ls.begin_version, ls.end_version,
             ls.stores or tuple((r.store, r.machine) for r in ls.logs))
            for ls in old_log_sets)
        region_logs = region.log_stores() if region is not None else ()
        await self.cstate.set_exclusive(CoreState(
            self.epoch, recovery_version, tuple(new_log_stores),
            old_for_cstate, region_logs=region_logs))

        # Phase 5: broadcast the new picture; commits may now flow
        info = ServerDBInfo(
            self.epoch, dbi.ACCEPTING_COMMITS, recovery_version, proxies,
            LogSetInfo(self.epoch, recovery_version, -1, tuple(new_logs),
                       stores=tuple(new_log_stores)),
            old_log_sets, self.cc.dbinfo.get().storages,
            failed=self.cc.dbinfo.get().failed,
            backup_active=backup_on, region_attached=region is not None)
        self.cc.publish(info)
        self._trace("MasterRecoveryState", State=dbi.ACCEPTING_COMMITS,
                    Epoch=self.epoch, RecoveryVersion=recovery_version)

        # Phase 6: the recovery transaction proves the new pipeline live
        # end-to-end (ref: the recovery txn in masterCore phase 5)
        await proxies[0].commits.get_reply(
            CommitRequest(recovery_version, (), (), ()), self.process)
        # re-read at publish time: a worker that rebooted while we
        # awaited the recovery txn may have merged fresh storage
        # endpoints into the broadcast — never clobber them with the
        # snapshot captured above (code review r3)
        cur = self.cc.dbinfo.get()
        self.cc.publish(cur._replace(recovery_state=dbi.FULLY_RECOVERED))
        self._trace("MasterRecoveredFully", Epoch=self.epoch)

        # Lifetime: retire drained old generations + rebalance resolver
        # load; both die with this epoch (CC cancels aux on teardown)
        self.aux.add(flow.spawn(self._cleanup_old_logs(),
                                TaskPriority.CLUSTER_CONTROLLER,
                                name=f"master-e{self.epoch}.oldLogCleanup"))
        if cfg.n_resolvers > 1:
            self.aux.add(flow.spawn(
                self._resolution_balancing(resolver_metrics),
                TaskPriority.RESOLUTION_METRICS,
                name=f"master-e{self.epoch}.resolutionBalancing"))
            # load-driven split/merge with live state handoff (ISSUE
            # 15) — spawned only when armed at recovery time, so the
            # RESOLVER_BALANCE=0 posture adds not a single timer event
            # to the sim schedule (byte-identical off, test-pinned)
            if flow.SERVER_KNOBS.resolver_balance:
                self.aux.add(flow.spawn(
                    self._resolver_balance_loop(
                        resolver_metrics, resolver_handoffs,
                        resolver_splits, cfg.n_resolvers),
                    TaskPriority.RESOLUTION_METRICS,
                    name=f"master-e{self.epoch}.resolverBalance"))
        await self.aux.get_result()

    def _set_state(self, state: str) -> None:
        cur = self.cc.dbinfo.get()
        self.cc.publish(cur._replace(recovery_state=state))
        self._trace("MasterRecoveryState", State=state)

    async def _epoch_end(self, prev: CoreState):
        """Lock the previous generation's logs; the recovery version is
        the max durable version across reachable replicas — the push
        path acks only when EVERY replica is durable, so any single
        survivor covers all acked commits (ref: epochEnd,
        TagPartitionedLogSystem.actor.cpp:1265)."""
        while True:
            refs = [self.cc.log_stores.get(store)
                    for store, _m in prev.logs]
            refs = [r for r in refs if r is not None]
            locked = []
            if refs:
                futs = [flow.catch_errors(flow.timeout_error(
                    r.locks.get_reply(TLogLockRequest(), self.process),
                    flow.SERVER_KNOBS.tlog_lock_timeout))
                    for r in refs]
                settled = await flow.all_of(futs)
                locked = [(r, f.get()) for r, f in zip(refs, settled)
                          if not f.is_error]
            if locked:
                recovery_version = max(rep.end_version for _r, rep in locked)
                return recovery_version, tuple(r for r, _rep in locked)
            # nothing reachable: wait for a worker reboot to re-register
            # a surviving store (ref: recovery waits for tlogs)
            self._trace("MasterRecoveryWaitingForLogs",
                        Stores=",".join(s for s, _m in prev.logs))
            await flow.delay(flow.SERVER_KNOBS.recovery_wait_for_logs_delay,
                             TaskPriority.CLUSTER_CONTROLLER)

    async def _epoch_end_region(self, prev: CoreState):
        """Explicit region takeover: lock the region's log store and
        recover at its durable frontier. The lock makes the takeover
        exact — after it, no in-flight router push can extend the
        remote log, so the reported end version is the last version the
        promoted epoch preserves (ref: epochEnd over the remote log
        set; the lock doubles as the fence the old promote() faked with
        a quiesce poll)."""
        grace = flow.now() + flow.SERVER_KNOBS.region_lock_grace
        while True:
            # the remote log PLUS whatever survives of the primary
            # epoch's log set — in a primary blackout that is exactly
            # the satellite replicas, which hold the complete acked
            # stream (push waits on every replica), so locking them
            # recovers to the acked frontier: zero data loss instead
            # of the router's shipped frontier (ref: epochEnd preferring
            # the satellite-backed recovery when remote logs lag)
            stores = tuple(prev.region_logs) + tuple(prev.logs)
            refs = {store: self.cc.log_stores.get(store)
                    for store, _m in stores}
            known = [(s, r) for s, r in refs.items() if r is not None]
            locked = []
            if known:
                futs = [flow.catch_errors(flow.timeout_error(
                    r.locks.get_reply(TLogLockRequest(), self.process),
                    flow.SERVER_KNOBS.tlog_lock_timeout))
                    for _s, r in known]
                settled = await flow.all_of(futs)
                locked = [(r, f.get()) for (_s, r), f in zip(known, settled)
                          if not f.is_error]
            # don't settle for the first lockable subset: worker
            # registrations with the freshly promoted controller race
            # this loop, and returning before the satellite stores land
            # would silently recover at the router's lagging frontier.
            # Proceed only once every store either has a locked ref or
            # the grace window for stragglers has passed (blacked-out
            # primary stores never register — they are what the grace
            # window exists to stop waiting for).
            unresolved = len(stores) - len(locked)
            if locked and (unresolved == 0 or flow.now() >= grace):
                if unresolved:
                    flow.TraceEvent(
                        "RegionTakeoverPartialLock", self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
                        Locked=len(locked), Total=len(stores)).log()
                flow.cover("master.region_takeover")
                recovery_version = max(rep.end_version
                                       for _r, rep in locked)
                return recovery_version, tuple(r for r, _ in locked)
            self._trace("MasterRecoveryWaitingForRegionLogs",
                        Stores=",".join(s for s, _m in stores))
            await flow.delay(flow.SERVER_KNOBS.recovery_wait_for_logs_delay,
                             TaskPriority.CLUSTER_CONTROLLER)

    async def _resolution_balancing(self, metric_refs) -> None:
        """Shift key-range ownership from the most- to the least-loaded
        resolver (ref: resolutionBalancing, masterserver.actor.cpp:1008
        + ResolutionSplitRequest). Per round: poll each resolver's
        cumulative work + first-byte key histogram, diff against the
        last round, and — when the spread is material — move the loaded
        resolver's hottest byte bucket, but only when the move reduces
        the maximum (a single-bucket hotspot never bounces)."""
        n = len(metric_refs)
        last_work = [0] * n
        last_hist = [[0] * 256 for _ in range(n)]
        while True:
            await flow.delay(flow.SERVER_KNOBS.resolution_balancing_interval,
                             TaskPriority.RESOLUTION_METRICS)
            if flow.SERVER_KNOBS.resolver_balance:
                # the split/merge balance loop (ISSUE 15) is
                # authoritative while armed: two movers would bounce
                # ranges against each other
                continue
            settled = await flow.all_of([flow.catch_errors(
                flow.timeout_error(
                    ref.get_reply(RESOLUTION_METRICS_REQUEST,
                                  self.process),
                    flow.SERVER_KNOBS.resolution_metrics_timeout))
                for ref in metric_refs])
            if any(f.is_error for f in settled):
                continue
            replies = [f.get() for f in settled]
            dwork = [r.work_units - last_work[i]
                     for i, r in enumerate(replies)]
            dhist = [[r.key_hist[b] - last_hist[i][b] for b in range(256)]
                     for i, r in enumerate(replies)]
            last_work = [r.work_units for r in replies]
            last_hist = [list(r.key_hist) for r in replies]
            hi = max(range(n), key=lambda i: dwork[i])
            lo = min(range(n), key=lambda i: dwork[i])
            if dwork[hi] < flow.SERVER_KNOBS.resolution_balancing_min_work \
                    or dwork[hi] <= 2 * (dwork[lo] + 1):
                continue
            bucket = max(range(256), key=lambda b: dhist[hi][b])
            moved = dhist[hi][bucket]
            # only when it actually reduces the max load
            if moved <= 0 or dwork[lo] + moved >= dwork[hi]:
                continue
            begin = bytes([bucket])
            end = bytes([bucket + 1]) if bucket < 255 else None
            # the move rides the version chain: every proxy picks it up
            # with its next assigned batch version and applies it at the
            # same effective version — no per-proxy delivery, no skew
            effective = self.master.register_move(begin, end, lo)
            self._trace("ResolutionBalancingMove", Bucket=bucket,
                        From=hi, To=lo, EffectiveVersion=effective)

    async def _resolver_balance_loop(self, metric_refs, handoff_refs,
                                     init_splits, n_resolvers) -> None:
        """Load-driven resolver split/merge with LIVE state handoff
        (ISSUE 15; ref: resolutionBalancing + the keyResolvers history,
        masterserver.actor.cpp:1008 / MasterProxyServer.actor.cpp:204 —
        grown with the checkpoint/clip/install machinery PR 5 built).

        Per round: poll every resolver's cumulative work + first-byte
        key histogram, diff against the last round, and when the skew
        crosses the knob thresholds move the loaded resolver's hottest
        OWNED byte bucket to the least-loaded one — but through the
        full handoff protocol (`_handoff`), so the recipient votes
        bit-exactly from its first post-move batch and the donor
        retires early instead of double-delivering for a whole MVCC
        window. A previously-split bucket whose traffic has died is
        merged back to its original owner (the symmetric stitch).
        Counters land on the CC (`resolver_balance` in status)."""
        n = len(metric_refs)
        last_work = [0] * n
        last_hist = [[0] * 256 for _ in range(n)]
        # shadow of the proxies' keyResolvers CURRENT ownership: every
        # move goes through this loop, so applying our own moves keeps
        # it exact (releases don't change current ownership)
        from .proxy import KeyResolverMap
        owners = KeyResolverMap(init_splits, n_resolvers)
        splits_made: list = []   # (begin, end, from_idx, to_idx)
        force_spent = False      # one-shot FORCE consumed for good
        bal = self.cc.balance_stats
        while True:
            await flow.delay(flow.SERVER_KNOBS.resolver_balance_interval,
                             TaskPriority.RESOLUTION_METRICS)
            k = flow.SERVER_KNOBS
            if not k.resolver_balance:
                continue
            settled = await flow.all_of([flow.catch_errors(
                flow.timeout_error(
                    ref.get_reply(RESOLUTION_METRICS_REQUEST,
                                  self.process),
                    flow.SERVER_KNOBS.resolution_metrics_timeout))
                for ref in metric_refs])
            if any(f.is_error for f in settled):
                continue
            replies = [f.get() for f in settled]
            dwork = [r.work_units - last_work[i]
                     for i, r in enumerate(replies)]
            dhist = [[r.key_hist[b] - last_hist[i][b] for b in range(256)]
                     for i, r in enumerate(replies)]
            last_work = [r.work_units for r in replies]
            last_hist = [list(r.key_hist) for r in replies]

            # merge pass first: stitch back any split whose traffic
            # died, so a transient hot spot does not fragment the map
            # forever (the sharded backend's upper-bound-row dedup
            # makes the re-graft exact)
            merged = None
            for mv in splits_made:
                begin, end, src, dst = mv
                bucket = begin[0] if begin else 0
                if dhist[dst][bucket] <= int(k.resolver_balance_merge_work):
                    if await self._handoff(begin, end, dst, src,
                                           handoff_refs, owners):
                        bal.counter("merges").add(1)
                        self._trace("ResolverBalanceMerge",
                                    Bucket=bucket, From=dst, To=src)
                        merged = mv
                    break
            if merged is not None:
                splits_made.remove(merged)
                continue

            # FORCE is one-shot and STICKY: it exists so smoke/CI can
            # make the FIRST split deterministic under a small
            # workload; once consumed the real thresholds govern even
            # if that split later merges away (deriving spent-ness
            # from splits_made would re-arm after every merge and
            # churn split/merge forever — review finding)
            force = bool(k.resolver_balance_force) and not force_spent
            hi = max(range(n), key=lambda i: dwork[i])
            lo = min(range(n), key=lambda i: dwork[i])
            if hi == lo or dwork[hi] <= 0:
                continue
            if not force:
                if dwork[hi] < k.resolver_balance_min_work:
                    continue
                if dwork[hi] <= k.resolver_balance_skew * (dwork[lo] + 1):
                    continue
            # hottest byte bucket the donor CURRENTLY owns (the shadow
            # map keeps picks honest after earlier rounds moved ranges)
            owned = owners.owned_buckets(hi)
            if not owned:
                continue
            bucket = max(owned, key=lambda b: dhist[hi][b])
            moved = dhist[hi][bucket]
            if moved <= 0:
                continue
            if not force and dwork[lo] + moved >= dwork[hi]:
                continue   # a single-bucket hotspot never bounces
            begin = bytes([bucket])
            end = bytes([bucket + 1]) if bucket < 255 else None
            if await self._handoff(begin, end, hi, lo, handoff_refs,
                                   owners):
                if force:
                    force_spent = True
                bal.counter("splits").add(1)
                self.cc.balance_last = {
                    "begin": begin.hex(),
                    "end": end.hex() if end is not None else "",
                    "from": hi, "to": lo,
                    "work_moved": moved}
                self._trace("ResolverBalanceSplit", Bucket=bucket,
                            From=hi, To=lo, WorkMoved=moved)
                splits_made.append((begin, end, hi, lo))

    async def _handoff(self, begin, end, src: int, dst: int,
                       handoff_refs, owners) -> bool:
        """The live-handoff protocol for one range move:

          1. register the move (rides the version chain; proxies start
             double-delivering [begin, end) to src AND dst at E),
          2. checkpoint the donor AT/ABOVE E (the request's
             min_version parks on the donor's version chain, so the
             clipped piece provably holds every pre-move write),
          3. graft the piece into the recipient (pointwise max — exact
             whatever post-E writes it already recorded), and
          4. register the early release: proxies drop the donor from
             the range's owner history at the next version, ending
             double delivery a full MVCC window early.

        A checkpoint/install failure (partitioned resolver, timeout)
        leaves the move in the reference's window-only mode — the donor
        keeps voting with complete history until the window passes, so
        verdicts stay exact; only the early retirement is lost."""
        timeout_s = float(flow.SERVER_KNOBS.resolver_handoff_timeout)
        eff = self.master.register_move(begin, end, dst)
        owners.move(begin, end, dst, eff)
        bal = self.cc.balance_stats
        try:
            rep = await flow.timeout_error(
                handoff_refs[src].get_reply(
                    ResolverCheckpointRequest(begin, end, eff),
                    self.process), timeout_s)
            await flow.timeout_error(
                handoff_refs[dst].get_reply(
                    ResolverInstallRequest(begin, end, rep.piece),
                    self.process), timeout_s)
        except flow.FdbError as e:
            if e.name == "operation_cancelled":
                raise
            flow.cover("master.resolver_balance.handoff_failed")
            bal.counter("handoff_timeouts").add(1)
            flow.TraceEvent("ResolverHandoffTimeout", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Begin=begin.hex(), From=src, To=dst,
                Error=e.name).log()
            return True   # the move stands; window semantics cover it
        rel = self.master.register_release(begin, end, src)
        bal.counter("releases").add(1)
        flow.cover("master.resolver_balance.handoff")
        self._trace("ResolverHandoffComplete", Begin=begin.hex(),
                    From=src, To=dst, CheckpointVersion=rep.version,
                    ReleaseVersion=rel)
        return True

    async def _cleanup_old_logs(self) -> None:
        """Drop a drained old generation from the broadcast picture once
        every storage server has pulled past its end (ref: the oldest
        log epoch retiring in TagPartitionedLogSystem)."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.old_log_cleanup_interval,
                             TaskPriority.CLUSTER_CONTROLLER)
            info = self.cc.dbinfo.get()
            if not info.old_logs:
                continue
            floor = self.cc.min_storage_version()
            agent = getattr(self.cc, "backup_agent", None)
            if agent is not None:
                # an active backup tail must drain a generation before
                # it retires, or the mutation log gets a silent hole
                floor = min(floor, agent._tailed_to)
            region = getattr(self.cc, "region", None)
            if region is not None:
                # same rule for the region log router: retiring a
                # generation it has not shipped would stall it forever
                # under the strict source-coverage rule
                floor = min(floor, region._pushed_to)
            keep = tuple(ls for ls in info.old_logs
                         if ls.end_version > floor)
            if len(keep) != len(info.old_logs):
                self.cc.publish(info._replace(old_logs=keep))

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
