"""Master role: commit-version authority.

Reference: fdbserver/masterserver.actor.cpp `getVersion` (:875-940) —
versions advance with real time (`version += VERSIONS_PER_SECOND * dt`,
capped per request by MAX_READ_TRANSACTION_LIFE_VERSIONS) so that a
version is also a coarse clock; each batch receives (prev_version,
version) so downstream stages can sequence without gaps.
"""

from __future__ import annotations

from typing import NamedTuple

from .. import flow
from ..flow import TaskPriority
from ..rpc import RequestStream, SimProcess

VERSIONS_PER_SECOND = 1_000_000          # ref: Knobs.cpp VERSIONS_PER_SECOND
MAX_VERSION_ADVANCE = 5_000_000          # cap per request (ref: :918)


class GetCommitVersionReply(NamedTuple):
    prev_version: int
    version: int


class Master:
    def __init__(self, process: SimProcess, recovery_version: int = 0):
        self.process = process
        self.version = recovery_version
        self._last_time = None
        self.version_requests = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        self._actors.add(flow.spawn(self._version_loop(),
                                    TaskPriority.PROXY_GET_CONSISTENT_READ_VERSION,
                                    name=f"{self.process.name}.getVersion"))
        self.process.on_kill(self._actors.cancel_all)

    def _next_version(self) -> GetCommitVersionReply:
        t = flow.now()
        if self._last_time is None:
            advance = 1
        else:
            advance = max(1, min(MAX_VERSION_ADVANCE,
                                 int(VERSIONS_PER_SECOND * (t - self._last_time))))
        self._last_time = t
        prev = self.version
        self.version = prev + advance
        return GetCommitVersionReply(prev, self.version)

    async def _version_loop(self):
        while True:
            _req, reply = await self.version_requests.pop()
            reply.send(self._next_version())
