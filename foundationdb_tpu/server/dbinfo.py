"""ServerDBInfo: the broadcast picture of the current transaction
subsystem.

Reference: fdbserver/ServerDBInfo.h — the ClusterController assembles a
struct naming the master, proxies, resolvers, log system and recovery
state, and broadcasts it to every worker; roles and clients act on
changes (new epochs re-point storage pull loops and client endpoints).
Here the broadcast seam is a flow AsyncVar owned by the
ClusterController — the simulated stand-in for the CC's push RPC; a
real transport would ship the same tuple as bytes.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

# recovery states (ref: fdbserver/RecoveryState.h)
UNINITIALIZED = "uninitialized"
READING_CSTATE = "reading_coordinated_state"
LOCKING_CSTATE = "locking_coordinated_state"
RECRUITING = "recruiting_transaction_servers"
ACCEPTING_COMMITS = "accepting_commits"
FULLY_RECOVERED = "fully_recovered"


class LogRefs(NamedTuple):
    """One TLog replica's endpoints (ref: TLogInterface.h)."""

    store: str            # durable store name, stable across reboots
    machine: str
    commits: object       # NetworkRef
    peeks: object
    pops: object
    locks: object


class LogSetInfo(NamedTuple):
    """One log generation (ref: LogSystemConfig / OldTLogConf)."""

    epoch: int
    begin_version: int    # first version this generation may contain
    end_version: int      # last version (locked gens; -1 = open)
    logs: Tuple[LogRefs, ...]
    # EVERY member store's (name, machine), including ones unreachable
    # when this picture was built — a store that reboots later rejoins
    # its generation by name (losing the name would orphan its records:
    # readers would skip the generation and silently lose data)
    stores: Tuple[Tuple[str, str], ...] = ()


class ProxyRefs(NamedTuple):
    """(ref: MasterProxyInterface.h)"""

    name: str
    grvs: object
    commits: object
    raw_committed: object = None   # getRawCommittedVersion (peer GRV)


class StorageRefs(NamedTuple):
    """One storage REPLICA's endpoints
    (ref: StorageServerInterface.h)."""

    name: str
    tag: int
    begin: bytes
    end: bytes            # None = +inf
    gets: object
    ranges: object
    get_keys: object
    watches: object


class StorageShard(NamedTuple):
    """A key-range shard: the team of replicas serving it (ref: the
    keyServers map entry — a range and its server team; every replica
    pulls the SAME tag, so the replicated stream keeps them identical
    and reads load-balance across them, fdbrpc/LoadBalance.actor.h)."""

    tag: int
    begin: bytes
    end: bytes            # None = +inf
    replicas: Tuple[StorageRefs, ...]


class ServerDBInfo(NamedTuple):
    epoch: int
    recovery_state: str
    recovery_version: int
    proxies: Tuple[ProxyRefs, ...]
    logs: LogSetInfo                      # current generation
    old_logs: Tuple[LogSetInfo, ...]      # locked gens still draining
    storages: Tuple[StorageShard, ...]    # shard map ordered by begin
    seq: int = 0                          # broadcast sequence number
    # process/role names the CC's failure monitor currently considers
    # down — PUSHED to clients through this broadcast so they stop
    # trying known-dead endpoints first (ref: FailureMonitor state
    # pushed from the cluster controller, fdbrpc/FailureMonitor.h:123 +
    # fdbclient/FailureMonitorClient.actor.cpp)
    failed: Tuple[str, ...] = ()
    # what this epoch was RECRUITED with: backup tagging / region
    # shipping on every proxy+TLog. Observers (backup agent, region
    # attach) wait on these rather than poking roles — a recovery that
    # raced past their flag change publishes the stale value here and
    # the level-triggered config-dirty recovery that follows publishes
    # the corrected one (ref: the log system configuration carried in
    # the LogSystemConfig the CC broadcasts)
    backup_active: bool = False
    region_attached: bool = False


EMPTY_DBINFO = ServerDBInfo(0, UNINITIALIZED, 0, (), LogSetInfo(0, 0, -1, ()),
                            (), (), 0)

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary


def pick_log_source(info: "ServerDBInfo", needed: int, rr: int):
    """The generation-chasing cursor shared by every log tail (backup
    agent, region log router, storage pull): the oldest generation
    COVERING `needed` serves first, then the current one; `rr` rotates
    replicas on failure (ref: LogSystemPeekCursor merging old
    generations before the live set). Returns (generation, log refs)
    or None.

    Coverage is strict: a generation serves `needed` only if
    begin_version < needed <= end_version. Picking a LATER generation
    when the covering one is temporarily unreachable (e.g. its store's
    worker is mid-reboot) would let the reply's durable watermark
    advance the reader past records it never saw — silent data loss.
    The caller must wait and retry until the covering store
    re-registers."""
    gens = sorted(info.old_logs, key=lambda g: g.end_version)
    for gen in gens:
        if gen.begin_version < needed <= gen.end_version:
            if not gen.logs:
                return None   # covering gen unreachable: wait, never skip
            return gen, gen.logs[rr % len(gen.logs)]
    cur = info.logs
    if cur.logs and needed > cur.begin_version:
        return cur, cur.logs[rr % len(cur.logs)]
    return None
