"""Metric history recorder: the status document's longitudinal twin.

Reference: the metrics-keyspace idiom (fdbclient/MetricLogger.actor.cpp
persisting TDMetric series through the ordinary commit pipeline) applied
to the signals clusterGetStatus already computes. The cluster
controller's recorder loop samples a BOUNDED, deterministic vocabulary
of cluster signals once per METRIC_HISTORY_INTERVAL, buffers them
per-signal, and commits METRIC_HISTORY_CHUNK-sample delta-encoded chunk
rows under \\xff\\x02/metrics/<signal>/<ts> (schema: systemkeys.py).

Two consumers read the result: the CC's own SLO engine evaluates rules
over the recorder's in-memory tail (no read transactions on the hot
path), and anything with a database handle — layers/metrics.read_history,
tools/soak.py's restart-safe read-back, tools/incident.py — replays the
persisted series.

All values are integers; float signals are stored fixed-point x1000
(the `_ms`/`_x1000` suffix names the unit). Sampling happens on the sim
clock at a fixed cadence, so same-seed runs record bit-identical series
(pinned by tests/test_longitudinal.py).
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Dict, List, Tuple

from .. import flow
from .chaos import chaos_status as _chaos_status
from .dbinfo import FULLY_RECOVERED
from .ratekeeper import LIMIT_REASONS
from .systemkeys import encode_metric_chunk, metric_history_key


def _fp(x) -> int:
    """Fixed-point x1000 (so p99 seconds become integer milliseconds)."""
    return int(round(float(x) * 1000))


def _good_count(bands, edge_s: float) -> int:
    """Requests that finished within `edge_s` — the cumulative band
    count at the largest edge <= edge_s (0 when the edge undercuts
    every band: nothing is provably fast enough)."""
    i = bisect_right(bands.bands, edge_s) - 1
    return bands.counts[i] if i >= 0 else 0


class MetricHistoryRecorder:
    """Owned by the ClusterController; `record()` is called once per
    METRIC_HISTORY_INTERVAL from the recorder loop, `drain_chunks()` by
    the flush path. Per-signal state is O(chunk + tail window), never
    O(run length) — the run's length lives in the keyspace."""

    def __init__(self, cc):
        self.cc = cc
        self._buffers: Dict[str, List[Tuple[int, int]]] = {}
        self._tail: Dict[str, List[Tuple[int, int]]] = {}
        self._recovery_down_since = None
        self.samples_taken = 0
        self.rows_written = 0
        self.flushes = 0

    # -- sampling --------------------------------------------------------
    def sample_signals(self, now: float) -> Dict[str, int]:
        """One tick's signal vector off the CC's live registry — the
        same sources get_status reads, collapsed to a bounded integer
        vocabulary."""
        from .resolver_role import Resolver
        cc = self.cc
        info = cc.dbinfo.get()
        out: Dict[str, int] = {"cluster/epoch": info.epoch}

        # recovery excursion age: 0 while fully recovered, else ms since
        # this excursion began (the SLO recovery-time rule's input)
        if info.recovery_state == FULLY_RECOVERED:
            self._recovery_down_since = None
            out["cluster/recovery_age_ms"] = 0
        else:
            if self._recovery_down_since is None:
                self._recovery_down_since = now
            out["cluster/recovery_age_ms"] = _fp(
                now - self._recovery_down_since)

        committed = conflicted = 0
        grv_total = grv_good = commit_total = commit_good = 0
        commit_p99 = grv_p99 = 0.0
        adm_admitted = adm_rejected = adm_throttled = 0
        commit_edge = flow.SERVER_KNOBS.slo_commit_p99_ms / 1000.0
        grv_edge = flow.SERVER_KNOBS.slo_grv_p99_ms / 1000.0
        for p in cc._current_proxies():
            snap = p.stats.snapshot()
            committed += snap.get("transactions_committed", 0)
            conflicted += snap.get("transactions_conflicted", 0)
            cb, gb = p.commit_bands, p.grv_bands
            commit_total += cb.bands.total
            commit_good += _good_count(cb.bands, commit_edge)
            grv_total += gb.bands.total
            grv_good += _good_count(gb.bands, grv_edge)
            commit_p99 = max(commit_p99, cb.sample.percentile(0.99))
            grv_p99 = max(grv_p99, gb.sample.percentile(0.99))
            adm = p.admission_status()
            adm_admitted += sum(adm.get("admitted", {}).values())
            adm_rejected += adm.get("rejected", 0) + adm.get(
                "throttle_rejected", 0)
            adm_throttled += adm.get("throttle_delayed", 0)
        out["cluster/txn_committed"] = committed
        out["cluster/txn_conflicted"] = conflicted
        out["latency/commit/total"] = commit_total
        out["latency/commit/bad"] = commit_total - commit_good
        out["latency/commit/p99_ms"] = _fp(commit_p99)
        out["latency/grv/total"] = grv_total
        out["latency/grv/bad"] = grv_total - grv_good
        out["latency/grv/p99_ms"] = _fp(grv_p99)
        out["admission/admitted"] = adm_admitted
        out["admission/rejected"] = adm_rejected
        out["admission/throttle_delayed"] = adm_throttled

        # shadow-resolve divergence across the epoch's resolvers (the
        # zero-divergent-verdicts SLO's input)
        mismatches = 0
        for _rn, role in cc._epoch_roles(info, Resolver):
            fo = role.failover_stats()
            if fo:
                mismatches += (fo.get("shadow", {}) or {}).get(
                    "mismatches", 0)
        out["cluster/shadow_mismatches"] = mismatches

        # ratekeeper decision
        rk = cc._current_ratekeeper()
        if rk is not None:
            out["rk/tps_limit"] = int(min(rk.rate, 10 ** 12))
            reason = (rk.last_decision or {}).get("limiting_reason",
                                                  "none")
            out["rk/limiting_reason"] = (
                LIMIT_REASONS.index(reason)
                if reason in LIMIT_REASONS else -1)

        # storage heat rollup (zeros while that plane is disarmed)
        heat = cc.storage_heat.top()
        out["heat/ranges"] = len(heat)
        out["heat/top_read_bps"] = int(heat[0]["read_bps"]) if heat else 0

        # chaos accounting (did the storm actually fire, and when)
        ch = _chaos_status(cc.process.net)
        out["chaos/events"] = ch["events"]
        out["chaos/messages_dropped"] = ch["messages_dropped"]
        out["chaos/messages_duplicated"] = ch["messages_duplicated"]

        # QoS plane: per role kind, the max of each smoothed signal
        # across that kind's roles (bounded: the QosSample vocabulary
        # is fixed per kind; empty while QOS_SAMPLE_INTERVAL is 0)
        agg: Dict[str, float] = {}
        for s in cc.qos_samples.values():
            for name, v in s.signals.items():
                if not isinstance(v, (int, float)):
                    continue
                key = f"qos/{s.kind}/{name}"
                agg[key] = max(agg.get(key, 0.0), float(v))
        for key in sorted(agg):
            out[key] = _fp(agg[key])
        return out

    def record(self, now: float) -> None:
        """Append one tick's samples to the per-signal buffers and the
        in-memory tail the SLO engine reads."""
        ts_ms = int(now * 1000)
        tail_ms = int(max(flow.SERVER_KNOBS.slo_burn_slow_window * 2,
                          120.0) * 1000)
        for signal, value in self.sample_signals(now).items():
            self._buffers.setdefault(signal, []).append((ts_ms, value))
            tail = self._tail.setdefault(signal, [])
            tail.append((ts_ms, value))
            cutoff = ts_ms - tail_ms
            while tail and tail[0][0] < cutoff:
                tail.pop(0)
        self.samples_taken += 1

    # -- flushing --------------------------------------------------------
    def drain_chunks(self, force: bool = False):
        """Pop every signal buffer that reached METRIC_HISTORY_CHUNK
        samples (all of them when `force`) as (key, value) chunk rows
        ready for one blind-write transaction."""
        chunk = max(1, int(flow.SERVER_KNOBS.metric_history_chunk))
        rows = []
        for signal in sorted(self._buffers):
            buf = self._buffers[signal]
            while len(buf) >= chunk or (force and buf):
                samples, self._buffers[signal] = buf[:chunk], buf[chunk:]
                buf = self._buffers[signal]
                rows.append((metric_history_key(signal, samples[0][0]),
                             encode_metric_chunk(samples)))
        return rows

    async def flush(self, db, force: bool = False) -> int:
        """Commit the ready chunk rows (blind sets — chunk keys are
        unique per (signal, first_ts), so this can never conflict)."""
        rows = self.drain_chunks(force)
        if not rows:
            return 0
        from ..client import run_transaction

        async def body(tr):
            tr.set_option("access_system_keys")
            for k, v in rows:
                tr.set(k, v)

        await run_transaction(db, body, max_retries=100)
        self.rows_written += len(rows)
        self.flushes += 1
        return len(rows)

    # -- reading (the SLO engine's view) ---------------------------------
    def tail_series(self) -> Dict[str, List[Tuple[int, int]]]:
        return self._tail

    def status(self) -> dict:
        return {"samples": self.samples_taken,
                "rows_written": self.rows_written,
                "flushes": self.flushes,
                "signals": len(self._tail),
                "buffered": sum(len(b) for b in self._buffers.values())}
