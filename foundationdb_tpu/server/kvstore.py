"""IKeyValueStore + the memory engine (log-structured over DiskQueue).

Reference: fdbserver/IKeyValueStore.h:38 (the engine interface) and
KeyValueStoreMemory.actor.cpp (the memory engine: all data in RAM,
durability via an operation log on a DiskQueue, periodically compacted
by snapshotting the whole map into the log). Re-implemented, not
ported: the snapshot here is a single log record carrying the full
sorted map, written when the op-log's live bytes exceed a threshold,
after which everything older is popped.

Engines are machine-scoped (open by name on the machine's SimDisk) so
a rebooted process recovers its predecessor's data.
"""

from __future__ import annotations

import struct
from bisect import bisect_left, insort
from typing import Dict, List, Optional, Tuple

from ..rpc.disk import SimDisk
from .diskqueue import DiskQueue

_OP_SET = 0
_OP_CLEAR = 1
_OP_SNAPSHOT = 2
_OP_BATCH = 3
_U32 = struct.Struct("<I")


def _enc_kv(op: int, a: bytes, b: bytes) -> bytes:
    return bytes([op]) + _U32.pack(len(a)) + a + _U32.pack(len(b)) + b


def _dec_kv(rec: bytes) -> Tuple[int, bytes, bytes]:
    op = rec[0]
    (la,) = _U32.unpack_from(rec, 1)
    a = rec[5:5 + la]
    (lb,) = _U32.unpack_from(rec, 5 + la)
    b = rec[9 + la:9 + la + lb]
    return op, a, b


class IKeyValueStore:
    """Engine contract (ref: IKeyValueStore.h): synchronous in-memory
    reads/staged writes + an async durability barrier."""

    async def recover(self) -> None:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def clear_range(self, begin: bytes, end: bytes) -> None:
        raise NotImplementedError

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def get_range(self, begin: bytes, end: bytes, limit: int = 1 << 30,
                  reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        raise NotImplementedError

    async def commit(self) -> None:
        """Make all staged mutations durable."""
        raise NotImplementedError

    def row_count(self) -> int:
        """Approximate stored row count (data-distribution signal)."""
        return len(self.get_range(b"", b"\xff", limit=1 << 20))


class EphemeralKeyValueStore(IKeyValueStore):
    """RAM-only engine for non-durable clusters: the storage server's
    durability loop runs against it unchanged, which keeps the MVCC
    window (and memory) bounded even when nothing persists (round-2
    VERDICT: the non-durable default leaked the window forever)."""

    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []

    async def recover(self) -> None:
        return

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._data:
            insort(self._keys, key)
        self._data[key] = value

    def clear_range(self, begin: bytes, end: bytes) -> None:
        lo = bisect_left(self._keys, begin)
        hi = bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._data[k]
        del self._keys[lo:hi]

    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def get_range(self, begin: bytes, end: bytes, limit: int = 1 << 30,
                  reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        lo = bisect_left(self._keys, begin)
        hi = bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = keys[::-1]
        return [(k, self._data[k]) for k in keys[:limit]]

    def row_count(self) -> int:
        return len(self._keys)

    async def commit(self) -> None:
        return


class KeyValueStoreMemory(IKeyValueStore):
    def __init__(self, disk: SimDisk, name: str, owner=None,
                 snapshot_threshold: int = 1 << 20):
        self._dq = DiskQueue(disk, name, owner)
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []  # sorted index over _data
        self._staged: List[bytes] = []  # encoded ops since last commit
        self._threshold = snapshot_threshold

    # -- recovery -------------------------------------------------------
    async def recover(self) -> None:
        """Replay the op log; the newest snapshot (if any) resets the
        map and earlier records are irrelevant."""
        records = await self._dq.recover()
        self._data.clear()
        for rec in records:
            self._replay(rec)
        self._keys = sorted(self._data)

    def _replay(self, rec: bytes) -> None:
        op, a, b = _dec_kv(rec)
        if op == _OP_BATCH:
            # one commit = one record: sub-ops apply all-or-nothing, so a
            # torn tail can never surface half a commit (atomics in the
            # storage durability batch must not double-apply on re-pull)
            off = 0
            while off < len(a):
                (ln,) = _U32.unpack_from(a, off)
                self._replay(a[off + 4:off + 4 + ln])
                off += 4 + ln
        elif op == _OP_SNAPSHOT:
            self._data = dict(_iter_snapshot(a))
        elif op == _OP_SET:
            self._data[a] = b
        else:  # clear range [a, b)
            for k in [k for k in self._data if a <= k < b]:
                del self._data[k]

    # -- staged mutations ----------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._data:
            insort(self._keys, key)
        self._data[key] = value
        self._staged.append(_enc_kv(_OP_SET, key, value))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        lo = bisect_left(self._keys, begin)
        hi = bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._data[k]
        del self._keys[lo:hi]
        self._staged.append(_enc_kv(_OP_CLEAR, begin, end))

    # -- reads ----------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def get_range(self, begin: bytes, end: bytes, limit: int = 1 << 30,
                  reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        lo = bisect_left(self._keys, begin)
        hi = bisect_left(self._keys, end)
        ks = self._keys[lo:hi]
        if reverse:
            ks = ks[::-1]
        return [(k, self._data[k]) for k in ks[:limit]]

    def row_count(self) -> int:
        return len(self._keys)

    # -- durability -----------------------------------------------------
    async def commit(self) -> None:
        staged, self._staged = self._staged, []
        if staged:
            blob = b"".join(_U32.pack(len(r)) + r for r in staged)
            await self._dq.push(_enc_kv(_OP_BATCH, blob, b""))
        await self._dq.commit()
        if self._dq.bytes_used > self._threshold:
            await self._snapshot()

    async def _snapshot(self) -> None:
        """Fold the whole map into one log record and pop the history
        (ref: KeyValueStoreMemory::semiCommit snapshot cycle)."""
        blob = b"".join(_U32.pack(len(k)) + k + _U32.pack(len(v)) + v
                        for k, v in sorted(self._data.items()))
        seq = await self._dq.push(_enc_kv(_OP_SNAPSHOT, blob, b""))
        await self._dq.commit()
        self._dq.pop(seq - 1)


def _iter_snapshot(blob: bytes):
    off = 0
    while off < len(blob):
        (lk,) = _U32.unpack_from(blob, off)
        k = blob[off + 4:off + 4 + lk]
        off += 4 + lk
        (lv,) = _U32.unpack_from(blob, off)
        v = blob[off + 4:off + 4 + lv]
        off += 4 + lv
        yield k, v
