"""ConsistencyCheck: full-database replica comparison + shard
accounting at a quiesced version.

Ref: fdbserver/workloads/ConsistencyCheck.actor.cpp (reads every shard
from every replica and byte-compares), tester.actor.cpp:741-765 (the
sweep runs after sim tests once the database is quiet). Here the sweep
is an async function over a SimCluster: quiesce, then for every shard
read the full range from EVERY replica through the same storage
endpoints clients use and require byte-for-byte agreement, plus check
that the shard map partitions the keyspace exactly.
"""

from __future__ import annotations

from .. import flow
from ..flow import TaskPriority, error
from .types import StorageGetRangeRequest

# the sweep's page size lives in the CONSISTENCY_CHECK_PAGE_ROWS knob:
# chunked like the reference's range reads so a huge shard cannot
# produce an unbounded reply (BUGGIFY shrinks it so paging is exercised)


class ConsistencyError(AssertionError):
    """A replica divergence or shard-accounting violation."""


async def _read_replica(rep, begin: bytes, end, version: int, process):
    """Full contents of [begin, end) from one replica, paged."""
    out = []
    cursor = begin
    page_rows = int(flow.SERVER_KNOBS.consistency_check_page_rows)
    # an open-ended last shard is swept through the stored system rows
    # too (\xff\x02 is replicated data); \xff\xff engine metadata is not
    hard_end = end if end is not None else b"\xff\xff"
    while True:
        rows = await flow.timeout_error(rep.ranges.get_reply(
            StorageGetRangeRequest(cursor, hard_end, version, page_rows),
            process), flow.SERVER_KNOBS.consistency_check_read_timeout)
        out.extend(rows)
        if len(rows) < page_rows:
            return out
        cursor = rows[-1][0] + b"\x00"


async def _quiesce_via_status(db, max_wait: float = 60.0) -> None:
    """Client-surface settling: poll the status document until the
    cluster is recovered and every replica has caught up to the log's
    durable frontier (ref: QuietDatabase's caught-up checks, but
    through StatusClient so a remote tool can run the sweep over TCP —
    the in-sim quiet_database reaches into role objects instead). A
    fully EMPTY log queue is not required: background traffic (the
    latency probe) keeps the tail entry pinned on a live cluster; the
    sweep reads at a GRV, so zero replica lag is the property that
    matters."""
    deadline = flow.now() + max_wait
    while True:
        try:
            st = (await db.get_status())["cluster"]
        except flow.FdbError as e:
            if e.name == "client_invalid_operation":
                # no status endpoint on this connection at all —
                # polling for 60s cannot fix that; fail immediately
                # with the real cause instead of a generic timeout
                raise
            st = {}
        logs = st.get("logs", [])
        reps = [r for s in st.get("storages", []) for r in s["replicas"]]
        frontier = max((l.get("durable_version", 0) for l in logs),
                       default=0)
        if st.get("recovery_state") == "fully_recovered" and logs \
                and reps \
                and all(r.get("version", -1) >= frontier for r in reps):
            return
        if flow.now() > deadline:
            raise error("timed_out")
        await flow.delay(flow.SERVER_KNOBS.quiet_database_poll,
                         TaskPriority.DEFAULT_ENDPOINT)


async def check_consistency(target, quiesce: bool = True) -> dict:
    """Sweep every shard from every replica; raise ConsistencyError on
    any divergence. Returns accounting: shards checked, replicas read,
    total rows (ref: ConsistencyCheck's performQuiescentChecks).

    `target` is a Database — in-sim or a RemoteDatabase over TCP: the
    sweep uses only the client surface (broadcast shard refs, GRVs,
    storage range reads, status), so `consistencycheck` works against
    a tools.server cluster the same as in simulation. A SimCluster is
    also accepted (the test harness shape), which additionally enables
    the stronger in-sim quiesce."""
    cluster = None
    db = target
    if not hasattr(target, "create_transaction"):
        cluster = target
        db = getattr(cluster, "_consistency_db", None)
        if db is None:
            db = cluster._consistency_db = \
                cluster.client("consistency-check")
    if quiesce:
        if cluster is not None:
            await cluster.quiet_database()
        else:
            await _quiesce_via_status(db)
    info = await db.info()
    proc = db.process
    # shard accounting: the shard map must partition [b"", +inf)
    # exactly — no gaps, no overlaps, ordered boundaries
    shards = info.storages
    if not shards:
        raise ConsistencyError("no shards in the published picture")
    if shards[0].begin != b"":
        raise ConsistencyError(
            f"first shard begins at {shards[0].begin!r}, not b''")
    for a, b in zip(shards, shards[1:]):
        if a.end != b.begin:
            raise ConsistencyError(
                f"shard gap/overlap: [..{a.end!r}) then [{b.begin!r}..)")
    if shards[-1].end is not None:
        raise ConsistencyError(
            f"last shard ends at {shards[-1].end!r}, not +inf")

    # read point: a GRV from the commit pipeline — after quiescence it
    # IS the log frontier every replica has reached; replicas slightly
    # behind it block (bounded by the read timeout) rather than serve
    # stale rows (ref: the workload's reads at a transaction version)
    version, _seq = await db.batched_grv()

    n_replicas = 0
    n_rows = 0
    expect_team = None
    for shard in shards:
        if not shard.replicas:
            raise ConsistencyError(
                f"shard [{shard.begin!r}..) has no replicas")
        if expect_team is None:
            expect_team = len(shard.replicas)
        elif len(shard.replicas) != expect_team:
            raise ConsistencyError(
                f"shard [{shard.begin!r}..) has {len(shard.replicas)} "
                f"replicas, others have {expect_team}")
        contents = []
        for rep in shard.replicas:
            try:
                rows = await _read_replica(rep, shard.begin, shard.end,
                                           version, proc)
            except flow.FdbError as e:
                raise ConsistencyError(
                    f"replica {rep.name} of [{shard.begin!r}..) "
                    f"unreadable: {e.name}") from None
            contents.append((rep.name, rows))
            n_replicas += 1
        base_name, base = contents[0]
        for name, rows in contents[1:]:
            if rows != base:
                detail = _first_divergence(base, rows)
                raise ConsistencyError(
                    f"replicas {base_name} and {name} of shard "
                    f"[{shard.begin!r}..{shard.end!r}) diverge: {detail}")
        n_rows += len(base)
    flow.TraceEvent("ConsistencyCheckOK").detail(
        Shards=len(shards), Replicas=n_replicas, Rows=n_rows).log()
    return {"shards": len(shards), "replicas": n_replicas,
            "rows": n_rows, "version": version}


def _first_divergence(a, b) -> str:
    da, db = dict(a), dict(b)
    for k in sorted(set(da) | set(db)):
        if da.get(k) != db.get(k):
            return (f"key {k!r}: {da.get(k)!r} vs {db.get(k)!r}")
    return f"row counts {len(a)} vs {len(b)}"
