"""ConsistencyCheck: full-database replica comparison + shard
accounting at a quiesced version.

Ref: fdbserver/workloads/ConsistencyCheck.actor.cpp (reads every shard
from every replica and byte-compares), tester.actor.cpp:741-765 (the
sweep runs after sim tests once the database is quiet). Here the sweep
is an async function over a SimCluster: quiesce, then for every shard
read the full range from EVERY replica through the same storage
endpoints clients use and require byte-for-byte agreement, plus check
that the shard map partitions the keyspace exactly.
"""

from __future__ import annotations

from .. import flow
from ..flow import TaskPriority, error
from .types import StorageGetRangeRequest

# the sweep's page size lives in the CONSISTENCY_CHECK_PAGE_ROWS knob:
# chunked like the reference's range reads so a huge shard cannot
# produce an unbounded reply (BUGGIFY shrinks it so paging is exercised)


class ConsistencyError(AssertionError):
    """A replica divergence or shard-accounting violation."""


async def _read_replica(rep, begin: bytes, end, version: int, process):
    """Full contents of [begin, end) from one replica, paged."""
    out = []
    cursor = begin
    page_rows = int(flow.SERVER_KNOBS.consistency_check_page_rows)
    # an open-ended last shard is swept through the stored system rows
    # too (\xff\x02 is replicated data); \xff\xff engine metadata is not
    hard_end = end if end is not None else b"\xff\xff"
    while True:
        rows = await flow.timeout_error(rep.ranges.get_reply(
            StorageGetRangeRequest(cursor, hard_end, version, page_rows),
            process), flow.SERVER_KNOBS.consistency_check_read_timeout)
        out.extend(rows)
        if len(rows) < page_rows:
            return out
        cursor = rows[-1][0] + b"\x00"


async def check_consistency(cluster, quiesce: bool = True) -> dict:
    """Sweep every shard from every replica; raise ConsistencyError on
    any divergence. Returns accounting: shards checked, replicas read,
    total rows (ref: ConsistencyCheck's performQuiescentChecks)."""
    if quiesce:
        await cluster.quiet_database()
    info = cluster.cc.dbinfo.get()
    proc = cluster.cc.process
    # shard accounting: the shard map must partition [b"", +inf)
    # exactly — no gaps, no overlaps, ordered boundaries
    shards = info.storages
    if not shards:
        raise ConsistencyError("no shards in the published picture")
    if shards[0].begin != b"":
        raise ConsistencyError(
            f"first shard begins at {shards[0].begin!r}, not b''")
    for a, b in zip(shards, shards[1:]):
        if a.end != b.begin:
            raise ConsistencyError(
                f"shard gap/overlap: [..{a.end!r}) then [{b.begin!r}..)")
    if shards[-1].end is not None:
        raise ConsistencyError(
            f"last shard ends at {shards[-1].end!r}, not +inf")

    # quiesced read point: the log frontier every replica has reached
    version = max(t.version.get() for t in cluster.cc.tlog_objs())

    n_replicas = 0
    n_rows = 0
    expect_team = None
    for shard in shards:
        if not shard.replicas:
            raise ConsistencyError(
                f"shard [{shard.begin!r}..) has no replicas")
        if expect_team is None:
            expect_team = len(shard.replicas)
        elif len(shard.replicas) != expect_team:
            raise ConsistencyError(
                f"shard [{shard.begin!r}..) has {len(shard.replicas)} "
                f"replicas, others have {expect_team}")
        contents = []
        for rep in shard.replicas:
            try:
                rows = await _read_replica(rep, shard.begin, shard.end,
                                           version, proc)
            except flow.FdbError as e:
                raise ConsistencyError(
                    f"replica {rep.name} of [{shard.begin!r}..) "
                    f"unreadable: {e.name}") from None
            contents.append((rep.name, rows))
            n_replicas += 1
        base_name, base = contents[0]
        for name, rows in contents[1:]:
            if rows != base:
                detail = _first_divergence(base, rows)
                raise ConsistencyError(
                    f"replicas {base_name} and {name} of shard "
                    f"[{shard.begin!r}..{shard.end!r}) diverge: {detail}")
        n_rows += len(base)
    flow.TraceEvent("ConsistencyCheckOK").detail(
        Shards=len(shards), Replicas=n_replicas, Rows=n_rows).log()
    return {"shards": len(shards), "replicas": n_replicas,
            "rows": n_rows, "version": version}


def _first_divergence(a, b) -> str:
    da, db = dict(a), dict(b)
    for k in sorted(set(da) | set(db)):
        if da.get(k) != db.get(k):
            return (f"key {k!r}: {da.get(k)!r} vs {db.get(k)!r}")
    return f"row counts {len(a)} vs {len(b)}"
