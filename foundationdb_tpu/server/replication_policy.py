"""Replication policy algebra over worker localities.

Reference: fdbrpc/ReplicationPolicy.h:101-168 — PolicyOne / PolicyAcross
/ PolicyAnd trees evaluated against LocalityData attribute sets
(flow/Locality.h), used by recruitment and team building to place
replicas across failure domains ("one per zone", "two per dc, each in a
distinct zone"). validate() checks an existing team; select() builds
one from candidates.

Selection walks attribute groups in candidate order (deterministic for
the simulator); because the groups partition the candidates, a greedy
scan that skips unsatisfiable groups is complete — no backtracking is
needed across disjoint groups.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class Locality:
    """Attribute set naming a process's failure domains (ref:
    flow/Locality.h LocalityData — processid/zoneid/machineid/dcid)."""

    __slots__ = ("attrs",)

    def __init__(self, **attrs: str):
        self.attrs = attrs

    def get(self, key: str) -> Optional[str]:
        return self.attrs.get(key)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Locality({self.attrs})"


Candidate = Tuple[object, Locality]


class ReplicationPolicy:
    def validate(self, localities: Sequence[Locality]) -> bool:
        raise NotImplementedError

    def select(self, candidates: Sequence[Candidate]
               ) -> Optional[List[object]]:
        """A team satisfying the policy drawn from candidates, or None."""
        raise NotImplementedError

    def replica_count(self) -> int:
        raise NotImplementedError


class PolicyOne(ReplicationPolicy):
    """Any single replica (ref: PolicyOne)."""

    def validate(self, localities: Sequence[Locality]) -> bool:
        return len(localities) >= 1

    def select(self, candidates: Sequence[Candidate]
               ) -> Optional[List[object]]:
        return [candidates[0][0]] if candidates else None

    def replica_count(self) -> int:
        return 1

    def __repr__(self) -> str:  # pragma: no cover
        return "One()"


class PolicyAcross(ReplicationPolicy):
    """`count` groups with distinct values of `attrib`, each group
    internally satisfying `inner` (ref: PolicyAcross — "Across(2,
    zoneid, One())" = two replicas in two different zones)."""

    def __init__(self, count: int, attrib: str, inner: ReplicationPolicy):
        self.count = count
        self.attrib = attrib
        self.inner = inner

    def validate(self, localities: Sequence[Locality]) -> bool:
        groups: Dict[str, List[Locality]] = {}
        for loc in localities:
            v = loc.get(self.attrib)
            if v is None:
                continue
            groups.setdefault(v, []).append(loc)
        ok = sum(1 for g in groups.values() if self.inner.validate(g))
        return ok >= self.count

    def select(self, candidates: Sequence[Candidate]
               ) -> Optional[List[object]]:
        groups: Dict[str, List[Candidate]] = {}
        order: List[str] = []
        for cand in candidates:
            v = cand[1].get(self.attrib)
            if v is None:
                continue
            if v not in groups:
                order.append(v)
            groups.setdefault(v, []).append(cand)
        team: List[object] = []
        filled = 0
        for v in order:
            if filled == self.count:
                break
            sub = self.inner.select(groups[v])
            if sub is not None:
                team.extend(sub)
                filled += 1
        return team if filled == self.count else None

    def replica_count(self) -> int:
        return self.count * self.inner.replica_count()

    def __repr__(self) -> str:  # pragma: no cover
        return f"Across({self.count},{self.attrib},{self.inner!r})"


class PolicyAnd(ReplicationPolicy):
    """All sub-policies must hold over the same team (ref: PolicyAnd).

    select() builds with the most demanding policy (largest replica
    count) and checks the rest validate over the result; a combination
    needing a team no single sub-policy would build returns None —
    matching the reference's best-effort PolicyAnd selection.
    """

    def __init__(self, policies: Sequence[ReplicationPolicy]):
        self.policies = list(policies)

    def validate(self, localities: Sequence[Locality]) -> bool:
        return all(p.validate(localities) for p in self.policies)

    def select(self, candidates: Sequence[Candidate]
               ) -> Optional[List[object]]:
        by_id = {id(c[0]): c[1] for c in candidates}
        for lead in sorted(self.policies, key=lambda p: -p.replica_count()):
            team = lead.select(candidates)
            if team is None:
                continue
            locs = [by_id[id(m)] for m in team]
            if all(p.validate(locs) for p in self.policies):
                return team
        return None

    def replica_count(self) -> int:
        return max((p.replica_count() for p in self.policies), default=0)

    def __repr__(self) -> str:  # pragma: no cover
        return f"And({self.policies!r})"
