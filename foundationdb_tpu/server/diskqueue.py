"""DiskQueue: a durable, poppable FIFO of byte records over two files.

Reference: fdbserver/DiskQueue.actor.cpp / IDiskQueue.h — the write-
ahead log under both the TLog and the memory KV engine. Capabilities
re-implemented (not ported):

  - push(bytes) appends a record; commit() makes everything pushed so
    far durable (one sync) and resolves only after the fsync;
  - pop(up_to) logically discards the oldest records; space is
    reclaimed by truncating a file once every record in it is popped
    (the reference's two-file alternation — a real disk cannot trim a
    file's front);
  - recovery scans both files and yields exactly the records of the
    longest valid committed prefix: each record carries a checksum and
    a monotone sequence number, so a torn tail (power loss mid-write,
    rpc/disk.py semantics) is detected and cut.

Record format (little-endian): [seq u64][len u32][crc32 u32][payload].
A file begins with an 8-byte header: the sequence number of its first
record (so recovery knows which file is older).
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

from .. import flow
from ..rpc.disk import SimDisk, SimFile

_REC_HDR = struct.Struct("<QII")
_FILE_HDR = struct.Struct("<Q")


def walk_records(raw) -> Tuple[List[Tuple[int, bytes, int, int, int]],
                               int, int]:
    """Walk a file image's valid record chain — THE shared format
    walker: recovery's `_scan` and the chaos corruption helpers
    (server/chaos.py) must agree byte-for-byte on what a committed
    record is, so the walk exists exactly once.

    -> ([(seq, payload, payload_off, length, record_off)...], first_seq,
        stop_off) where stop_off is where the chain ended (EOF, or the
    first record that failed a header/length/CRC check). The payload is
    the bytes the CRC check already materialized — recovery keeps it,
    offset-only callers ignore it."""
    if len(raw) < _FILE_HDR.size:
        return [], 1 << 62, 0
    (first_seq,) = _FILE_HDR.unpack_from(raw, 0)
    off = _FILE_HDR.size
    expect = first_seq
    out: List[Tuple[int, bytes, int, int, int]] = []
    while off + _REC_HDR.size <= len(raw):
        seq, length, crc = _REC_HDR.unpack_from(raw, off)
        payload = bytes(raw[off + _REC_HDR.size:
                            off + _REC_HDR.size + length])
        if seq != expect or len(payload) != length \
                or zlib.crc32(payload) != crc:
            break
        out.append((seq, payload, off + _REC_HDR.size, length, off))
        expect += 1
        off += _REC_HDR.size + length
    return out, first_seq, off


class DiskQueue:
    """Two-file durable FIFO. Single writer, cooperative scheduling."""

    def __init__(self, disk: SimDisk, name: str, owner=None,
                 file_size_limit: int = None):
        self._disk = disk
        self._name = name
        self._owner = owner
        if file_size_limit is None:
            from ..flow import SERVER_KNOBS
            file_size_limit = int(SERVER_KNOBS.disk_queue_file_size)
        self._limit = file_size_limit
        self._files: List[SimFile] = [
            disk.open(f"{name}.dq0", owner), disk.open(f"{name}.dq1", owner)]
        # in-memory mirror of the live queue: (seq, payload); a SPILLED
        # record's payload is None — re-read from file via read(seq)
        # (ref: spill-by-reference, the TLog keeping DiskQueue positions
        # instead of values once memory exceeds the spill threshold)
        self._records: List[Tuple[int, Optional[bytes]]] = []
        self._offsets: dict = {}   # seq -> (file_idx, payload_off, length)
        self._next_seq = 0
        self._popped_seq = -1  # highest seq discarded
        self._cur = 0          # index of the file being appended
        self._append_off = [0, 0]
        self._file_first_seq = [0, 0]
        self._file_last_seq = [-1, -1]
        self._unsynced = False
        self._recovered = False

    # -- recovery -------------------------------------------------------
    async def recover(self) -> List[bytes]:
        """Scan both files; rebuild state; return surviving payloads in
        order (ref: DiskQueue::initializeRecovery + readNext).

        The valid data is the longest strictly-sequential record prefix
        across both files (older file first). Everything past it —
        torn tails AND whole stale files whose sequences fall outside
        the prefix — is physically truncated, so a regrown sequence can
        never collide with stale records at a later recovery.

        DETECTED corruption — a record whose header chain is intact but
        whose payload fails its checksum, with a VALID successor record
        chained right behind it — raises checksum_failed instead of
        silently cutting: records are appended in single writes, so
        power loss can only damage a suffix (drop whole writes / tear
        the final one); an intact chain continuing past a bad checksum
        means the bytes rotted AFTER they were written, i.e. media
        corruption of possibly-acked data. The caller treats that as a
        recoverable role death (the store is lost, replication heals),
        never as a quietly shorter log."""
        scans = [await self._scan(f) for f in self._files]
        if any(corrupt for _recs, _first, corrupt in scans):
            flow.cover("diskqueue.corruption_detected")
            raise flow.error("checksum_failed")
        order = sorted(range(2), key=lambda i: scans[i][1])
        all_recs: List[Tuple[int, bytes, int, int]] = []  # seq,payload,file,end
        for i in order:
            recs, _first, _corrupt = scans[i]
            all_recs.extend((seq, payload, i, end) for seq, payload, end in recs)
        valid: List[Tuple[int, bytes, int, int]] = []
        expect = all_recs[0][0] if all_recs else 0
        for seq, payload, i, end in all_recs:
            if seq != expect:
                break
            valid.append((seq, payload, i, end))
            expect += 1

        # per-file: truncate to the last byte of its last valid record
        # (or wipe entirely if it holds none)
        keep_end = [0, 0]
        self._file_first_seq = [1 << 62, 1 << 62]
        self._file_last_seq = [-1, -1]
        self._offsets = {}
        for seq, payload, i, end in valid:
            keep_end[i] = end
            self._file_first_seq[i] = min(self._file_first_seq[i], seq)
            self._file_last_seq[i] = max(self._file_last_seq[i], seq)
            self._offsets[seq] = (i, end - len(payload), len(payload))
        for i in range(2):
            await self._files[i].truncate(keep_end[i])
            self._append_off[i] = keep_end[i]

        self._records = [(seq, payload) for seq, payload, _i, _e in valid]
        self._next_seq = (valid[-1][0] + 1) if valid else 0
        self._popped_seq = (valid[0][0] - 1) if valid else self._next_seq - 1
        self._cur = valid[-1][2] if valid else 0
        self._recovered = True
        return [p for _s, p in self._records]

    async def _scan(self, f: SimFile):
        """-> ([(seq, payload, end_offset)...], first_seq, corrupted)."""
        size = await f.size()
        if size < _FILE_HDR.size:
            return [], 1 << 62, False
        raw = await f.read(0, size)
        walked, first_seq, stop = walk_records(raw)
        corrupted = False
        if stop + _REC_HDR.size <= size:
            # the chain broke on a parseable header: classify the hole
            seq, length, crc = _REC_HDR.unpack_from(raw, stop)
            payload = bytes(raw[stop + _REC_HDR.size:
                                stop + _REC_HDR.size + length])
            expect = walked[-1][0] + 1 if walked else first_seq
            corrupted = self._is_corruption_hole(
                raw, size, stop, expect, seq, length, payload, crc)
            if not corrupted:
                flow.cover("diskqueue.torn_tail_dropped")
        recs = [(seq, payload, poff + length)
                for seq, payload, poff, length, _off in walked]
        if not recs:
            return [], 1 << 62, corrupted
        return recs, first_seq, corrupted

    @staticmethod
    def _is_corruption_hole(raw, size, off, expect, seq, length, payload,
                            crc) -> bool:
        """Bad record with an intact header AND a valid successor right
        behind it ⇒ mid-log corruption, not tail damage (each record is
        one write, so power loss only damages a suffix of the chain)."""
        if seq != expect or len(payload) != length \
                or zlib.crc32(payload) == crc:
            return False   # header damage or actually fine: tail cases
        nxt = off + _REC_HDR.size + length
        if nxt + _REC_HDR.size > size:
            return False   # nothing behind it: indistinguishable tear
        nseq, nlen, ncrc = _REC_HDR.unpack_from(raw, nxt)
        npay = bytes(raw[nxt + _REC_HDR.size:nxt + _REC_HDR.size + nlen])
        return (nseq == expect + 1 and len(npay) == nlen
                and zlib.crc32(npay) == ncrc)

    # -- writing --------------------------------------------------------
    async def _write_file_header(self, i: int, first_seq: int) -> None:
        await self._files[i].write(0, _FILE_HDR.pack(first_seq))
        self._append_off[i] = _FILE_HDR.size
        self._file_first_seq[i] = first_seq
        self._file_last_seq[i] = -1

    async def push(self, payload: bytes) -> int:
        """Append one record (not yet durable); returns its seq."""
        assert self._recovered, "recover() before use"
        seq = self._next_seq
        self._next_seq += 1
        i = self._cur
        if self._append_off[i] == 0:
            await self._write_file_header(i, seq)
        rec = _REC_HDR.pack(seq, len(payload), zlib.crc32(payload)) + payload
        await self._files[i].write(self._append_off[i], rec)
        self._offsets[seq] = (i, self._append_off[i] + _REC_HDR.size,
                             len(payload))
        self._append_off[i] += len(rec)
        self._file_last_seq[i] = seq
        self._records.append((seq, payload))
        self._unsynced = True
        # roll to the other file when full AND it is free (fully popped)
        other = 1 - i
        if (self._append_off[i] >= self._limit
                and self._file_last_seq[other] <= self._popped_seq):
            await self._files[other].truncate(0)
            self._append_off[other] = 0
            self._file_first_seq[other] = 1 << 62
            self._file_last_seq[other] = -1
            self._cur = other
        return seq

    async def commit(self) -> None:
        """Durability barrier for all pushes so far (ref: doQueueCommit:
        sync both files — header writes may touch the spare)."""
        assert self._recovered
        if not self._unsynced:
            return
        self._unsynced = False
        for f in self._files:
            await f.sync()

    def pop(self, up_to_seq: int) -> None:
        """Logically discard records with seq <= up_to_seq; physical
        space reclaim happens at the next file roll."""
        if up_to_seq <= self._popped_seq:
            return
        self._popped_seq = up_to_seq
        idx = 0
        recs = self._records
        while idx < len(recs) and recs[idx][0] <= up_to_seq:
            self._offsets.pop(recs[idx][0], None)
            idx += 1
        del recs[:idx]

    # -- spill ----------------------------------------------------------
    def spill(self, up_to_seq: int) -> None:
        """Drop the in-memory payloads of committed records with
        seq <= up_to_seq; they remain durable on disk and readable via
        read(seq) (ref: TLog spill-by-reference — updatePersistentData
        keeping DiskQueue locations instead of values)."""
        for k, (seq, payload) in enumerate(self._records):
            if seq > up_to_seq:
                break
            if payload is not None:
                self._records[k] = (seq, None)

    async def read(self, seq: int) -> Optional[bytes]:
        """A committed record's payload straight from its file (the
        spilled-peek path). None if the record is gone — popped before
        the lookup, OR its file truncated by a roll while the read was
        in flight (the header re-validates seq + crc, so a racing
        truncation can never surface as garbage)."""
        loc = self._offsets.get(seq)
        if loc is None:
            return None
        i, off, length = loc
        raw = await self._files[i].read(off - _REC_HDR.size,
                                        _REC_HDR.size + length)
        if len(raw) < _REC_HDR.size + length:
            return None
        got_seq, got_len, crc = _REC_HDR.unpack_from(raw, 0)
        payload = bytes(raw[_REC_HDR.size:])
        if got_seq != seq or got_len != length or \
                zlib.crc32(payload) != crc:
            return None
        return payload

    # -- introspection --------------------------------------------------
    @property
    def records(self) -> List[Tuple[int, bytes]]:
        """Live (unpopped) records, oldest first."""
        return self._records

    @property
    def next_seq(self) -> int:
        return self._next_seq

    @property
    def bytes_used(self) -> int:
        """In-MEMORY bytes (spilled payloads don't count)."""
        return sum(len(p) for _, p in self._records if p is not None)
