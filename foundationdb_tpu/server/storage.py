"""Versioned in-memory storage server role.

Reference: fdbserver/storageserver.actor.cpp — a 5-second MVCC window in
a versioned map (:265-306) updated by pulling the log (`update` :2461,
applyMutation :1664), serving `getValueQ` (:763) and `getKeyValues`
(:1274) at a requested version, waiting for the version to arrive and
throwing future_version if it is too far ahead. The versioned map here
is per-key version chains + a range-clear list over a bisect-sorted key
index (the PTree of fdbclient/VersionedMap.h:43 re-expressed for host
Python; the TPU-resident sorted-array engine reuses ops/keys.py).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import NotifiedVersion, TaskPriority, error
from ..rpc import NetworkRef, RequestStream, SimProcess
from . import atomic
from .types import (ADD_VALUE, AND, APPEND_IF_FITS, BYTE_MAX, BYTE_MIN,
                    CLEAR_RANGE, COMPARE_AND_CLEAR, KeySelector, MAX, MIN,
                    MutationRef, OR, SET_VALUE, StorageGetKeyRequest,
                    StorageGetRangeRequest, StorageGetRequest,
                    StorageWatchRequest, TLogPeekRequest, XOR)

MAX_READ_AHEAD_VERSIONS = 5_000_000  # ref: MAX_READ_TRANSACTION_LIFE_VERSIONS

_ATOMIC_APPLY = {
    ADD_VALUE: atomic.add,
    AND: atomic.bit_and,
    OR: atomic.bit_or,
    XOR: atomic.bit_xor,
    APPEND_IF_FITS: atomic.append_if_fits,
    MAX: atomic.vmax,
    MIN: atomic.vmin,
    BYTE_MIN: atomic.byte_min,
    BYTE_MAX: atomic.byte_max,
    COMPARE_AND_CLEAR: atomic.compare_and_clear,
}


class VersionedMap:
    """Per-key version chains + version-stamped range clears."""

    def __init__(self):
        self._keys: List[bytes] = []           # sorted index
        self._chains: Dict[bytes, List[Tuple[int, Optional[bytes]]]] = {}
        self._clears: List[Tuple[int, bytes, bytes]] = []

    def _set(self, version: int, key: bytes, value: Optional[bytes]) -> None:
        chain = self._chains.get(key)
        if chain is None:
            self._chains[key] = [(version, value)]
            insort(self._keys, key)
        else:
            chain.append((version, value))

    def apply(self, version: int, m: MutationRef) -> None:
        if m.type == SET_VALUE:
            self._set(version, m.param1, m.param2)
        elif m.type == CLEAR_RANGE:
            self._clears.append((version, m.param1, m.param2))
            i = bisect_left(self._keys, m.param1)
            while i < len(self._keys) and self._keys[i] < m.param2:
                self._chains[self._keys[i]].append((version, None))
                i += 1
        elif m.type in _ATOMIC_APPLY:
            # read-modify-write at apply time, in version order (ref:
            # storageserver applyMutation -> Atomic.h apply functions)
            existing = self.get(m.param1, version)
            self._set(version, m.param1, _ATOMIC_APPLY[m.type](existing,
                                                               m.param2))
        else:
            raise error("client_invalid_operation")

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        chain = self._chains.get(key)
        if not chain:
            return None
        for v, val in reversed(chain):
            if v <= version:
                return val
        return None

    def get_range(self, begin: bytes, end: bytes, version: int,
                  limit: int, reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        out = []
        if reverse:
            i = bisect_left(self._keys, end) - 1
            while i >= 0 and self._keys[i] >= begin:
                k = self._keys[i]
                val = self.get(k, version)
                if val is not None:
                    out.append((k, val))
                    if len(out) >= limit:
                        break
                i -= 1
            return out
        i = bisect_left(self._keys, begin)
        while i < len(self._keys) and self._keys[i] < end:
            k = self._keys[i]
            val = self.get(k, version)
            if val is not None:
                out.append((k, val))
                if len(out) >= limit:
                    break
            i += 1
        return out

    def resolve_selector(self, sel: KeySelector, version: int) -> bytes:
        """Resolve a KeySelector against the keys present at `version`
        (ref: storageserver findKey / fdbclient KeySelectorRef semantics:
        start from the last key < (or <= when or_equal) the reference
        key, then move `offset` present keys forward). Clamps to b'' on
        underflow and to \\xff on overflow."""
        present = [k for k in self._keys if self.get(k, version) is not None]
        if sel.or_equal:
            base = bisect_right(present, sel.key) - 1
        else:
            base = bisect_left(present, sel.key) - 1
        idx = base + sel.offset
        if idx < 0:
            return b""
        if idx >= len(present):
            return b"\xff"
        return present[idx]


class StorageServer:
    def __init__(self, process: SimProcess, tlog_peek: NetworkRef):
        self.process = process
        self.tlog_peek = tlog_peek
        self.data = VersionedMap()
        self.version = NotifiedVersion(0)
        self.gets = RequestStream(process)
        self.ranges = RequestStream(process)
        self.get_keys = RequestStream(process)
        self.watches = RequestStream(process)
        # key -> list of (value_at_registration, reply)
        self._watch_map: Dict[bytes, list] = {}
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        for coro, prio, name in (
                (self._pull_loop(), TaskPriority.UPDATE_STORAGE, "pull"),
                (self._get_loop(), TaskPriority.STORAGE, "get"),
                (self._range_loop(), TaskPriority.STORAGE, "getrange"),
                (self._get_key_loop(), TaskPriority.STORAGE, "getkey"),
                (self._watch_loop(), TaskPriority.STORAGE, "watch")):
            self._actors.add(flow.spawn(coro, prio,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    async def _pull_loop(self):
        """Pull committed mutations from the log (ref: update :2461)."""
        while True:
            reply = await self.tlog_peek.get_reply(
                TLogPeekRequest(self.version.get() + 1), self.process)
            for version, mutations in reply.entries:
                if version <= self.version.get():
                    continue
                for m in mutations:
                    self.data.apply(version, m)
                self.version.set(version)
                self._check_watches(version, mutations)
            if reply.committed_version > self.version.get():
                self.version.set(reply.committed_version)

    # -- watches --------------------------------------------------------
    def _check_watches(self, version: int, mutations) -> None:
        """Fire watches whose key's value changed (ref: storageserver
        watch triggering on mutation apply)."""
        if not self._watch_map:
            return
        touched = set()
        for m in mutations:
            if m.type == CLEAR_RANGE:
                touched.update(k for k in self._watch_map
                               if m.param1 <= k < m.param2)
            else:
                if m.param1 in self._watch_map:
                    touched.add(m.param1)
        for k in touched:
            waiters = self._watch_map.get(k, [])
            still = []
            now_val = self.data.get(k, version)
            for expected, reply in waiters:
                if now_val != expected:
                    reply.send(version)
                else:
                    still.append((expected, reply))
            if still:
                self._watch_map[k] = still
            else:
                self._watch_map.pop(k, None)

    async def _wait_version(self, version: int):
        """(ref: waitForVersion — future_version when too far ahead)"""
        if version > self.version.get() + MAX_READ_AHEAD_VERSIONS:
            raise error("future_version")
        await self.version.when_at_least(version)

    async def _get_loop(self):
        while True:
            req, reply = await self.gets.pop()
            flow.spawn(self._serve_get(req, reply), TaskPriority.STORAGE)

    async def _serve_get(self, req: StorageGetRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.get(req.key, req.version))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _range_loop(self):
        while True:
            req, reply = await self.ranges.pop()
            flow.spawn(self._serve_range(req, reply), TaskPriority.STORAGE)

    async def _serve_range(self, req: StorageGetRangeRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.get_range(req.begin, req.end, req.version,
                                           req.limit, req.reverse))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _get_key_loop(self):
        while True:
            req, reply = await self.get_keys.pop()
            flow.spawn(self._serve_get_key(req, reply), TaskPriority.STORAGE)

    async def _serve_get_key(self, req: StorageGetKeyRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.resolve_selector(req.selector, req.version))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _watch_loop(self):
        while True:
            req, reply = await self.watches.pop()
            flow.spawn(self._serve_watch(req, reply), TaskPriority.STORAGE)

    async def _serve_watch(self, req: StorageWatchRequest, reply):
        try:
            await self._wait_version(req.version)
            expected = self.data.get(req.key, req.version)
            current = self.data.get(req.key, self.version.get())
            if current != expected:
                reply.send(self.version.get())
                return
            self._watch_map.setdefault(req.key, []).append((expected, reply))
        except flow.FdbError as e:
            reply.send_error(e)
