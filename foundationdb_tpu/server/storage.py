"""Storage server role: a versioned MVCC window over a durable engine.

Reference: fdbserver/storageserver.actor.cpp — a 5-second MVCC window in
a versioned map (:265-306) updated by pulling the log (`update` :2461,
applyMutation :1664), serving `getValueQ` (:763) and `getKeyValues`
(:1274) at a requested version. Durability (updateStorage): the oldest
window versions are applied to the persistent engine
(IKeyValueStore — kvstore.py), the durable version is persisted with
them, the log is popped up to it, and the window forgets what became
durable, so memory stays bounded at the MVCC window (round-1 VERDICT:
chains grew forever). Reads below the durable (oldest) version raise
transaction_too_old; reads too far ahead raise future_version.

On reboot the server recovers the engine, resumes from the persisted
durable version, and re-pulls the rest from the TLog.
"""

from __future__ import annotations

import math
import struct
import zlib
from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import (SERVER_KNOBS, Future, NotifiedVersion, TaskPriority,
                    error)
from ..rpc import NetworkRef, RequestStream, SimProcess
from . import atomic
from .kvstore import IKeyValueStore
from .types import (ADD_VALUE, AND, AND_V2, APPEND_IF_FITS, BYTE_MAX,
                    BYTE_MIN, CLEAR_RANGE, COMPARE_AND_CLEAR, INERT_OPS,
                    KeySelector, MAX, MIN, MIN_V2, MutationRef, OR,
                    SET_VALUE, StorageGetKeyRequest,
                    StorageGetRangeRequest, StorageGetRequest,
                    StorageWatchRequest, TLogPeekRequest, TLogPopRequest,
                    XOR)

DURABLE_VERSION_KEY = b"\xff\xff/storageDurableVersion"
SHARD_META_KEY = b"\xff\xff/shardMeta"   # persisted tag + owned range
_NO_HINT = object()  # sentinel: _get_hinted must consult the base engine


class StorageMetrics:
    """Sampled byte metrics + smoothed write bandwidth for DD
    decisions (ref: storageserver.actor.cpp:310-312 byteSample — each
    entry is sampled with probability min(1, size/factor) and recorded
    at weight max(size, factor), an unbiased estimator of total bytes
    whose memory cost is O(total/factor); StorageMetrics.actor.h:302
    splitMetrics picking byte-balanced split points). Inclusion is a
    deterministic hash of the key so every replica samples
    identically and sim runs replay exactly."""

    __slots__ = ("_sample", "_keys", "_total", "_rate", "_rate_t",
                 "_prefix", "_read_sample", "_read_rate", "_read_ops",
                 "_read_t")

    def __init__(self):
        self._sample: Dict[bytes, int] = {}
        self._keys: List[bytes] = []   # sorted index over the sample
        self._total = 0                # running sum of sampled weights
        self._rate = 0.0               # smoothed write bytes/sec
        self._rate_t: Optional[float] = None
        # lazily rebuilt prefix sums over _keys' weights: range-bytes
        # queries and split_key become two bisects + O(log n) instead
        # of an O(range) sum (the CC split scan calls them per shard
        # per tick). None = stale; any sample mutation invalidates.
        self._prefix: Optional[List[int]] = None
        # -- read side (ISSUE 13): deterministic crc32-sampled read
        # bandwidth per key + shard-wide leaky read meters. key ->
        # [decayed bytes/sec, last update]; bounded by
        # READ_SAMPLE_MAX_KEYS (lowest decayed rate evicted)
        self._read_sample: Dict[bytes, list] = {}
        self._read_rate = 0.0          # smoothed read bytes/sec
        self._read_ops = 0.0           # smoothed read ops/sec
        self._read_t: Optional[float] = None

    @staticmethod
    def _weight(key: bytes, nbytes: int) -> int:
        factor = SERVER_KNOBS.byte_sample_factor
        if nbytes >= factor:
            return nbytes
        if zlib.crc32(key) / 0xFFFFFFFF < nbytes / factor:
            return factor
        return 0

    def note_set(self, key: bytes, nbytes: int) -> None:
        w = self._weight(key, nbytes)
        old = self._sample.get(key)
        if w:
            self._sample[key] = w
            self._total += w - (old or 0)
            if old is None:
                insort(self._keys, key)
            self._prefix = None
        elif old is not None:
            del self._sample[key]
            self._total -= old
            del self._keys[bisect_left(self._keys, key)]
            self._prefix = None

    def note_clear(self, begin: bytes, end: bytes) -> None:
        i = bisect_left(self._keys, begin)
        j = bisect_left(self._keys, end)
        if i == j:
            return
        for k in self._keys[i:j]:
            self._total -= self._sample.pop(k)
        del self._keys[i:j]
        self._prefix = None

    def apply(self, m: MutationRef) -> None:
        if m.type == CLEAR_RANGE:
            self.note_clear(m.param1, m.param2)
        elif m.type not in INERT_OPS:
            # atomics: the result's size is approximated by the
            # operand's (exact for set, bounded for the fold ops)
            self.note_set(m.param1,
                          len(m.param1) + len(m.param2 or b""))

    def rebuild(self, rows) -> None:
        self._sample.clear()
        self._keys.clear()
        self._total = 0
        self._prefix = None
        for k, v in rows:
            self.note_set(k, len(k) + len(v))

    def _prefix_sums(self) -> List[int]:
        """prefix[i] = sum of sampled weights of _keys[:i]; rebuilt
        lazily after a sample mutation, so a tick's worth of
        sampled_bytes/split_key/read-hot queries share one O(n) pass."""
        ps = self._prefix
        if ps is None or len(ps) != len(self._keys) + 1:
            ps = [0] * (len(self._keys) + 1)
            acc = 0
            sample = self._sample
            for i, k in enumerate(self._keys):
                acc += sample[k]
                ps[i + 1] = acc
            self._prefix = ps
        return ps

    def sampled_bytes(self, begin: bytes = b"",
                      end: Optional[bytes] = None) -> int:
        if begin == b"" and end is None:
            return self._total
        ps = self._prefix_sums()
        i = bisect_left(self._keys, begin)
        j = (bisect_left(self._keys, end) if end is not None
             else len(self._keys))
        return ps[j] - ps[i] if j > i else 0

    def split_key(self, begin: bytes,
                  end: Optional[bytes]) -> Optional[bytes]:
        """First key past half the sampled bytes — the byte-balanced
        split point (ref: splitMetrics). None when the sample is too
        thin to name an interior key. O(log n) over the lazy prefix
        sums instead of the old O(range) accumulation."""
        ps = self._prefix_sums()
        i = bisect_left(self._keys, begin)
        j = (bisect_left(self._keys, end) if end is not None
             else len(self._keys))
        if j - i < 2:
            return None
        total = ps[j] - ps[i]
        # first index m in (i, j) with 2*(ps[m+1]-ps[i]) >= total and
        # _keys[m] > begin — bisect over the monotone prefix, then walk
        # past any boundary-equal keys (at most the begin key itself)
        lo, hi = i, j - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if (ps[mid + 1] - ps[i]) * 2 >= total:
                hi = mid
            else:
                lo = mid + 1
        for m in range(lo, j):
            if self._keys[m] > begin:
                return self._keys[m]
        return None

    def reset_rate(self) -> None:
        """Forget the smoothed rates and the read sample — the meters
        are server-scoped, so after bounds shrink (split/shrink_to) the
        departed range's traffic must not keep counting against this
        shard (reads reset exactly like the write meter)."""
        self._rate = 0.0
        self._rate_t = None
        self._read_rate = 0.0
        self._read_ops = 0.0
        self._read_t = None
        self._read_sample.clear()

    def note_write(self, nbytes: int, now: float) -> None:
        """Leaky-integrator bandwidth: rate decays with time constant
        DD_BANDWIDTH_TAU and each write adds nbytes/tau — steady-state
        equals the true bytes/sec (ref: bytesInput rate smoothing
        feeding SHARD_MAX_BYTES_PER_KSEC splits)."""
        tau = SERVER_KNOBS.dd_bandwidth_tau
        if self._rate_t is not None and tau > 0:
            self._rate *= math.exp(-(now - self._rate_t) / tau)
        self._rate_t = now
        self._rate += nbytes / max(tau, 1e-9)

    def write_bytes_per_sec(self, now: float) -> float:
        tau = SERVER_KNOBS.dd_bandwidth_tau
        if self._rate_t is None or tau <= 0:
            return 0.0
        return self._rate * math.exp(-(now - self._rate_t) / tau)

    # -- read side (ISSUE 13; ref: StorageMetrics bytesReadSample +
    # getReadHotRanges density math) -----------------------------------

    @staticmethod
    def _read_weight(key: bytes, nbytes: int) -> int:
        """Deterministic inclusion, mirroring the write-side estimator
        with its own READ_SAMPLE_FACTOR: every replica samples the same
        reads and sim replays sample identically."""
        factor = SERVER_KNOBS.read_sample_factor
        if nbytes >= factor:
            return nbytes
        if zlib.crc32(key) / 0xFFFFFFFF < nbytes / factor:
            return factor
        return 0

    def note_read(self, key: bytes, nbytes: int, now: float) -> None:
        """Charge one read of `nbytes` at `key`: the shard-wide leaky
        read meters always, the per-key read-bandwidth sample when the
        crc32 draw includes it."""
        tau = max(SERVER_KNOBS.dd_bandwidth_tau, 1e-9)
        if self._read_t is not None:
            decay = math.exp(-(now - self._read_t) / tau)
            self._read_rate *= decay
            self._read_ops *= decay
        self._read_t = now
        self._read_rate += nbytes / tau
        self._read_ops += 1.0 / tau
        w = self._read_weight(key, nbytes)
        if not w:
            return
        ent = self._read_sample.get(key)
        if ent is None:
            self._read_sample[key] = [w / tau, now]
            if len(self._read_sample) > \
                    int(SERVER_KNOBS.read_sample_max_keys):
                coldest = min(
                    self._read_sample,
                    key=lambda k: self._read_sample[k][0]
                    * math.exp(-(now - self._read_sample[k][1]) / tau))
                del self._read_sample[coldest]
        else:
            ent[0] = ent[0] * math.exp(-(now - ent[1]) / tau) + w / tau
            ent[1] = now

    def read_bytes_per_sec(self, now: float) -> float:
        tau = SERVER_KNOBS.dd_bandwidth_tau
        if self._read_t is None or tau <= 0:
            return 0.0
        return self._read_rate * math.exp(-(now - self._read_t) / tau)

    def read_ops_per_sec(self, now: float) -> float:
        tau = SERVER_KNOBS.dd_bandwidth_tau
        if self._read_t is None or tau <= 0:
            return 0.0
        return self._read_ops * math.exp(-(now - self._read_t) / tau)

    def read_hot_ranges(self, begin: bytes, end: bytes,
                        now: float) -> List[Tuple[bytes, bytes, float,
                                                  float]]:
        """Read-hot sub-ranges of [begin, end) (ref: the
        ReadHotSubRangeRequest density scan): split the shard's sampled
        keys into READ_HOT_SUB_RANGE_CHUNKS byte-balanced buckets and
        flag every bucket whose read-bandwidth ÷ sampled-byte density
        exceeds READ_HOT_RANGE_RATIO × the shard's own density. Rows
        are (begin, end, density_ratio, read_bytes_per_sec), hottest
        first. Pull-computed: nothing here ever runs on the read hot
        path."""
        tau = max(SERVER_KNOBS.dd_bandwidth_tau, 1e-9)
        shard_read = self.read_bytes_per_sec(now)
        shard_bytes = self.sampled_bytes(begin, end)
        if shard_read <= 0 or shard_bytes <= 0:
            return []
        ps = self._prefix_sums()
        i = bisect_left(self._keys, begin)
        j = bisect_left(self._keys, end)
        if j - i < 2:
            return []
        chunks = max(1, int(SERVER_KNOBS.read_hot_sub_range_chunks))
        total = ps[j] - ps[i]
        # byte-balanced bucket boundaries: the first key at or past
        # each total*k/chunks prefix crossing
        bounds = [begin]
        for c in range(1, chunks):
            target = ps[i] + total * c // chunks
            lo, hi = i, j
            while lo < hi:
                mid = (lo + hi) // 2
                if ps[mid + 1] > target:
                    hi = mid
                else:
                    lo = mid + 1
            k = self._keys[min(lo, j - 1)]
            if k > bounds[-1]:
                bounds.append(k)
        bounds.append(end)
        n = len(bounds) - 1
        read_bps = [0.0] * n
        for key, (rate, t) in self._read_sample.items():
            if not (begin <= key < end):
                continue
            b = bisect_right(bounds, key) - 1
            read_bps[min(max(b, 0), n - 1)] += \
                rate * math.exp(-(now - t) / tau)
        shard_density = shard_read / shard_bytes
        ratio = SERVER_KNOBS.read_hot_range_ratio
        out = []
        for b in range(n):
            bi = bisect_left(self._keys, bounds[b])
            bj = bisect_left(self._keys, bounds[b + 1])
            bucket_bytes = ps[bj] - ps[bi]
            if bucket_bytes <= 0 or read_bps[b] <= 0:
                continue
            density = (read_bps[b] / bucket_bytes) / shard_density
            if density >= ratio:
                out.append((bounds[b], bounds[b + 1], round(density, 4),
                            round(read_bps[b], 2)))
        out.sort(key=lambda r: (-r[2], r[0]))
        return out


def encode_shard_meta(tag: int, begin: bytes, end: Optional[bytes],
                      floors=()) -> bytes:
    """Shard identity + fetched-range floors: a floor records that
    [b, e) was installed from a snapshot at `floor` — on re-pull after
    a crash, that range's log mutations at or below the floor are
    already folded into the base and must not re-apply (the atomic-op
    double-apply hazard of fetchKeys; ref: persistent shard assignment
    + fetchedVersion bookkeeping in storageserver)."""
    e = end if end is not None else b""
    has_end = 1 if end is not None else 0
    out = [struct.pack("<HBI", tag, has_end, len(begin)), begin,
           struct.pack("<I", len(e)), e, struct.pack("<I", len(floors))]
    for fb, fe, fv in floors:
        out.append(struct.pack("<I", len(fb)))
        out.append(fb)
        out.append(struct.pack("<I", len(fe)))
        out.append(fe)
        out.append(struct.pack("<q", fv))
    return b"".join(out)


def decode_shard_meta(buf: bytes):
    tag, has_end, lb = struct.unpack_from("<HBI", buf, 0)
    off = 7
    begin = buf[off:off + lb]
    off += lb
    (le,) = struct.unpack_from("<I", buf, off)
    end = buf[off + 4:off + 4 + le] if has_end else None
    off += 4 + le
    floors = []
    if off < len(buf):
        (nf,) = struct.unpack_from("<I", buf, off)
        off += 4
        for _ in range(nf):
            (l1,) = struct.unpack_from("<I", buf, off)
            fb = bytes(buf[off + 4:off + 4 + l1])
            off += 4 + l1
            (l2,) = struct.unpack_from("<I", buf, off)
            fe = bytes(buf[off + 4:off + 4 + l2])
            off += 4 + l2
            (fv,) = struct.unpack_from("<q", buf, off)
            off += 8
            floors.append((fb, fe, fv))
    return tag, bytes(begin), (bytes(end) if end is not None else None), \
        floors

def _split_mutation(m: MutationRef, begin: bytes, end: Optional[bytes]):
    """Split a mutation into (inside, outside) parts relative to
    [begin, end): point mutations go whole to one side; clears clip."""
    hi = end  # None = +inf
    if m.type != CLEAR_RANGE:
        k = m.param1
        if begin <= k and (hi is None or k < hi):
            return [m], []
        return [], [m]
    b, e = m.param1, m.param2
    ib, ie = max(b, begin), (e if hi is None else min(e, hi))
    inside = [MutationRef(CLEAR_RANGE, ib, ie)] if ib < ie else []
    outside = []
    if b < min(begin, e):
        outside.append(MutationRef(CLEAR_RANGE, b, min(begin, e)))
    if hi is not None and max(b, hi) < e:
        outside.append(MutationRef(CLEAR_RANGE, max(b, hi), e))
    return inside, outside


_ATOMIC_APPLY = {
    ADD_VALUE: atomic.add,
    AND: atomic.bit_and,
    OR: atomic.bit_or,
    XOR: atomic.bit_xor,
    APPEND_IF_FITS: atomic.append_if_fits,
    MAX: atomic.vmax,
    MIN: atomic.vmin,
    MIN_V2: atomic.vmin,       # MIN already applies V2 semantics
    AND_V2: atomic.bit_and,    # ...as does AND
    BYTE_MIN: atomic.byte_min,
    BYTE_MAX: atomic.byte_max,
    COMPARE_AND_CLEAR: atomic.compare_and_clear,
}


class _ClearIndex:
    """Versioned range-tombstone index: the keyspace is segmented at
    clear boundaries; each segment carries its stamps sorted by
    (version, seq), so a stabbing query is two bisects instead of a
    scan over every clear ever applied (round-2 VERDICT weak #5: the
    linear _clear_version scan was O(clears) per get)."""

    def __init__(self):
        self._bounds: List[bytes] = [b""]   # segment i = [bounds[i], next)
        self._stamps: List[List[Tuple[int, int]]] = [[]]

    def _split(self, key: bytes) -> int:
        """Ensure a segment boundary at `key`; return its index."""
        i = bisect_right(self._bounds, key) - 1
        if self._bounds[i] == key:
            return i
        self._bounds.insert(i + 1, key)
        self._stamps.insert(i + 1, list(self._stamps[i]))
        return i + 1

    def insert(self, version: int, seq: int, begin: bytes,
               end: bytes) -> None:
        i = self._split(begin)
        j = self._split(end)
        for k in range(i, j):
            self._stamps[k].append((version, seq))

    def query(self, key: bytes,
              version: int) -> Optional[Tuple[int, int]]:
        """Latest (version, seq) clear at or below `version` covering
        `key`, or None. Stamps are appended in (version, seq) order —
        the pull loop applies mutations in commit order."""
        i = bisect_right(self._bounds, key) - 1
        st = self._stamps[i]
        j = bisect_right(st, (version, 1 << 62)) - 1
        return st[j] if j >= 0 else None


class VersionedMap:
    """The in-memory window: per-key version chains + version-stamped
    range clears, overlaid on an optional durable base. Chain lookups
    fall through to the base for versions at or below the window floor
    (ref: fdbclient/VersionedMap.h + storageserver read path)."""

    def __init__(self, base: Optional[IKeyValueStore] = None):
        self._keys: List[bytes] = []           # sorted index of window keys
        # key -> [(version, seq, value)]; seq is a map-wide monotonic
        # stamp so mutations within one version keep their apply order
        # (ref: storageserver.actor.cpp:1664 applyMutation applies the
        # batch strictly in order)
        self._chains: Dict[bytes, List[Tuple[int, int, Optional[bytes]]]] = {}
        self._clears: List[Tuple[int, int, bytes, bytes]] = []
        self._clear_index = _ClearIndex()
        self._base = base
        self._seq = 0

    def _base_get(self, key: bytes) -> Optional[bytes]:
        return self._base.get(key) if self._base is not None else None

    def _set(self, version: int, key: bytes, value: Optional[bytes]) -> None:
        self._seq += 1
        chain = self._chains.get(key)
        if chain is None:
            self._chains[key] = [(version, self._seq, value)]
            insort(self._keys, key)
        else:
            chain.append((version, self._seq, value))

    def apply(self, version: int, m: MutationRef) -> None:
        if m.type == SET_VALUE:
            self._set(version, m.param1, m.param2)
        elif m.type == CLEAR_RANGE:
            # clears are kept as stamped ranges; gets consult them, so
            # base keys need no materialized tombstones
            self._seq += 1
            self._clears.append((version, self._seq, m.param1, m.param2))
            self._clear_index.insert(version, self._seq, m.param1, m.param2)
        elif m.type in _ATOMIC_APPLY:
            # read-modify-write at apply time, in version order (ref:
            # storageserver applyMutation -> Atomic.h apply functions)
            existing = self.get(m.param1, version)
            self._set(version, m.param1, _ATOMIC_APPLY[m.type](existing,
                                                               m.param2))
        elif m.type in INERT_OPS:
            # DebugKeyRange/DebugKey/NoOp ride the commit stream but
            # never change data (ref: applyMutation ignoring them)
            pass
        else:
            raise error("client_invalid_operation")

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        return self._get_hinted(key, version, _NO_HINT)

    def _get_hinted(self, key: bytes, version: int, base_hint):
        """`get` that can skip the base lookup when the caller already
        has the base value in hand (scan paths: the candidate iterator
        fetched it from the engine chunk)."""
        cs = self._clear_index.query(key, version)
        chain = self._chains.get(key)
        if chain:
            for v, s, val in reversed(chain):
                if v <= version:
                    return None if cs is not None and cs > (v, s) else val
        if cs is not None:
            return None
        return self._base_get(key) if base_hint is _NO_HINT else base_hint

    def _candidates(self, begin: bytes, end: bytes, reverse: bool = False):
        """Lazily yield candidate keys in [begin, end) in order (or
        reverse): window keys merged with base-engine chunks, dedup'd.
        Scans stop at \\xff\\xff — the engine's own metadata never
        surfaces in reads; stored system rows under \\xff (conf,
        excluded, backup progress) are real data the CLIENT gates
        (ref: FDBTypes.h normalKeys/systemKeys). Laziness is what keeps
        limited scans and selector walks from materializing the whole
        shard (round-2 VERDICT weak #5)."""
        end = min(end, b"\xff\xff")
        if begin >= end:
            return
        win = self._keys[bisect_left(self._keys, begin):
                         bisect_left(self._keys, end)]
        if reverse:
            win = win[::-1]
        wi = 0
        if self._base is None:
            for k in win:
                yield k, _NO_HINT
            return
        CHUNK = int(SERVER_KNOBS.fetch_block_rows)
        pending: List[Tuple[bytes, bytes]] = []
        pi = 0
        done_base = False
        cursor = begin if not reverse else end
        while True:
            if pi >= len(pending) and not done_base:
                if not reverse:
                    pending = self._base.get_range(cursor, end, limit=CHUNK)
                else:
                    pending = self._base.get_range(begin, cursor, limit=CHUNK,
                                                   reverse=True)
                pi = 0
                if len(pending) < CHUNK:
                    done_base = True
                elif not reverse:
                    cursor = pending[-1][0] + b"\x00"
                else:
                    cursor = pending[-1][0]
            have_b = pi < len(pending)
            have_w = wi < len(win)
            if not have_b and not have_w:
                return
            if not have_b:
                k, hint, wi = win[wi], _NO_HINT, wi + 1
            elif not have_w:
                (k, hint), pi = pending[pi], pi + 1
            else:
                b, w = pending[pi][0], win[wi]
                if b == w:
                    (k, hint), pi, wi = pending[pi], pi + 1, wi + 1
                elif (b < w) != reverse:
                    (k, hint), pi = pending[pi], pi + 1
                else:
                    k, hint, wi = w, _NO_HINT, wi + 1
            yield k, hint

    def get_range(self, begin: bytes, end: bytes, version: int,
                  limit: int, reverse: bool = False) -> List[Tuple[bytes, bytes]]:
        out = []
        for k, hint in self._candidates(begin, end, reverse):
            val = self._get_hinted(k, version, hint)
            if val is not None:
                out.append((k, val))
                if len(out) >= limit:
                    break
        return out

    def resolve_selector(self, sel: KeySelector, version: int,
                         begin: bytes = b"",
                         end: Optional[bytes] = None):
        """Resolve a KeySelector against the keys present at `version`
        within [begin, end) by walking outward from the reference key —
        cost is O(offset) present keys, not O(shard) (ref: storageserver
        findKey / KeySelectorRef semantics: the result is the key
        `offset` present keys past the last key < (or <= when or_equal)
        the reference key).

        Returns (key, leftover): leftover 0 means resolved in-shard;
        a negative leftover means the answer is the |leftover|-th
        present key LEFT of `begin` (1-based); a positive leftover means
        the leftover-th present key RIGHT of `end` — the client walks
        the neighboring shard with a boundary-anchored selector (ref:
        NativeAPI getKey readThrough iteration across shards)."""
        hi = min(end if end is not None else b"\xff\xff", b"\xff\xff")
        key = sel.key
        if sel.offset >= 1:
            # the offset-th present key >= key (> key when or_equal)
            needed = sel.offset
            start = max(key + b"\x00" if sel.or_equal else key, begin)
            found = 0
            for k, hint in self._candidates(start, hi):
                if self._get_hinted(k, version, hint) is not None:
                    found += 1
                    if found == needed:
                        return k, 0
            return b"\xff", needed - found
        # the (1 - offset)-th present key < key (<= key when or_equal)
        needed = 1 - sel.offset
        stop = min(key + b"\x00" if sel.or_equal else key, hi)
        found = 0
        for k, hint in self._candidates(begin, stop, reverse=True):
            if self._get_hinted(k, version, hint) is not None:
                found += 1
                if found == needed:
                    return k, 0
        return b"", -(needed - found)

    def forget(self, up_to: int) -> None:
        """Drop window state at or below `up_to` — it lives in the base
        now (ref: VersionedMap::forgetVersionsBefore via updateStorage)."""
        self._clears = [c for c in self._clears if c[0] > up_to]
        self._clear_index = _ClearIndex()
        for v, s, b, e in self._clears:
            self._clear_index.insert(v, s, b, e)
        dead = []
        for k, chain in list(self._chains.items()):
            keep = [e for e in chain if e[0] > up_to]
            if keep:
                self._chains[k] = keep
            else:
                dead.append(k)
        for k in dead:
            del self._chains[k]
            i = bisect_left(self._keys, k)
            if i < len(self._keys) and self._keys[i] == k:
                del self._keys[i]


class StorageServer:
    def __init__(self, process: SimProcess, tlog_peek: NetworkRef = None,
                 kv: Optional[IKeyValueStore] = None,
                 tlog_pop: Optional[NetworkRef] = None,
                 durability_lag_versions: Optional[int] = None,
                 tag: int = 0, dbinfo=None,
                 shard_begin: bytes = b"",
                 shard_end: Optional[bytes] = None, floors=(),
                 name: Optional[str] = None):
        self.process = process
        # direct log wiring (component tests) or dbinfo-driven discovery
        # of the current log generation (clusters with recovery)
        self.tlog_peek = tlog_peek
        self.tlog_pop = tlog_pop
        self.dbinfo = dbinfo            # AsyncVar[ServerDBInfo] or None
        self.kv = kv
        self.tag = tag
        self.name = name or process.name   # store name = replica identity
        self.shard_begin = shard_begin
        self.shard_end = shard_end
        # fetched-range floors (see encode_shard_meta) + the in-flight
        # incoming range, whose mutations buffer until the snapshot
        # lands (ref: AddingShard, storageserver.actor.cpp:149)
        self._floors: List[Tuple[bytes, bytes, int]] = list(floors)
        # reads below an installed snapshot's version would see future
        # data through the unversioned base: floor them out (code
        # review r3 — clients retry with a fresh GRV, which is always
        # at or above any published install version)
        self._read_floor = max((f[2] for f in self._floors), default=0)
        self._adding: Optional[Tuple[bytes, bytes]] = None
        self._adding_buf: List[Tuple[int, MutationRef]] = []
        self.known_committed = 0  # replicated log-set-wide (peek piggyback)
        self._replica_rr = tag    # peek replica rotation, offset by tag
        self._seen_epoch = 0
        self.data = VersionedMap(base=kv)
        self.version = NotifiedVersion(0)
        self.durable_version = NotifiedVersion(0)
        self._lag = (durability_lag_versions if durability_lag_versions
                     is not None else
                     int(SERVER_KNOBS.storage_durability_lag *
                         SERVER_KNOBS.versions_per_second))
        if durability_lag_versions is None and \
                flow.buggify("storage/short_durability_lag"):
            # near-zero MVCC window: every read races the window floor
            self._lag = 1000
        # read-ahead bound (ref: MAX_READ_TRANSACTION_LIFE_VERSIONS;
        # BUGGIFY shrinks it so future_version paths get exercised)
        self._max_read_ahead = SERVER_KNOBS.max_read_transaction_life_versions
        # raw pulled entries not yet durable: [(version, mutations)]
        self._pending: List[Tuple[int, tuple]] = []
        self.gets = RequestStream(process)
        self.ranges = RequestStream(process)
        self.get_keys = RequestStream(process)
        self.watches = RequestStream(process)
        # key -> list of (value_at_registration, reply, deadline)
        self._watch_map: Dict[bytes, list] = {}
        # (ref: StorageServer::counters — query/mutation accounting)
        self.stats = flow.CounterCollection("storage")
        # banded + sampled point-read latency (ref: LatencyBandConfig's
        # read bands in status)
        self.read_bands = flow.RequestLatency("read")
        # QoS saturation signals (ref: StorageQueuingMetrics — the
        # smoothed queue/lag/rate surface the Ratekeeper polls). Pull
        # model: nothing here updates on the hot paths; qos_sample()
        # reads raw state and smooths it at the collection cadence
        self._qos_queue = flow.SmoothedQueue()
        self._qos_lag = flow.SmoothedQueue()
        self._qos_read_rate = flow.SmoothedRate()
        self._qos_mutation_rate = flow.SmoothedRate()
        # byte sample + write bandwidth for DD sizing decisions
        self.metrics = StorageMetrics()
        # per-storage read-cost tag accounting (ref: fdbserver/
        # TransactionTagCounter ON the storage server — the busiest-tag
        # signal the ratekeeper's storage-aware throttling reads; PR 6's
        # proxy-side counter reused, bounded + decaying). Touched only
        # while STORAGE_HEAT_TRACKING is armed.
        from .proxy import TransactionTagCounter
        self.tag_counter = TransactionTagCounter()
        # typed metrics probes (StorageMetricsRequest /
        # ReadHotRangesRequest / SplitMetricsRequest)
        self.metrics_requests = RequestStream(process)
        self._hot_cache = None   # (sim time, rows) read_hot_ranges memo
        self._actors = flow.ActorCollection()
        self.recovered = Future()   # engine recovery complete (fetchKeys
                                    # sources/destinations wait on this)

    def start(self) -> None:
        self._actors.add(flow.spawn(self._run(), TaskPriority.UPDATE_STORAGE,
                                    name=f"{self.process.name}.run"))
        self.process.on_kill(self._actors.cancel_all)

    def retire(self) -> None:
        """End this replica: actors stop and every endpoint breaks with
        broken_promise so stale-map clients refresh their picture
        instead of timing out (ref: storage server removal — endpoint
        death IS the signal the location cache invalidates on)."""
        self._actors.cancel_all()
        # parked watch waiters would otherwise hang forever once the
        # expiry actor dies with the role — fail them like set_bounds does
        # so their clients refresh the location map
        self._fail_watches(lambda k: True)
        for stream in (self.gets, self.ranges, self.get_keys, self.watches,
                       self.metrics_requests):
            stream.close()

    def _fail_watches(self, pred) -> None:
        """Fail every parked watch whose key matches `pred` with
        wrong_shard_server so its client refreshes the location map."""
        for k in [k for k in self._watch_map if pred(k)]:
            for _expected, reply, _deadline in self._watch_map.pop(k):
                reply.send_error(error("wrong_shard_server"))

    async def _run(self) -> None:
        await self._recover()
        if not self.recovered.is_ready:
            self.recovered.send(None)
        for coro, prio, name in (
                (self._pull_loop(), TaskPriority.UPDATE_STORAGE, "pull"),
                (self._durability_loop(), TaskPriority.UPDATE_STORAGE,
                 "updateStorage"),
                (self._get_loop(), TaskPriority.STORAGE, "get"),
                (self._range_loop(), TaskPriority.STORAGE, "getrange"),
                (self._get_key_loop(), TaskPriority.STORAGE, "getkey"),
                (self._metrics_loop(), TaskPriority.LOW_PRIORITY,
                 "storageMetrics"),
                (self._watch_loop(), TaskPriority.STORAGE, "watch"),
                (self._watch_expiry_loop(), TaskPriority.LOW_PRIORITY,
                 "watchExpiry")):
            self._actors.add(flow.spawn(coro, prio,
                                        name=f"{self.process.name}.{name}"))

    async def _recover(self) -> None:
        """Recover the engine; resume pulling after the persisted durable
        version (ref: storageServer recovery from IKeyValueStore +
        byteSample/metadata keys)."""
        if self.kv is None:
            return
        await self.kv.recover()
        raw = self.kv.get(DURABLE_VERSION_KEY)
        if raw is not None:
            (v,) = struct.unpack("<Q", raw)
            self.durable_version.set(v)
            self.version.set(v)
        if self.kv.get(SHARD_META_KEY) is None:
            # first boot of this store: persist the shard identity NOW so
            # a crash before the first durability batch still leaves a
            # self-describing store for the worker's boot scan
            self.kv.set(SHARD_META_KEY,
                        encode_shard_meta(self.tag, self.shard_begin,
                                          self.shard_end))
            await self.kv.commit()
        # re-seed the byte sample from the recovered base (the
        # reference persists its byteSample; a scan-on-boot is the
        # sim-scale equivalent)
        self._rebuild_metrics()

    async def _pull_loop(self):
        """Pull this tag's committed mutations from the log
        (ref: update :2461, peeking the server's own tag). With a
        dbinfo, the source is the generation covering the next needed
        version — old locked generations drain first, then the current
        one; replicas rotate on failure; an epoch change below our
        version triggers a rollback (ref: storageserver rollback +
        peekcursor generation fail-over)."""
        while True:
            if self.dbinfo is None:
                reply = await self.tlog_peek.get_reply(
                    TLogPeekRequest(self.version.get() + 1, self.tag),
                    self.process)
                self._apply_peek(reply, cap=None)
                continue
            self._maybe_rollback()
            needed = self.version.get() + 1
            src = self._pick_source(needed)
            if src is None:
                await flow.first_of(
                    self.dbinfo.on_change(),
                    flow.delay(flow.SERVER_KNOBS.storage_pull_idle_delay,
                               TaskPriority.UPDATE_STORAGE))
                continue
            gen, refs = src
            try:
                reply = await flow.timeout_error(refs.peeks.get_reply(
                    TLogPeekRequest(needed, self.tag), self.process),
                    SERVER_KNOBS.storage_peek_timeout)
            except flow.FdbError:
                self._replica_rr += 1  # rotate to another replica
                await flow.delay(SERVER_KNOBS.storage_rollback_delay,
                                 TaskPriority.UPDATE_STORAGE)
                continue
            cap = gen.end_version if gen.end_version >= 0 else None
            before = self.version.get()
            self._apply_peek(reply, cap)
            # NOTE: pops happen only from the durability loop at the
            # DURABLE version — popping a drained generation at the
            # pulled version would free log data this server still
            # needs if it crashes before persisting (code review r3)
            if cap is not None and self.version.get() == before and \
                    self.version.get() < cap:
                # a locked replica that answered instantly with nothing
                # lacks the generation's tail (it died behind its peers):
                # rotate instead of re-peeking it forever
                self._replica_rr += 1
                await flow.delay(SERVER_KNOBS.storage_rollback_delay,
                                 TaskPriority.UPDATE_STORAGE)

    def _apply_peek(self, reply, cap: Optional[int]) -> None:
        if reply.known_committed > self.known_committed:
            self.known_committed = reply.known_committed
        for version, mutations in reply.entries:
            if version <= self.version.get():
                continue
            if cap is not None and version > cap:
                break  # stale data beyond the generation's locked end
            apply_now = self._partition(version, mutations)
            wbytes = 0
            hi = self.shard_end if self.shard_end is not None else b"\xff"
            for m in apply_now:
                self.data.apply(version, m)
                self.metrics.apply(m)
                # bandwidth counts OWNED-range traffic only: stray
                # parts of shard-spanning mutations must not push this
                # shard over the split ceiling
                if m.type == CLEAR_RANGE:
                    if m.param1 < hi and m.param2 > self.shard_begin:
                        wbytes += len(m.param1) + len(m.param2)
                elif self.shard_begin <= m.param1 < hi:
                    wbytes += len(m.param1) + len(m.param2 or b"")
            if wbytes:
                self.metrics.note_write(wbytes, flow.now())
            self.stats.counter("mutations").add(len(mutations))
            if apply_now:
                self._pending.append((version, apply_now))
            self.version.set(version)
            self._check_watches(version, apply_now)
        adv = reply.committed_version
        if cap is not None:
            adv = min(adv, cap)
        if adv > self.version.get():
            self.version.set(adv)

    def _partition(self, version: int, mutations):
        """Route each mutation part: the in-flight incoming range
        buffers until its snapshot lands; floored ranges drop parts the
        installed snapshot already contains (post-crash replay); the
        rest applies now. Clears are clipped at the range edges.

        Parts outside the owned range apply too — clipping to bounds
        here would be WRONG: a rebooted replica replays history
        against stale persisted bounds (the authoritative clamp
        arrives asynchronously after registration) and would drop
        clears it legitimately owns. Stale out-of-range window state
        left by a shard-spanning mutation is purged when the range is
        (re-)acquired (_purge_window_range at install)."""
        if self._adding is None and not self._floors:
            return tuple(mutations)
        out = []
        for m in mutations:
            if self._adding is not None:
                ab, ae = self._adding
                inside, outside = _split_mutation(m, ab, ae)
                for part in inside:
                    self._adding_buf.append((version, part))
            else:
                outside = [m]
            for part in outside:
                rest = [part]
                for fb, fe, fv in self._floors:
                    if version > fv:
                        continue
                    nxt = []
                    for p in rest:
                        _in, out_parts = _split_mutation(p, fb, fe)
                        nxt.extend(out_parts)   # in-floor parts drop
                    rest = nxt
                out.extend(rest)
        return tuple(out)

    def _pick_source(self, needed: int):
        """The generation that OWNS `needed`, and one of its replicas
        (see dbinfo.pick_log_source for the strict-coverage rule — a
        non-covering generation's durable watermark would silently skip
        records)."""
        from .dbinfo import pick_log_source
        return pick_log_source(self.dbinfo.get(), needed,
                               self._replica_rr)

    def _maybe_rollback(self) -> None:
        """A new epoch whose recovery version is below what we pulled
        means the surplus came from a replica that died un-acked: rebuild
        the window from the durable base plus the surviving prefix
        (ref: storageserver.actor.cpp rollback)."""
        info = self.dbinfo.get()
        if info.epoch == self._seen_epoch:
            return
        self._seen_epoch = info.epoch
        rv = info.recovery_version
        if rv <= 0 or self.version.get() <= rv:
            return
        keep = [(v, ms) for v, ms in self._pending if v <= rv]
        self.data = VersionedMap(base=self.kv)
        self._rebuild_metrics()
        for v, ms in keep:
            for m in ms:
                self.data.apply(v, m)
                self.metrics.apply(m)
        self._pending = keep
        self.version.rollback(rv)
        flow.cover("storage.rollback")
        flow.TraceEvent("StorageRollback", self.process.name).detail(
            To=rv).log()

    async def _durability_loop(self):
        """Apply old window versions to the engine, persist the durable
        version, pop the log, forget the window prefix
        (ref: updateStorage + tLogPop driven by storage durability)."""
        if self.kv is None:
            return
        while True:
            await flow.delay(SERVER_KNOBS.storage_commit_interval,
                             TaskPriority.UPDATE_STORAGE)
            # never make durable a version that could still be rolled
            # back by an epoch recovery: cap at the highest version known
            # replicated across the whole log set (ref: storageserver
            # updateStorage bounded by knownCommittedVersion semantics)
            target = min(self.version.get() - self._lag,
                         max(self.known_committed,
                             self.durable_version.get()))
            if target <= self.durable_version.get():
                continue
            made = self.durable_version.get()
            i = 0
            while i < len(self._pending) and self._pending[i][0] <= target:
                version, mutations = self._pending[i]
                for m in mutations:
                    self._apply_to_kv(m)
                # replayed install entries can sit below the marker:
                # never let it regress
                made = max(made, version)
                i += 1
            del self._pending[:i]
            # nothing may exist below `target` that we haven't applied:
            # advance the marker even with an empty queue so pops keep
            # flowing from idle shards (a stalled marker starved the
            # tag's log records once pops became per-replica)
            made = max(made, target)
            live_floors = [f for f in self._floors if f[2] > made]
            if len(live_floors) != len(self._floors):
                # a floor only filters crash-replay of versions at or
                # below it; once the durable marker passes it, re-pulls
                # start above it and it is dead weight (code review r3)
                self._floors = live_floors
                self._persist_meta()
            self.kv.set(DURABLE_VERSION_KEY, struct.pack("<Q", made))
            await self.kv.commit()
            self.durable_version.set(made)
            self.data.forget(made)
            me = self.name
            if self.tlog_pop is not None:
                self.tlog_pop.send(TLogPopRequest(made, self.tag, me),
                                   self.process)
            elif self.dbinfo is not None:
                info = self.dbinfo.get()
                for lr in info.logs.logs:
                    lr.pops.send(TLogPopRequest(made, self.tag, me),
                                 self.process)
                for gen in info.old_logs:
                    for lr in gen.logs:
                        lr.pops.send(TLogPopRequest(
                            min(made, gen.end_version), self.tag, me),
                            self.process)

    def _apply_to_kv(self, m: MutationRef) -> None:
        if m.type == SET_VALUE:
            self.kv.set(m.param1, m.param2)
        elif m.type == CLEAR_RANGE:
            self.kv.clear_range(m.param1, m.param2)
        elif m.type in _ATOMIC_APPLY:
            self.kv.set(m.param1,
                        _ATOMIC_APPLY[m.type](self.kv.get(m.param1), m.param2)
                        or b"")
        else:
            raise error("client_invalid_operation")

    # -- shard movement (ref: fetchKeys/AddingShard + moveKeys) ---------
    def begin_adding(self, begin: bytes, end: Optional[bytes]) -> None:
        """Start buffering mutations for an incoming range; the dual-tag
        must begin AFTER this so nothing slips through un-buffered."""
        self._adding = (begin, end)
        self._adding_buf = []

    def abort_adding(self) -> None:
        self._adding = None
        self._adding_buf = []

    def snapshot_range(self, begin: bytes, end: Optional[bytes],
                       at_version: int):
        """This shard's view of the range at `at_version` — the
        fetchKeys source side. The caller picks a version at or below
        known_committed so an epoch rollback can never invalidate the
        snapshot after it lands durably on the destination. The bound
        is \\xff\\xff: stored system rows move WITH the shard (engine
        metadata never surfaces through the window's read path)."""
        hi = end if end is not None else b"\xff\xff"
        return self.data.get_range(begin, hi, at_version, 1 << 30)

    async def install_snapshot(self, rows, at_version: int) -> None:
        """Fold the fetched snapshot into the DURABLE base (with its
        floor persisted in the shard meta) before ownership flips, then
        replay buffered mutations above the snapshot version. Making
        the install durable first keeps a crash from resurrecting the
        old ownership after the source has shrunk."""
        begin, end = self._adding
        # purge stale window/pending state for the acquired range at
        # versions <= at_version FIRST: a vacate clear left by an
        # earlier shrink_to would otherwise shadow the installed base
        # rows on reads (its window stamp survives re-acquisition) and
        # clobber them on the durability replay (ref: fetchKeys
        # clearing the fetched range in versioned data before
        # inserting the snapshot, storageserver.actor.cpp fetchKeys)
        self._purge_window_range(begin, end, at_version)
        # the snapshot IS the range's complete state at at_version:
        # wipe the base range first — stale rows from a previous
        # ownership era (whose vacate clear the purge just dropped
        # from the pending queue) must not shine through under the
        # installed data (ref: fetchKeys clear-then-insert)
        hi = end if end is not None else b"\xff\xff"
        self.kv.clear_range(begin, hi)
        self.metrics.note_clear(begin, hi)
        for k, v in rows:
            self.kv.set(k, v)
            self.metrics.note_set(k, len(k) + len(v))
        self._floors.append((begin,
                             end if end is not None else b"\xff\xff",
                             at_version))
        self._read_floor = max(self._read_floor, at_version)
        new_begin = min(self.shard_begin, begin)
        new_end = self.shard_end
        if end is None or (self.shard_end is not None
                           and end > self.shard_end):
            new_end = end
        self.shard_begin, self.shard_end = new_begin, new_end
        self._persist_meta()
        # a WHOLE-shard install (vacate/split newcomer) makes at_version
        # a durable version outright: everything below it is in the
        # snapshot. Without this, a crash before the first durability
        # cycle recovers at version 0 and wedges pulling generations
        # that no longer exist. (Partial installs — boundary moves —
        # must NOT claim it: the old range still needs its own replay.)
        if begin <= new_begin and (
                end is None or (new_end is not None and end >= new_end)):
            if at_version > self.durable_version.get():
                self.kv.set(DURABLE_VERSION_KEY,
                            struct.pack("<Q", at_version))
                self.durable_version.set(at_version)
                if self.version.get() < at_version:
                    self.version.set(at_version)
        await self.kv.commit()
        buf, self._adding_buf = self._adding_buf, []
        self._adding = None
        replay = [(v, m) for v, m in buf if v > at_version]
        for v, m in replay:
            self.data.apply(v, m)
            self.metrics.apply(m)
        if replay:
            self._merge_pending(replay)

    def _purge_window_range(self, begin: bytes, end: Optional[bytes],
                            up_to: int) -> None:
        """Drop window chains, clears, and pending replay covering
        [begin, end) at versions <= up_to — the installed snapshot IS
        that range's state at up_to. Parts outside the range (a clear
        spanning the boundary) are kept. Reads below up_to are already
        rejected by the install's read floor, so no reader can miss
        the removed history."""
        hi = end if end is not None else b"\xff\xff"
        d = self.data
        i = bisect_left(d._keys, begin)
        j = bisect_left(d._keys, hi)
        survivors = []
        for k in d._keys[i:j]:
            chain = [e for e in d._chains[k] if e[0] > up_to]
            if chain:
                d._chains[k] = chain
                survivors.append(k)
            else:
                del d._chains[k]
        d._keys[i:j] = survivors
        kept = []
        for v, s, cb, ce in d._clears:
            if v > up_to or ce <= begin or cb >= hi:
                kept.append((v, s, cb, ce))
                continue
            if cb < begin:
                kept.append((v, s, cb, begin))
            if ce > hi:
                kept.append((v, s, hi, ce))
        d._clears = kept
        d._clear_index = _ClearIndex()
        for v, s, cb, ce in kept:
            d._clear_index.insert(v, s, cb, ce)
        pending = []
        for v, ms in self._pending:
            if v > up_to:
                pending.append((v, ms))
                continue
            keep_ms = []
            for m in ms:
                _inside, outside = _split_mutation(m, begin, end)
                keep_ms.extend(outside)
            if keep_ms:
                pending.append((v, tuple(keep_ms)))
        self._pending = pending

    async def set_bounds(self, begin: bytes, end: Optional[bytes]) -> None:
        """Adopt authoritative bounds (the CC's shard map is ground
        truth; a rebooted server whose persisted meta disagrees — e.g.
        it crashed mid-move — is clamped back on registration). Shrinks
        clear the vacated range versioned and fail its watches so
        stale-map clients refresh."""
        if begin > self.shard_begin or (
                self.shard_end is None and end is not None) or (
                end is not None and self.shard_end is not None
                and end < self.shard_end):
            await self.shrink_to(max(begin, self.shard_begin),
                                 end if end is not None else self.shard_end)
        self.shard_begin, self.shard_end = begin, end
        self._persist_meta()
        if self.kv is not None:
            await self.kv.commit()

    async def shrink_to(self, begin: bytes, end: Optional[bytes]) -> None:
        """Give up ownership outside [begin, end): the vacated range is
        cleared VERSIONED at the current version so stale-map readers at
        older versions still see consistent data (ref: the old team
        keeping data through the move grace)."""
        v = self.version.get()
        clears = []
        if begin > self.shard_begin:
            clears.append(MutationRef(CLEAR_RANGE, self.shard_begin, begin))
        if end is not None and (self.shard_end is None
                                or end < (self.shard_end or b"\xff\xff")):
            clears.append(MutationRef(
                CLEAR_RANGE, end,
                self.shard_end if self.shard_end is not None
                else b"\xff\xff"))
        for m in clears:
            self.data.apply(v, m)
            self.metrics.apply(m)
        if clears:
            self._merge_pending([(v, m) for m in clears])
        # watches on vacated keys will never fire here again: fail them
        # so their clients refresh the location map (code review r3)
        self._fail_watches(
            lambda k: k < begin or (end is not None and k >= end))
        self.shard_begin, self.shard_end = begin, end
        # the departed range's write traffic must not keep this shard
        # over the bandwidth-split ceiling (the meter is server-scoped)
        self.metrics.reset_rate()
        self._persist_meta()
        if self.kv is not None:
            await self.kv.commit()

    def _persist_meta(self) -> None:
        if self.kv is not None:
            self.kv.set(SHARD_META_KEY,
                        encode_shard_meta(self.tag, self.shard_begin,
                                          self.shard_end, self._floors))

    def _merge_pending(self, entries) -> None:
        """Insert (version, mutation) singletons into the durability
        queue, keeping it version-sorted (installs replay versions that
        may be older than the queue tail)."""
        for v, m in entries:
            i = bisect_right([p[0] for p in self._pending], v)
            self._pending.insert(i, (v, (m,)))

    def approx_rows(self) -> int:
        """Row-count estimate (status/observability; DD sizing runs on
        sampled BYTES — see sampled_bytes): the base engine's O(1)
        count plus the window's key-index size."""
        base = self.kv.row_count() if self.kv is not None else 0
        win = len(self.data._keys)
        return base + win

    def _rebuild_metrics(self) -> None:
        """Re-seed the byte sample from the durable base's owned range
        (rollback discarded window state; recovery starts fresh)."""
        if self.kv is None:
            self.metrics.rebuild(())
            return
        hi = self.shard_end if self.shard_end is not None else b"\xff"
        self.metrics.rebuild(self.kv.get_range(self.shard_begin, hi))

    def sampled_bytes(self) -> int:
        """Estimated logical bytes in this shard (ref:
        storageserver.actor.cpp:310 byteSample → getStorageMetrics).
        Capped at \\xff: system-space rows (backup progress, \\xff/conf)
        must not count toward user-shard sizing or split points."""
        return self.metrics.sampled_bytes(
            self.shard_begin,
            self.shard_end if self.shard_end is not None else b"\xff")

    def write_bandwidth(self) -> float:
        """Smoothed write bytes/sec into this shard (ref: bytesInput
        rate driving SHARD_MAX_BYTES_PER_KSEC splits)."""
        return self.metrics.write_bytes_per_sec(flow.now())

    # -- storage heat plane (ISSUE 13) ----------------------------------
    def _note_read(self, key: bytes, nbytes: int, tags) -> None:
        """Charge one admitted point read: the read sample + leaky
        meters, and read cost against the request's transaction tags.
        Called only behind the STORAGE_HEAT_TRACKING guard — the off
        posture pays exactly one knob read per request."""
        now = flow.now()
        self.metrics.note_read(key, nbytes, now)
        for tag in tags:
            self.tag_counter.record(tag, "started", now,
                                    weight=float(nbytes))

    def _note_range_read(self, rows, tags) -> None:
        """Charge an admitted range read row by row (each returned key
        enters the read sample — a hot scan range heats every key it
        covers, matching the reference's per-key bytesReadSample)."""
        if not rows:
            return
        now = flow.now()
        m = self.metrics
        cost = 0
        for k, v in rows:
            nb = len(k) + len(v)
            cost += nb
            m.note_read(k, nb, now)
        for tag in tags:
            self.tag_counter.record(tag, "started", now,
                                    weight=float(cost))

    def read_bandwidth(self) -> float:
        """Smoothed read bytes/sec out of this shard (ref: the
        bytesReadSample-backed read bandwidth in StorageMetrics)."""
        return self.metrics.read_bytes_per_sec(flow.now())

    def read_ops_rate(self) -> float:
        """Smoothed key reads/sec (point reads + range rows)."""
        return self.metrics.read_ops_per_sec(flow.now())

    def read_hot_ranges(self) -> list:
        """Read-hot sub-ranges of the OWNED range, hottest first:
        (begin, end, density_ratio, read_bytes_per_sec). Capped at
        \\xff like the sizing queries — system-space reads must not
        name user-shard split candidates. Memoized per sim instant:
        the QoS sample and the CC heat rollup both pull within one
        sampler tick, and the bucket scan is pure in (state, now) —
        one scan serves every same-tick consumer."""
        now = flow.now()
        cached = self._hot_cache
        if cached is not None and cached[0] == now:
            return cached[1]
        hi = self.shard_end if self.shard_end is not None else b"\xff"
        rows = self.metrics.read_hot_ranges(self.shard_begin, hi, now)
        self._hot_cache = (now, rows)
        return rows

    def busiest_read_tag(self) -> tuple:
        """(tag bytes | None, decayed read-cost busyness) — the
        per-storage busiest-tag signal the ratekeeper's storage-aware
        throttling reads (ref: TransactionTagCounter::getBusiestTag)."""
        rows = self.tag_counter.top(1)
        if not rows or rows[0]["busyness"] <= 0:
            return None, 0.0
        return bytes.fromhex(rows[0]["tag"]), rows[0]["busyness"]

    async def _metrics_loop(self):
        """Serve the typed metrics probes (ref: the waitMetrics /
        ReadHotSubRangeRequest / SplitMetricsRequest endpoints on
        StorageServerInterface). Pull-computed from the samples — a
        probe never touches the read/write hot paths."""
        from .types import (ReadHotRangesReply, ReadHotRangesRequest,
                            SplitMetricsReply, SplitMetricsRequest,
                            StorageMetricsReply, StorageMetricsRequest)
        while True:
            req, reply = await self.metrics_requests.pop()
            try:
                now = flow.now()
                if isinstance(req, StorageMetricsRequest):
                    tag, busy = self.busiest_read_tag()
                    reply.send(StorageMetricsReply(
                        self.sampled_bytes(),
                        round(self.metrics.write_bytes_per_sec(now), 2),
                        round(self.metrics.read_bytes_per_sec(now), 2),
                        round(self.metrics.read_ops_per_sec(now), 2),
                        tag, round(busy, 4)))
                elif isinstance(req, ReadHotRangesRequest):
                    reply.send(ReadHotRangesReply(
                        tuple(self.read_hot_ranges())))
                elif isinstance(req, SplitMetricsRequest):
                    reply.send(SplitMetricsReply(self.split_key_estimate()))
                else:
                    reply.send_error(error("client_invalid_operation"))
            except flow.FdbError as e:
                reply.send_error(e)

    def qos_sample(self, now: float) -> "QosSample":
        """Saturation-signal snapshot (ref: StorageQueuingMetricsReply
        — the per-storage surface the Ratekeeper's updateRate polls):
        smoothed MVCC-window queue bytes (pulled but not yet durable),
        durable-version lag, and read/mutation rates. Computed on
        demand at the collection cadence — the read/write hot paths
        never touch any of this."""
        from .types import QosSample, mutation_bytes as _mb
        qbytes = sum(_mb(m) for _v, ms in self._pending for m in ms)
        lag = max(0, self.version.get() - self.durable_version.get())
        snap = self.stats.snapshot()
        signals = {
            "queue_bytes": round(self._qos_queue.sample(qbytes, now), 1),
            "durability_lag_versions": round(
                self._qos_lag.sample(lag, now), 1),
            "read_rate": round(self._qos_read_rate.sample_total(
                snap.get("get_queries", 0)
                + snap.get("range_queries", 0), now), 2),
            "mutation_rate": round(self._qos_mutation_rate.sample_total(
                snap.get("mutations", 0), now), 2),
            # folded in from the DD meter so every storage signal flows
            # through the one QosSample path (ISSUE 13 satellite: the
            # CC used to read write_bandwidth out-of-band)
            "write_bandwidth": round(
                self.metrics.write_bytes_per_sec(now), 1),
        }
        if SERVER_KNOBS.storage_heat_tracking:
            # the read-side heat signals, armed-only so the pinned
            # default schema (and the off posture) stay untouched
            _tag, busy = self.busiest_read_tag()
            signals.update(
                read_bytes_per_sec=round(
                    self.metrics.read_bytes_per_sec(now), 1),
                read_ops_per_sec=round(
                    self.metrics.read_ops_per_sec(now), 1),
                read_hot_ranges=len(self.read_hot_ranges()),
                busiest_read_tag_busyness=round(busy, 2))
        return QosSample("storage", self.name, now, signals)

    def split_key_estimate(self) -> Optional[bytes]:
        """A byte-balanced interior key from the sample (ref:
        StorageMetrics.actor.h:302 splitMetrics); the window's row
        median is the fallback while the sample is too thin."""
        hi = self.shard_end if self.shard_end is not None else b"\xff"
        k = self.metrics.split_key(self.shard_begin, hi)
        if k is not None:
            return k
        rows = self.data.get_range(self.shard_begin, hi,
                                   self.version.get(), 5000)
        if len(rows) < 2:
            return None
        return rows[len(rows) // 2][0]

    # -- watches --------------------------------------------------------
    def _check_watches(self, version: int, mutations) -> None:
        """Fire watches whose key's value changed (ref: storageserver
        watch triggering on mutation apply)."""
        if not self._watch_map:
            return
        touched = set()
        for m in mutations:
            if m.type == CLEAR_RANGE:
                touched.update(k for k in self._watch_map
                               if m.param1 <= k < m.param2)
            else:
                if m.param1 in self._watch_map:
                    touched.add(m.param1)
        for k in touched:
            waiters = self._watch_map.get(k, [])
            still = []
            now_val = self.data.get(k, version)
            for expected, reply, deadline in waiters:
                if now_val != expected:
                    reply.send(version)
                else:
                    still.append((expected, reply, deadline))
            if still:
                self._watch_map[k] = still
            else:
                self._watch_map.pop(k, None)

    async def _wait_version(self, version: int):
        """(ref: waitForVersion — future_version when too far ahead,
        transaction_too_old below the window floor)"""
        if version > self.version.get() + self._max_read_ahead:
            raise error("future_version")
        if version < max(self.durable_version.get(), self._read_floor):
            raise error("transaction_too_old")
        await self.version.when_at_least(version)

    async def _get_loop(self):
        while True:
            req, reply = await self.gets.pop()
            flow.spawn(self._serve_get(req, reply), TaskPriority.STORAGE)

    def _check_owned(self, begin: bytes, end: Optional[bytes]) -> None:
        """Reject requests outside the owned range so stale-map clients
        refresh their location picture instead of silently reading a
        vacated range (ref: storageserver wrong_shard_server on
        shard-miss, the location-cache invalidation signal)."""
        if begin < self.shard_begin:
            raise error("wrong_shard_server")
        if self.shard_end is not None:
            probe = end if end is not None else begin + b"\x00"
            if probe > self.shard_end:
                raise error("wrong_shard_server")

    async def _serve_get(self, req: StorageGetRequest, reply):
        t0 = flow.now()
        dbg = getattr(req, "debug_id", None)
        admitted = False
        try:
            self.stats.counter("get_queries").add(1)
            self._check_owned(req.key, None)
            await self._wait_version(req.version)
            if dbg is not None:
                # the storage leg of a sampled read (ref: the
                # GetValueDebug stations in storageserver.actor.cpp
                # getValueQ). Emitted only once the read is actually
                # admitted — a wrong-shard/too-old rejection must not
                # file an unpaired DoRead into the stitching
                flow.g_trace_batch.add_event(
                    "GetValueDebug", dbg,
                    "StorageServer.getValue.DoRead")
                admitted = True
            value = self.data.get(req.key, req.version)
            if SERVER_KNOBS.storage_heat_tracking:
                # armed-only read accounting; off, the whole heat plane
                # costs this one knob read (PERF.md posture table)
                self._note_read(req.key,
                                len(req.key) + len(value or b""),
                                req.tags)
            self.read_bands.record(flow.now() - t0)
            if dbg is not None:
                flow.g_trace_batch.add_event(
                    "GetValueDebug", dbg,
                    "StorageServer.getValue.AfterRead")
            reply.send(value)
        except flow.FdbError as e:
            if admitted:
                # pair-closing error station — only when a DoRead
                # opened the pair (ref: getValueQ's error path tracing)
                flow.g_trace_batch.add_event(
                    "GetValueDebug", dbg, "StorageServer.getValue.Error")
            reply.send_error(e)

    async def _range_loop(self):
        while True:
            req, reply = await self.ranges.pop()
            flow.spawn(self._serve_range(req, reply), TaskPriority.STORAGE)

    async def _serve_range(self, req: StorageGetRangeRequest, reply):
        try:
            self.stats.counter("range_queries").add(1)
            self._check_owned(req.begin, req.end)
            await self._wait_version(req.version)
            rows = self.data.get_range(req.begin, req.end, req.version,
                                       req.limit, req.reverse)
            if SERVER_KNOBS.storage_heat_tracking:
                self._note_range_read(rows, req.tags)
            reply.send(rows)
        except flow.FdbError as e:
            reply.send_error(e)

    async def _get_key_loop(self):
        while True:
            req, reply = await self.get_keys.pop()
            flow.spawn(self._serve_get_key(req, reply), TaskPriority.STORAGE)

    async def _serve_get_key(self, req: StorageGetKeyRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.resolve_selector(
                req.selector, req.version, self.shard_begin, self.shard_end))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _watch_loop(self):
        while True:
            req, reply = await self.watches.pop()
            flow.spawn(self._serve_watch(req, reply), TaskPriority.STORAGE)

    async def _serve_watch(self, req: StorageWatchRequest, reply):
        try:
            self._check_owned(req.key, None)
            await self._wait_version(req.version)
            expected = self.data.get(req.key, req.version)
            current = self.data.get(req.key, self.version.get())
            if current != expected:
                reply.send(self.version.get())
                return
            deadline = flow.now() + SERVER_KNOBS.watch_timeout
            self._watch_map.setdefault(req.key, []).append(
                (expected, reply, deadline))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _watch_expiry_loop(self):
        """Abandoned registrations (a client that timed out and went
        away) must not pin _watch_map forever (ref: the database's
        WATCH timeout, DEFAULT_MAX_WATCHES/timeout handling) — expired
        waiters get timed_out; a live client just re-arms."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.watch_expiry_sweep_interval,
                             TaskPriority.LOW_PRIORITY)
            now = flow.now()
            for k in list(self._watch_map):
                keep = []
                for expected, reply, deadline in self._watch_map.get(k, ()):
                    if deadline <= now:
                        reply.send_error(error("timed_out"))
                    else:
                        keep.append((expected, reply, deadline))
                if keep:
                    self._watch_map[k] = keep
                else:
                    self._watch_map.pop(k, None)
