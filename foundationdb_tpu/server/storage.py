"""Versioned in-memory storage server role.

Reference: fdbserver/storageserver.actor.cpp — a 5-second MVCC window in
a versioned map (:265-306) updated by pulling the log (`update` :2461,
applyMutation :1664), serving `getValueQ` (:763) and `getKeyValues`
(:1274) at a requested version, waiting for the version to arrive and
throwing future_version if it is too far ahead. The versioned map here
is per-key version chains + a range-clear list over a bisect-sorted key
index (the PTree of fdbclient/VersionedMap.h:43 re-expressed for host
Python; the TPU-resident sorted-array engine reuses ops/keys.py).
"""

from __future__ import annotations

from bisect import bisect_left, insort
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import NotifiedVersion, TaskPriority, error
from ..rpc import NetworkRef, RequestStream, SimProcess
from .types import (CLEAR_RANGE, SET_VALUE, MutationRef, StorageGetRangeRequest,
                    StorageGetRequest, TLogPeekRequest)

MAX_READ_AHEAD_VERSIONS = 5_000_000  # ref: MAX_READ_TRANSACTION_LIFE_VERSIONS


class VersionedMap:
    """Per-key version chains + version-stamped range clears."""

    def __init__(self):
        self._keys: List[bytes] = []           # sorted index
        self._chains: Dict[bytes, List[Tuple[int, Optional[bytes]]]] = {}
        self._clears: List[Tuple[int, bytes, bytes]] = []

    def apply(self, version: int, m: MutationRef) -> None:
        if m.type == SET_VALUE:
            chain = self._chains.get(m.param1)
            if chain is None:
                self._chains[m.param1] = [(version, m.param2)]
                insort(self._keys, m.param1)
            else:
                chain.append((version, m.param2))
        elif m.type == CLEAR_RANGE:
            self._clears.append((version, m.param1, m.param2))
            i = bisect_left(self._keys, m.param1)
            while i < len(self._keys) and self._keys[i] < m.param2:
                self._chains[self._keys[i]].append((version, None))
                i += 1
        else:
            raise error("client_invalid_operation")

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        chain = self._chains.get(key)
        if not chain:
            return None
        for v, val in reversed(chain):
            if v <= version:
                return val
        return None

    def get_range(self, begin: bytes, end: bytes, version: int,
                  limit: int) -> List[Tuple[bytes, bytes]]:
        out = []
        i = bisect_left(self._keys, begin)
        while i < len(self._keys) and self._keys[i] < end:
            k = self._keys[i]
            val = self.get(k, version)
            if val is not None:
                out.append((k, val))
                if len(out) >= limit:
                    break
            i += 1
        return out


class StorageServer:
    def __init__(self, process: SimProcess, tlog_peek: NetworkRef):
        self.process = process
        self.tlog_peek = tlog_peek
        self.data = VersionedMap()
        self.version = NotifiedVersion(0)
        self.gets = RequestStream(process)
        self.ranges = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        for coro, prio, name in (
                (self._pull_loop(), TaskPriority.UPDATE_STORAGE, "pull"),
                (self._get_loop(), TaskPriority.STORAGE, "get"),
                (self._range_loop(), TaskPriority.STORAGE, "getrange")):
            self._actors.add(flow.spawn(coro, prio,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    async def _pull_loop(self):
        """Pull committed mutations from the log (ref: update :2461)."""
        while True:
            reply = await self.tlog_peek.get_reply(
                TLogPeekRequest(self.version.get() + 1), self.process)
            for version, mutations in reply.entries:
                if version <= self.version.get():
                    continue
                for m in mutations:
                    self.data.apply(version, m)
                self.version.set(version)
            if reply.committed_version > self.version.get():
                self.version.set(reply.committed_version)

    async def _wait_version(self, version: int):
        """(ref: waitForVersion — future_version when too far ahead)"""
        if version > self.version.get() + MAX_READ_AHEAD_VERSIONS:
            raise error("future_version")
        await self.version.when_at_least(version)

    async def _get_loop(self):
        while True:
            req, reply = await self.gets.pop()
            flow.spawn(self._serve_get(req, reply), TaskPriority.STORAGE)

    async def _serve_get(self, req: StorageGetRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.get(req.key, req.version))
        except flow.FdbError as e:
            reply.send_error(e)

    async def _range_loop(self):
        while True:
            req, reply = await self.ranges.pop()
            flow.spawn(self._serve_range(req, reply), TaskPriority.STORAGE)

    async def _serve_range(self, req: StorageGetRangeRequest, reply):
        try:
            await self._wait_version(req.version)
            reply.send(self.data.get_range(req.begin, req.end, req.version,
                                           req.limit))
        except flow.FdbError as e:
            reply.send_error(e)
