"""Atomic mutation semantics.

Reference: fdbclient/Atomic.h — apply functions for the read-modify-write
mutation types carried in MutationRef (fdbclient/CommitTransaction.h:49-109).
Semantics re-implemented from the reference behavior, V2 variants (the
API-520 fixes) for And/Min: an absent existing value behaves as the
operand itself rather than as empty.

Little-endian arithmetic: operands are unsigned little-endian integers;
the result is truncated/zero-padded to the operand's length (the operand
defines the width, ref doLittleEndianAdd).
"""

from __future__ import annotations

from typing import Optional

VALUE_SIZE_LIMIT = 100_000  # ref: CLIENT_KNOBS->VALUE_SIZE_LIMIT


def _le_int(b: bytes) -> int:
    return int.from_bytes(b, "little")


def _le_bytes(v: int, length: int) -> bytes:
    return (v & ((1 << (8 * length)) - 1)).to_bytes(length, "little") \
        if length else b""


def add(existing: Optional[bytes], param: bytes) -> bytes:
    if not param:
        return b""
    if not existing:
        return param
    return _le_bytes(_le_int(existing) + _le_int(param), len(param))


def bit_and(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param  # V2 semantics (ref: AndV2)
    ex = existing.ljust(len(param), b"\x00")
    return bytes(a & b for a, b in zip(ex, param))


def bit_or(existing: Optional[bytes], param: bytes) -> bytes:
    ex = (existing or b"").ljust(len(param), b"\x00")
    return bytes(a | b for a, b in zip(ex, param))


def bit_xor(existing: Optional[bytes], param: bytes) -> bytes:
    ex = (existing or b"").ljust(len(param), b"\x00")
    return bytes(a ^ b for a, b in zip(ex, param))


def vmax(existing: Optional[bytes], param: bytes) -> bytes:
    if not existing or not param:
        return param
    return _le_bytes(max(_le_int(existing), _le_int(param)), len(param))


def vmin(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param  # V2 semantics (ref: MinV2)
    if not param:
        return param
    width = len(param)
    return _le_bytes(min(_le_int(existing), _le_int(param)), width)


def byte_min(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    return min(existing, param)


def byte_max(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    return max(existing, param)


def append_if_fits(existing: Optional[bytes], param: bytes) -> bytes:
    ex = existing or b""
    return ex + param if len(ex) + len(param) <= VALUE_SIZE_LIMIT else ex


def compare_and_clear(existing: Optional[bytes],
                      param: bytes) -> Optional[bytes]:
    """Returns None (clear) when equal, else the existing value."""
    return None if existing == param else existing
