"""Enforced GRV admission control: the ratekeeper's budget made real.

Reference: fdbserver/GrvProxyServer.actor.cpp — transactionStarter
releases queued GetReadVersion requests no faster than this proxy's
SHARE of the ratekeeper's rate (GrvTransactionRateInfo: a token budget
refilled per batch window with a bounded burst allowance), with strict
priority classes (SystemImmediate bypasses the gate entirely, Default
pays the normal budget, Batch pays the separate — lower — batch budget
so background work throttles first) and queue-memory bounds that
REJECT overflow with a retryable error instead of letting the queue
grow without bound — and GrvProxyTransactionTagThrottler, which parks
tagged requests in per-tag queues in FRONT of the class gate and
releases them at the rate the \\xff\\x02/throttledTags/ rows command.

Pieces:

- `TokenBucket`: lazy-refill budget bucket with a bounded burst
  allowance and an explicit debt mode (an oversized head request is
  admitted into debt rather than starving forever — the same rule the
  pre-admission batcher applied).
- `TagThrottleTable`: the proxy-side view of the throttledTags rows
  (installed by the poll loop in server/proxy.py). Each live row gets
  a pacing bucket and a bounded FIFO of parked requests; expiry
  releases the parked queue back into the class queues.
- `GrvAdmissionQueues`: per-priority FIFO queues with STRICT class
  ordering — immediate drains first and pays no tokens, batch drains
  last and pays both buckets — plus the depth/wait bounds. One
  `tick()` per GRV_BATCH_INTERVAL window admits a batch that the proxy
  serves with a single causal-confirmation round trip (the GRV
  batching coalesce: N admitted transactions per confirmation ask).

Everything is knob-gated OFF by default (GRV_ADMISSION_CONTROL /
TAG_THROTTLING): with both 0 the proxy never routes a request through
this module and the GRV path is byte-identical to the pre-subsystem
one. BUGGIFY arms the knobs randomly so sim storms run throttled.

Counters live in the owning proxy's CounterCollection (`admission_*`,
`throttle_*`), so the metric sampler, status and exporter pick them up
like every other proxy counter.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..flow import SERVER_KNOBS, error
from .types import (PRIORITY_BATCH, PRIORITY_DEFAULT, PRIORITY_IMMEDIATE)

#: a queued GRV admission entry, the shape Proxy._serve_grv_batch
#: consumes: (reply, transaction_count, priority, enqueued_at, tags)
Entry = Tuple[object, int, int, float, Tuple[bytes, ...]]

PRIORITY_NAMES = {PRIORITY_BATCH: "batch", PRIORITY_DEFAULT: "default",
                  PRIORITY_IMMEDIATE: "immediate"}


class TokenBucket:
    """Budget-rate token bucket with lazy refill, a bounded burst
    allowance, and debt (ref: GrvTransactionRateInfo — `budget` may go
    negative when an oversized request is force-admitted, and the
    refill pays the debt off before new admissions)."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float = 0.0, burst: float = 1.0,
                 now: float = 0.0):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = 0.0
        self._last = float(now)

    def set_rate(self, rate: float, burst: float, now: float) -> None:
        """Adopt a new budget; accrued tokens are refilled at the OLD
        rate first, so a rate change never retroactively rewrites the
        past window."""
        self._refill(now)
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))

    def _refill(self, now: float) -> None:
        dt = now - self._last
        if dt > 0:
            if self.rate <= 0:
                # a ZERO rate is a full stop (emergency throttle), not
                # a trickle — accrued tokens are confiscated too
                self.tokens = 0.0
            else:
                self.tokens = min(self.tokens + self.rate * dt, self.burst)
        self._last = now

    def available(self, now: float) -> float:
        self._refill(now)
        return self.tokens

    def try_take(self, n: float, now: float) -> bool:
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def force_take(self, n: float, now: float) -> None:
        """Admit into debt (tokens go negative; refill repays)."""
        self._refill(now)
        self.tokens -= n


class TagThrottleRow:
    """One live throttledTags row as the proxy enforces it."""

    __slots__ = ("tag", "tps", "expiry", "priority", "auto", "bucket",
                 "queue")

    def __init__(self, tag: bytes, tps: float, expiry: float,
                 priority: int, auto: bool, now: float):
        self.tag = tag
        self.tps = float(tps)
        self.expiry = float(expiry)
        self.priority = int(priority)
        self.auto = bool(auto)
        # pacing bucket: one admission immediately, then strictly at
        # the commanded rate (burst 1 — a throttled tag has no burst
        # allowance by design)
        self.bucket = TokenBucket(self.tps, 1.0, now)
        self.bucket.tokens = 1.0
        self.queue: deque = deque()   # parked Entry FIFOs

    def doc(self) -> dict:
        return {"tag": self.tag.hex(), "tps": round(self.tps, 3),
                "expiry": round(self.expiry, 3),
                "priority": PRIORITY_NAMES.get(self.priority, "default"),
                "auto": int(self.auto), "queued": len(self.queue)}


class TagThrottleTable:
    """The proxy's enforcement view of \\xff\\x02/throttledTags/.
    `install` adopts a freshly-polled row set wholesale (pacing buckets
    survive for unchanged tags so a poll never resets accrued debt);
    expiry and rate changes are honored at the next interaction — the
    knobs and rows are read live, never frozen at construction."""

    def __init__(self):
        self.rows: Dict[bytes, TagThrottleRow] = {}

    def install(self, rows, now: float) -> List[Entry]:
        """rows: (tag, tps, expiry, priority, auto). Returns parked
        entries released by rows that vanished (manual `throttle off`)
        — the caller feeds them back into the class queues."""
        released: List[Entry] = []
        fresh: Dict[bytes, TagThrottleRow] = {}
        for tag, tps, expiry, priority, auto in rows:
            if expiry <= now:
                continue
            old = self.rows.get(tag)
            if old is not None:
                old.tps = float(tps)
                old.expiry = float(expiry)
                old.priority = int(priority)
                old.auto = bool(auto)
                old.bucket.set_rate(float(tps), 1.0, now)
                fresh[tag] = old
            else:
                fresh[tag] = TagThrottleRow(tag, tps, expiry, priority,
                                            auto, now)
        for tag, row in self.rows.items():
            if tag not in fresh and row.queue:
                released.extend(row.queue)
                row.queue.clear()
        self.rows = fresh
        return released

    def expire(self, now: float) -> List[Entry]:
        """Drop expired rows; their parked requests are released."""
        released: List[Entry] = []
        for tag in [t for t, r in self.rows.items() if r.expiry <= now]:
            row = self.rows.pop(tag)
            released.extend(row.queue)
            row.queue.clear()
        return released

    def applying(self, tags, priority: int,
                 now: float) -> Optional[TagThrottleRow]:
        """The most restrictive live row throttling this request: a row
        applies to priorities AT OR BELOW its own class (a `default`
        row throttles default and batch; immediate is never
        tag-throttled)."""
        if priority >= PRIORITY_IMMEDIATE or not self.rows:
            return None
        best = None
        for tag in tags:
            row = self.rows.get(tag)
            if row is None or row.expiry <= now:
                continue
            if priority > row.priority:
                continue
            if best is None or row.tps < best.tps:
                best = row
        return best

    def reply_rows(self, tags, now: float) -> Tuple:
        """The (tag, tps, expiry) triples riding the GRV reply so the
        client honors the throttle locally before its next request."""
        out = []
        for tag in tags:
            row = self.rows.get(tag)
            if row is not None and row.expiry > now:
                out.append((tag, row.tps, row.expiry))
        return tuple(out)

    def depth(self) -> int:
        return sum(len(r.queue) for r in self.rows.values())


class GrvAdmissionQueues:
    """Per-priority admission queues at one proxy's GRV stream."""

    def __init__(self, process, stats: "flow.CounterCollection"):
        self.process = process
        self.stats = stats
        self._queues: Dict[int, deque] = {PRIORITY_IMMEDIATE: deque(),
                                          PRIORITY_DEFAULT: deque(),
                                          PRIORITY_BATCH: deque()}
        self._default_bucket = TokenBucket()
        self._batch_bucket = TokenBucket()
        self.tags = TagThrottleTable()

    # -- intake ----------------------------------------------------------
    def submit(self, entry: Entry, now: float) -> None:
        """Queue one GRV request (or reject it, bounded): per-tag gate
        first, then the class FIFO. The reply is answered either by a
        later tick's admission or by a rejection here — never dropped."""
        reply, count, prio, t0, tags = entry
        # normalize foreign priority values onto the three classes the
        # way the rate gate reads them (>= immediate bypasses, <= batch
        # pays the batch bucket)
        if prio >= PRIORITY_IMMEDIATE:
            prio = PRIORITY_IMMEDIATE
        elif prio <= PRIORITY_BATCH:
            prio = PRIORITY_BATCH
        else:
            prio = PRIORITY_DEFAULT
        entry = (reply, count, prio, t0, tags)
        if SERVER_KNOBS.tag_throttling and tags:
            # the tag gate runs FIRST: a pace-limited request parks in
            # its tag's FIFO and only occupies a class queue once the
            # pacing releases it — so the class depth bound below
            # judges only requests actually contending for admission
            row = self.tags.applying(tags, prio, now)
            if row is not None:
                if row.bucket.available(now) < count:
                    # pacing denies: park (or bound-reject) — a full
                    # class queue is irrelevant to a request that was
                    # never going to occupy a class slot yet
                    if len(row.queue) >= int(
                            SERVER_KNOBS.tag_throttle_queue_max):
                        flow.cover("admission.tag_queue_full")
                        self.stats.counter("throttle_rejected").add(1)
                        self._reject(reply, "tag_throttled")
                        return
                    flow.cover("admission.tag_parked")
                    self.stats.counter("throttle_delayed").add(1)
                    row.queue.append(entry)
                    self._note_depth()
                    return
                if not self._class_room(prio):
                    # pacing would admit but the class queue is full:
                    # reject WITHOUT consuming the token — burning the
                    # tag's budget on a request that was never
                    # admitted would cut the tag below its commanded
                    # tps exactly when the proxy is already shedding
                    flow.cover("admission.queue_full")
                    self.stats.counter("admission_rejected").add(1)
                    self._reject(reply, "proxy_memory_limit_exceeded")
                    return
                row.bucket.force_take(count, now)   # peeked: affords
        self._class_enqueue(entry)
        self._note_depth()

    def _class_room(self, prio: int) -> bool:
        """Does the class FIFO have room? Immediate always does: it
        drains every tick, is never shed, and can hold at most one
        window's arrivals."""
        return prio >= PRIORITY_IMMEDIATE or \
            len(self._queues[prio]) < int(SERVER_KNOBS.grv_queue_max)

    def _class_enqueue(self, entry: Entry) -> None:
        """Append to the entry's class FIFO, honoring the depth bound
        — the one gatekeeper for every path into a class queue (fresh
        submits AND tag-queue releases)."""
        if not self._class_room(entry[2]):
            flow.cover("admission.queue_full")
            self.stats.counter("admission_rejected").add(1)
            self._reject(entry[0], "proxy_memory_limit_exceeded")
            return
        self._queues[entry[2]].append(entry)

    @staticmethod
    def _reject(reply, name: str) -> None:
        try:
            reply.send_error(error(name))
        except Exception:
            pass  # already answered

    # -- the per-window admission decision -------------------------------
    def tick(self, now: float, rate: float, batch_rate: float,
             interval: float) -> List[Entry]:
        """One GRV_BATCH_INTERVAL window: release tag-parked requests
        whose pacing allows, shed wait-bound violations, then admit in
        STRICT class order — immediate drains first and pays nothing,
        default pays the default bucket, batch drains last and pays
        BOTH buckets (so batch traffic starves first, exactly the
        separate batch limit's point). The returned batch is served
        with ONE causal-confirmation round trip."""
        k = SERVER_KNOBS
        # tag gate upkeep: expired rows free their parked queues; live
        # rows release at their commanded pace, FIFO (releases pass
        # through the same bounded class enqueue as fresh submits)
        for entry in self.tags.expire(now):
            self._class_enqueue(entry)
            self.stats.counter("throttle_released").add(1)
        # a tag-parked request past the wait bound is shed BEFORE the
        # release pass (never released-and-shed in one tick), and with
        # the TAG error — its wait was designed pacing, and labeling
        # it proxy overload would steer an operator at the wrong knob
        max_wait = float(SERVER_KNOBS.grv_queue_max_wait)
        for row in self.tags.rows.values():
            while row.queue and now - row.queue[0][3] > max_wait:
                flow.cover("admission.tag_wait_bound")
                self.stats.counter("throttle_rejected").add(1)
                self._reject(row.queue.popleft()[0], "tag_throttled")
        for row in self.tags.rows.values():
            while row.queue:
                cnt = row.queue[0][1]
                if not self._class_room(row.queue[0][2]):
                    # class queue full: stay parked (no token spent);
                    # the pacing resumes once admission drains room
                    break
                if row.bucket.try_take(cnt, now):
                    pass
                elif row.bucket.available(now) >= row.bucket.burst - 1e-9:
                    # a head bigger than the burst (a client-coalesced
                    # multi-transaction request) releases into DEBT at
                    # a full bucket — the refill repays it, so the
                    # average stays at the commanded tps and the head
                    # can never wedge the tag queue forever
                    flow.cover("admission.tag_debt")
                    row.bucket.force_take(cnt, now)
                else:
                    break
                entry = row.queue.popleft()
                self._class_enqueue(entry)
                self.stats.counter("throttle_released").add(1)
        # wait bound: a queued request past the bound is shed with the
        # retryable overflow error — bounded wait is the contract that
        # keeps ADMITTED latency meaningful under overload (FIFO, so
        # the head is always the oldest)
        for prio, q in self._queues.items():
            if prio >= PRIORITY_IMMEDIATE:
                continue   # immediate drains every tick; never shed
            while q and now - q[0][3] > max_wait:
                flow.cover("admission.wait_bound")
                self.stats.counter("admission_timed_out").add(1)
                self._reject(q.popleft()[0], "proxy_memory_limit_exceeded")

        burst_ivals = float(k.grv_burst_intervals)
        # the class buckets ALWAYS charge: with tag-throttling-only
        # armed (GRV_ADMISSION_CONTROL=0) these entries bypass the
        # legacy batcher, so the budget gate the batcher would have
        # applied must live here too — the rate fed in is the same
        # ratekeeper budget either way (undivided in that posture)
        self._default_bucket.set_rate(
            rate, rate * burst_ivals * interval, now)
        self._batch_bucket.set_rate(
            batch_rate, batch_rate * burst_ivals * interval, now)
        out: List[Entry] = []
        # immediate: never queued behind anything, never charged
        imm = self._queues[PRIORITY_IMMEDIATE]
        while imm:
            out.append(imm.popleft())
        if out:
            self.stats.counter("admission_admitted_immediate").add(
                sum(e[1] for e in out))
        # default: FIFO while the default bucket affords; an oversized
        # head with at least one token admits into debt (it could
        # never afford its count otherwise and would starve)
        admitted_default = 0
        dq = self._queues[PRIORITY_DEFAULT]
        while dq:
            cnt = dq[0][1]
            if self._default_bucket.try_take(cnt, now):
                pass
            elif not admitted_default and \
                    self._default_bucket.available(now) >= 1.0:
                flow.cover("admission.default_debt")
                self._default_bucket.force_take(cnt, now)
            else:
                break
            admitted_default += cnt
            out.append(dq.popleft())
        if admitted_default:
            self.stats.counter("admission_admitted_default").add(
                admitted_default)
        # batch: last, and pays BOTH buckets
        admitted_batch = 0
        bq = self._queues[PRIORITY_BATCH]
        while bq:
            cnt = bq[0][1]
            if self._batch_bucket.available(now) >= cnt and \
                    self._default_bucket.available(now) >= cnt:
                self._batch_bucket.force_take(cnt, now)
                self._default_bucket.force_take(cnt, now)
            elif not admitted_batch and not admitted_default and \
                    self._batch_bucket.available(now) >= 1.0 and \
                    self._default_bucket.available(now) >= 1.0:
                flow.cover("admission.batch_debt")
                self._batch_bucket.force_take(cnt, now)
                self._default_bucket.force_take(cnt, now)
            else:
                break
            admitted_batch += cnt
            out.append(bq.popleft())
        if admitted_batch:
            self.stats.counter("admission_admitted_batch").add(
                admitted_batch)
        self._note_depth()
        return out

    # -- surfaces --------------------------------------------------------
    def depth(self) -> int:
        return (sum(len(q) for q in self._queues.values())
                + self.tags.depth())

    def _note_depth(self) -> None:
        self.stats.counter("admission_queued_now").set(self.depth())

    def reply_throttles(self, tags, now: float) -> Tuple:
        return self.tags.reply_rows(tags, now)

    def status(self) -> dict:
        k = SERVER_KNOBS
        snap = self.stats.snapshot()
        return {
            "grv_admission_enabled": int(bool(k.grv_admission_control)),
            "tag_throttling_enabled": int(bool(k.tag_throttling)),
            "admitted": {
                name: snap.get(f"admission_admitted_{name}", 0)
                for name in ("immediate", "default", "batch")},
            "queued": {
                PRIORITY_NAMES[p]: len(q)
                for p, q in self._queues.items()},
            "rejected": snap.get("admission_rejected", 0),
            "timed_out": snap.get("admission_timed_out", 0),
            "throttle_delayed": snap.get("throttle_delayed", 0),
            "throttle_released": snap.get("throttle_released", 0),
            "throttle_rejected": snap.get("throttle_rejected", 0),
            "confirm_rounds": snap.get("grv_confirm_rounds", 0),
            "tag_rows": [r.doc() for r in sorted(
                self.tags.rows.values(), key=lambda r: r.tag)],
        }

    def shutdown(self) -> None:
        """Epoch over: break every queued request so stale clients fail
        over instead of hanging (same contract as the proxy's GRV
        drain)."""
        for q in self._queues.values():
            while q:
                self._reject(q.popleft()[0], "broken_promise")
        for row in self.tags.rows.values():
            while row.queue:
                self._reject(row.queue.popleft()[0], "broken_promise")
        self.tags.rows.clear()
