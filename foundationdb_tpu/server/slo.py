"""SLO engine: declarative health rules over the metric history.

The burn-rate rules follow the multiwindow, multi-burn-rate alerting
shape (SRE workbook ch. 5): an error budget (SLO_ERROR_BUDGET — the
fraction of requests allowed past the latency band edge), and a page
only when BOTH a short and a long window burn that budget faster than
their rate thresholds — the fast window catches an acute breach within
seconds, the slow window keeps a transient blip from paging. The other
rule kinds are direct: `ceiling` (a gauge must not sit above a limit
for a sustained window), `zero` (a corruption-grade counter must never
move — shadow-resolve divergence), and the recovery-time bound is a
ceiling on the recorder's `cluster/recovery_age_ms` excursion clock.

`evaluate()` is pure (series in, verdict out) and shared by BOTH
consumers: the CC's continuous loop feeds it the recorder's in-memory
tail, and tools/soak.py's restart-safe read-back feeds it series read
straight from \\xff\\x02/metrics/ — the same math decides "was this run
healthy" online and post-hoc.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

from .. import flow

Series = Dict[str, List[Tuple[int, int]]]   # signal -> [(ts_ms, value)]


class SloRule(NamedTuple):
    name: str
    kind: str                    # ceiling | zero | burn_rate
    signal: str                  # ceiling/zero: the gauge; burn: bad
    threshold: float = 0.0       # ceiling limit (same units as signal)
    window_s: float = 10.0       # ceiling sustain window
    total_signal: str = ""       # burn_rate: the total counter
    budget: float = 0.01         # burn_rate: error budget fraction
    fast_window_s: float = 10.0
    slow_window_s: float = 60.0
    fast_rate: float = 14.0
    slow_rate: float = 3.0


def default_rules() -> List[SloRule]:
    """The shipped rule table, parameterized by the SLO_* knobs (the
    README documents this table; `cli slo` renders its live verdicts)."""
    k = flow.SERVER_KNOBS
    return [
        SloRule("commit_p99", "ceiling", "latency/commit/p99_ms",
                threshold=k.slo_commit_p99_ms,
                window_s=k.slo_burn_fast_window),
        SloRule("grv_p99", "ceiling", "latency/grv/p99_ms",
                threshold=k.slo_grv_p99_ms,
                window_s=k.slo_burn_fast_window),
        SloRule("recovery_time", "ceiling", "cluster/recovery_age_ms",
                threshold=k.slo_recovery_seconds * 1000.0,
                window_s=0.0),
        SloRule("no_divergence", "zero", "cluster/shadow_mismatches"),
        SloRule("commit_error_budget", "burn_rate", "latency/commit/bad",
                total_signal="latency/commit/total",
                budget=k.slo_error_budget,
                fast_window_s=k.slo_burn_fast_window,
                slow_window_s=k.slo_burn_slow_window,
                fast_rate=k.slo_burn_fast_rate,
                slow_rate=k.slo_burn_slow_rate),
        SloRule("grv_error_budget", "burn_rate", "latency/grv/bad",
                total_signal="latency/grv/total",
                budget=k.slo_error_budget,
                fast_window_s=k.slo_burn_fast_window,
                slow_window_s=k.slo_burn_slow_window,
                fast_rate=k.slo_burn_fast_rate,
                slow_rate=k.slo_burn_slow_rate),
    ]


def _window(samples: List[Tuple[int, int]], now_ms: int,
            window_s: float) -> List[Tuple[int, int]]:
    cutoff = now_ms - int(window_s * 1000)
    return [s for s in samples if s[0] >= cutoff]


def _delta(samples: List[Tuple[int, int]], now_ms: int,
           window_s: float) -> Optional[int]:
    """Counter increase across a window; None without two samples in
    it (no verdict beats a wrong one — rules stay `ok` until the
    series can actually answer)."""
    w = _window(samples, now_ms, window_s)
    if len(w) < 2:
        return None
    return w[-1][1] - w[0][1]


def burn_rate(samples_bad: List[Tuple[int, int]],
              samples_total: List[Tuple[int, int]], now_ms: int,
              window_s: float, budget: float) -> Optional[float]:
    """How many times faster than allowed the error budget burned over
    the window: (bad/total)/budget. 1.0 = exactly on budget."""
    d_bad = _delta(samples_bad, now_ms, window_s)
    d_total = _delta(samples_total, now_ms, window_s)
    if d_bad is None or d_total is None or d_total <= 0:
        return None
    return (max(d_bad, 0) / d_total) / max(budget, 1e-9)


def _eval_rule(rule: SloRule, series: Series, now_ms: int) -> dict:
    doc = {"name": rule.name, "kind": rule.kind, "ok": True,
           "value": None, "threshold": rule.threshold}
    samples = series.get(rule.signal, [])
    if rule.kind == "zero":
        latest = samples[-1][1] if samples else 0
        doc.update(value=latest, threshold=0, ok=latest == 0)
    elif rule.kind == "ceiling":
        if rule.window_s <= 0:
            # instantaneous gauge bound (recovery age integrates its
            # own time — one over-limit sample IS a sustained breach)
            latest = samples[-1][1] if samples else 0
            doc.update(value=latest, ok=latest <= rule.threshold)
        else:
            w = _window(samples, now_ms, rule.window_s)
            doc["value"] = w[-1][1] if w else None
            # sustained: every sample in the window over the limit,
            # and at least two so one blip never pages
            doc["ok"] = not (len(w) >= 2
                             and all(v > rule.threshold for _t, v in w))
    elif rule.kind == "burn_rate":
        fast = burn_rate(samples, series.get(rule.total_signal, []),
                         now_ms, rule.fast_window_s, rule.budget)
        slow = burn_rate(samples, series.get(rule.total_signal, []),
                         now_ms, rule.slow_window_s, rule.budget)
        doc.update(value=None if fast is None else round(fast, 3),
                   slow_value=None if slow is None else round(slow, 3),
                   threshold=rule.fast_rate,
                   slow_threshold=rule.slow_rate,
                   ok=not (fast is not None and slow is not None
                           and fast >= rule.fast_rate
                           and slow >= rule.slow_rate))
    return doc


def evaluate(rules: List[SloRule], series: Series, now_ms: int) -> dict:
    """The verdict document: per-rule ok/value rows + the rolled-up
    state (`ok` | `breach`)."""
    rows = [_eval_rule(r, series, now_ms) for r in rules]
    breached = [r["name"] for r in rows if not r["ok"]]
    return {"state": "breach" if breached else "ok",
            "breached": breached,
            "evaluated_at_ms": now_ms,
            "rules": rows}
