"""Tag throttling through the system keyspace: ratekeeper-side
auto-detection, the shared row reader, and the client-honored backoff.

Reference: fdbserver/Ratekeeper.actor.cpp monitorThrottlingChanges +
fdbclient/TagThrottle.actor.cpp — the ratekeeper watches per-tag
busyness reported by the proxies, writes AUTO throttle rows (tag,
priority, tps rate, expiry) under \\xff\\x02/throttledTags/, and
operators write MANUAL rows through `fdbcli throttle on|off|list`;
every GRV proxy watches the range and enforces the rates
(server/admission.py), and clients that receive tag-throttle info on a
GRV reply delay locally before their next request so the server sheds
work it never has to queue.

Three pieces:

- `TagThrottler` (mounted on the Ratekeeper): smooths each tag's
  started-transaction rate from the proxies' TransactionTagCounter
  rows (PR 6); a tag past TAG_THROTTLE_BUSY_RATE gets an auto row
  cutting it to TAG_THROTTLE_TARGET_FRACTION of its observed rate for
  TAG_THROTTLE_DURATION. Rows are committed BLIND through the
  ordinary pipeline (no conflict ranges — last writer wins, and the
  throttler is the only auto writer), so manual and automatic
  throttles round-trip through the same durable keys. Expired auto
  rows are cleared by their writer; manual rows are never touched.
- `read_throttle_rows`: the proxy poll loop's raw storage-range read
  of the table (dbinfo shard walk, the RepairManager re-read idiom).
- `ClientTagThrottleCache`: per-Database cache of the (tag, tps,
  expiry) triples ridden in on GRV replies; `delay()` paces the next
  tagged GRV at the commanded rate (capped at
  CLIENT_TAG_BACKOFF_MAX), mirroring PR 8's conflict-window plumbing.

AUTO_TAG_THROTTLING=0 (default) disables detection; TAG_THROTTLING=0
disables enforcement and backoff. BUGGIFY arms both randomly.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import flow
from ..flow import SERVER_KNOBS, TaskPriority
from ..flow.smoother import SmoothedRate
from .systemkeys import (THROTTLED_TAGS_END, THROTTLED_TAGS_PREFIX,
                         encode_tag_throttle_value,
                         parse_tag_throttle_value, parse_throttled_tag_key,
                         throttled_tag_key)
from .types import (COMPARE_AND_CLEAR, SET_VALUE, CommitRequest,
                    MutationRef, PRIORITY_DEFAULT, StorageGetRangeRequest)

#: a parsed throttledTags row: (tag, tps, expiry, priority, auto)
ThrottleRow = Tuple[bytes, float, float, int, bool]


def _overlapping_shards(storages, begin: bytes, end: bytes):
    out = []
    for s in storages:
        if (s.end is None or begin < s.end) and s.begin < end:
            out.append(s)
    return out


async def read_throttle_rows(info, process, version: int) -> List[ThrottleRow]:
    """The throttledTags table read straight from storage at `version`
    (the proxy's committed version — what a client scan would see).
    Unparseable rows are skipped, the same skip-foreign-encodings
    contract every system-keyspace reader honors."""
    rows: List[ThrottleRow] = []
    if info is None or not info.storages:
        return rows
    for s in _overlapping_shards(info.storages, THROTTLED_TAGS_PREFIX,
                                 THROTTLED_TAGS_END):
        b = max(THROTTLED_TAGS_PREFIX, s.begin)
        e = (THROTTLED_TAGS_END if s.end is None
             else min(THROTTLED_TAGS_END, s.end))
        if b >= e or not s.replicas:
            continue
        kvs = await s.replicas[0].ranges.get_reply(
            StorageGetRangeRequest(b, e, version, 1 << 20), process)
        for key, value in kvs:
            tag = parse_throttled_tag_key(key)
            parsed = parse_tag_throttle_value(value)
            if tag is None or parsed is None:
                continue
            tps, expiry, priority, auto = parsed
            rows.append((tag, tps, expiry, priority, auto))
    return rows


class TagThrottler:
    """The ratekeeper's auto-throttler (ref: Ratekeeper's
    autoThrottleTags loop). Counters ride its own CounterCollection so
    the status doc can report detection activity beside the proxies'
    enforcement counters."""

    def __init__(self, process, cc):
        self.process = process
        self.cc = cc
        self.stats = flow.CounterCollection("tag_throttler")
        self._rates: Dict[bytes, SmoothedRate] = {}
        # per-(storage server, tag) read-request rate trackers — the
        # TAG_THROTTLE_STORAGE_BUSYNESS input (ISSUE 13): the
        # reference's ratekeeper reads tag busyness FROM the storage
        # servers, so a tenant hammering one shard is throttled even
        # when its cluster-wide rate looks modest
        self._ss_rates: Dict[tuple, SmoothedRate] = {}
        #: tag -> (expiry, exact encoded value) of the auto row WE
        #: wrote — the value is kept so expiry cleanup can use
        #: COMPARE_AND_CLEAR and can never delete a manual row an
        #: operator wrote over ours in the meantime
        self._written: Dict[bytes, tuple] = {}

    async def run(self) -> None:
        while True:
            interval = float(SERVER_KNOBS.tag_throttle_update_interval)
            await flow.delay(interval if interval > 0 else 1.0,
                             TaskPriority.RATEKEEPER)
            if not SERVER_KNOBS.auto_tag_throttling:
                continue
            try:
                await self._update()
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                # a mid-recovery commit failure retries next tick

    def _proxy_roles(self, info):
        from .cluster_controller import epoch_roles
        from .proxy import Proxy
        return epoch_roles(self.cc.workers, info.epoch, Proxy)

    async def _update(self) -> None:
        k = SERVER_KNOBS
        info = self.cc.dbinfo.get()
        if not info.proxies:
            return
        now = flow.now()
        # cluster-wide per-tag started totals (the busyness source:
        # PR 6's TransactionTagCounter at every proxy)
        totals: Dict[bytes, int] = {}
        for _rn, role in self._proxy_roles(info):
            for row in role.tag_counter.top(1 << 20):
                tag = bytes.fromhex(row["tag"])
                totals[tag] = totals.get(tag, 0) + row["started"]
        tau = float(k.qos_smoothing_tau)
        # per-storage-server tag busyness (ISSUE 13): with the knob
        # armed, each (server, tag)'s smoothed read-request rate joins
        # the detection — the per-SS MAX is what a single hot shard
        # sees, which cluster-wide proxy rates dilute by design
        ss_busy: Dict[bytes, float] = {}
        if not (k.tag_throttle_storage_busyness
                and k.storage_heat_tracking):
            # disarmed mid-run: drop the accumulated (server, tag)
            # trackers — stale pairs must not pin memory or keep
            # reporting through tracked_ss_pairs
            if self._ss_rates:
                self._ss_rates.clear()
        else:
            live_ss: set = set()
            for name, obj in sorted(self.cc._storage_objs.items()):
                if not obj.process.alive:
                    continue
                for row in obj.tag_counter.top(1 << 20):
                    tag = bytes.fromhex(row["tag"])
                    key = (name, tag)
                    live_ss.add(key)
                    sm = self._ss_rates.get(key)
                    if sm is None:
                        sm = self._ss_rates[key] = SmoothedRate()
                    rate = sm.sample_total(row["started"], now, tau)
                    if rate > ss_busy.get(tag, 0.0):
                        ss_busy[tag] = rate
            for key in [kk for kk in self._ss_rates if kk not in live_ss]:
                del self._ss_rates[key]
        candidates = []   # busy tags due a (re)written auto row:
        #                   (tag, txn rate the tps command derives
        #                   from, the rate that crossed detection)
        for tag in sorted(set(totals) | set(ss_busy)):
            rate = 0.0
            if tag in totals:
                sm = self._rates.get(tag)
                if sm is None:
                    sm = self._rates[tag] = SmoothedRate()
                rate = sm.sample_total(totals[tag], now, tau)
            rate_eff = max(rate, ss_busy.get(tag, 0.0))
            if rate_eff < float(k.tag_throttle_busy_rate):
                continue
            if rate < float(k.tag_throttle_busy_rate):
                # only the per-SS signal crossed the line: the
                # storage-aware detection ROADMAP item 3 steers by
                flow.cover("tag_throttler.storage_busyness")
            expiry = self._written.get(tag, (0.0, b""))[0]
            if expiry - now > float(k.tag_throttle_duration) / 2:
                continue   # the active row still covers the abuse
            candidates.append((tag, rate, rate_eff))
        # a live MANUAL row takes precedence over auto-throttling: the
        # operator's word stands, however busy the tag reads (ref:
        # manual throttles winning over auto in TagThrottle.actor.cpp)
        # — so the throttler reads what the table ACTUALLY holds
        # before writing, not just its own bookkeeping
        manual_live = set()
        if candidates and info.proxies[0].raw_committed is not None:
            from .types import RAW_COMMITTED_REQUEST
            ver = await flow.timeout_error(
                info.proxies[0].raw_committed.get_reply(
                    RAW_COMMITTED_REQUEST, self.process), 2.0)
            for tag, _tps, expiry, _prio, auto in await read_throttle_rows(
                    info, self.process, ver):
                if not auto and expiry > now:
                    manual_live.add(tag)
        mutations = []
        throttled = []   # (tag, rate, tps, new_expiry, value) pending
        for tag, txn_rate, rate in candidates:
            if tag in manual_live:
                flow.cover("tag_throttler.manual_precedence")
                continue
            # the commanded tps is in TRANSACTIONS/sec (what the
            # proxy's per-tag pacing bucket enforces), so it must
            # derive from the tag's txn rate — a storage-detected
            # read-heavy tenant (high read-request rate, modest txn
            # rate) would otherwise get a row far above its own txn
            # rate that never throttles anything (code review r13)
            tps = max(float(k.tag_throttle_min_tps),
                      txn_rate * float(k.tag_throttle_target_fraction))
            new_expiry = now + float(k.tag_throttle_duration)
            value = encode_tag_throttle_value(tps, new_expiry,
                                              PRIORITY_DEFAULT, auto=True)
            mutations.append(MutationRef(SET_VALUE,
                                         throttled_tag_key(tag), value))
            throttled.append((tag, rate, tps, new_expiry, value))
        # clear expired auto rows we wrote — via COMPARE_AND_CLEAR on
        # the EXACT value we committed, so an operator's manual row
        # written over ours in the meantime survives the cleanup
        # (last-writer-wins for sets; the janitor only ever removes
        # its own writes). A tag being REWRITTEN this very tick (its
        # old row expired while commits were failing, but it is still
        # busy) must not also be cleared — the clear would apply after
        # the set and kill the fresh row
        rewriting = {t for t, _r, _tp, _e, _v in throttled}
        cleared = [t for t, (exp, _v) in self._written.items()
                   if exp <= now and t not in rewriting]
        for tag in cleared:
            mutations.append(MutationRef(COMPARE_AND_CLEAR,
                                         throttled_tag_key(tag),
                                         self._written[tag][1]))
        # prune rate trackers for tags that vanished from the counters
        for tag in [t for t in self._rates
                    if t not in totals and t not in self._written]:
            del self._rates[tag]
        if not mutations:
            return
        # blind write through the ordinary commit pipeline: the rows
        # are durable, replicated data any reader can scan. The
        # bookkeeping applies only AFTER the commit returns — a failed
        # commit (swallowed by run()) must leave state claiming the
        # rows do NOT exist, so the next tick genuinely retries
        # instead of trusting a row that never landed
        await flow.timeout_error(
            info.proxies[0].commits.get_reply(
                CommitRequest(0, (), (), tuple(mutations)),
                self.process), 2.0)
        for tag, rate, tps, new_expiry, value in throttled:
            flow.cover("tag_throttler.auto_throttle")
            self._written[tag] = (new_expiry, value)
            self.stats.counter("auto_throttles").add(1)
            flow.TraceEvent("TagThrottleAuto", self.process.name).detail(
                Tag=tag.hex(), ObservedRate=round(rate, 1),
                ThrottleTps=round(tps, 2),
                Expiry=round(new_expiry, 2)).log()
        for tag in cleared:
            del self._written[tag]
            self._rates.pop(tag, None)
            self.stats.counter("auto_cleared").add(1)

    def status(self) -> dict:
        snap = self.stats.snapshot()
        return {
            "enabled": int(bool(SERVER_KNOBS.auto_tag_throttling)),
            "auto_throttles": snap.get("auto_throttles", 0),
            "auto_cleared": snap.get("auto_cleared", 0),
            "tracked_tags": len(self._rates),
            "active_auto": sorted(t.hex() for t in self._written),
            # storage-aware detection posture (ISSUE 13)
            "storage_busyness_enabled": int(bool(
                SERVER_KNOBS.tag_throttle_storage_busyness)),
            "tracked_ss_pairs": len(self._ss_rates),
        }


# -- client side -------------------------------------------------------

#: process-wide client-backoff counters (the client_profile pattern:
#: every simulated client shares one collection, surfaced through
#: status.cluster.admission_control.client and the exporter)
g_client_throttle_stats = flow.CounterCollection("client_tag_throttle")


def note_backoff(seconds: float) -> None:
    g_client_throttle_stats.counter("backoffs").add(1)
    g_client_throttle_stats.counter("backoff_ms").add(
        int(seconds * 1000))


def client_throttle_counters() -> dict:
    return g_client_throttle_stats.snapshot()


class ClientTagThrottleCache:
    """Per-Database cache of server-advertised tag throttles (the
    client-honored-backoff half). A row is (tag, tps, expiry): until
    expiry, tagged GRVs pace themselves at tps locally — the delayed
    request never reaches the proxy's queue at all. Pacing state
    (`next_slot`) survives row refreshes so a renewed throttle cannot
    be gamed by re-arrival."""

    __slots__ = ("_rows",)

    def __init__(self):
        #: tag -> [tps, expiry, next_slot]
        self._rows: Dict[bytes, list] = {}

    def update(self, rows, now: float) -> None:
        for tag, tps, expiry in rows:
            ent = self._rows.get(tag)
            if ent is None:
                self._rows[tag] = [float(tps), float(expiry), now]
            else:
                ent[0] = float(tps)
                ent[1] = float(expiry)
        g_client_throttle_stats.counter("updates").add(1)
        g_client_throttle_stats.counter("tags_cached").set(len(self._rows))

    def delay(self, tags, now: float) -> float:
        """Seconds this tagged request should wait before its GRV
        (0.0 = go now). Advances the pacing slot — the caller is
        expected to proceed after waiting."""
        d = 0.0
        for tag in tags:
            ent = self._rows.get(tag)
            if ent is None:
                continue
            tps, expiry, nxt = ent
            if expiry <= now:
                del self._rows[tag]
                g_client_throttle_stats.counter("tags_cached").set(
                    len(self._rows))
                continue
            start = max(nxt, now)
            ent[2] = start + 1.0 / max(tps, 1e-6)
            d = max(d, start - now)
        return min(d, float(SERVER_KNOBS.client_tag_backoff_max))
