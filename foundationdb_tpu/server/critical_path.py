"""Commit critical-path decomposition (ISSUE 18 tentpole, part 1).

Reference: the commit-debug station timeline the reference threads
debug ids through (`resolveBatch`, fdbserver/Resolver.actor.cpp:71 and
the g_traceBatch locations in MasterProxyServer.actor.cpp) — grown
into a measurement plane: while CRITICAL_PATH is armed, EVERY commit
batch records consecutive `flow.now()` timestamps at the pipeline
stations, so each transaction's end-to-end latency decomposes into a
telescoping sum of per-station segments:

    proxy_batcher   arrival -> batch close (batcher window + deferral)
    commit_version  batch close -> version assigned (interlock + master)
    resolve         version -> verdicts drained (submit + device + drain)
    tlog_fsync      verdicts -> every log's durability ack
    reply           ack -> client reply sent (incl. injections)

Because the segment boundaries are the SAME clock reads, the segments
sum to the measured end-to-end latency exactly (the residual is float
rounding — bounded by CRITICAL_PATH_TOLERANCE and pinned by test).
The resolver and tlog keep their own queue-vs-service splits (version-
ordering wait vs actual service) in `RolePathRecorder`s; the cluster
controller folds everything into a decaying dominant-station table
(`status.cluster.critical_path`, `cli path`, `fdbtpu_path_*`).

Everything here is inert data structures: no actors, no RNG, no knob
writes — the off posture (knob 0) never constructs a sample.
"""

from __future__ import annotations

from typing import Optional

from .. import flow

#: pipeline stations in path order (the proxy's segment keys)
STATIONS = ("proxy_batcher", "commit_version", "resolve", "tlog_fsync",
            "reply")

#: bound on the arrival-stamp map: commits captured by the admission
#: scheduler and then rejected never reach a batch, so the map must
#: self-trim instead of growing with them
MAX_ARRIVALS = 4096


def dominant_station(segments: dict) -> str:
    """The station that contributed the most seconds (ties break in
    path order, so a uniform batch reads as batcher-bound)."""
    best = STATIONS[0]
    best_v = -1.0
    for s in STATIONS:
        v = segments.get(s, 0.0)
        if v > best_v:
            best, best_v = s, v
    return best


class ProxyPathRecorder:
    """Per-proxy decomposition state: arrival stamps (keyed by the
    reply promise's identity — the one object that survives scheduler
    deferral and re-entry intact), per-station latency bands, dominant
    counts, and a bounded sample buffer the CC loop drains."""

    def __init__(self):
        self._arrivals: dict = {}
        self.bands = {s: flow.LatencyBands(s) for s in STATIONS}
        self.e2e = flow.LatencyBands("end_to_end")
        self.dominant: dict = {s: 0 for s in STATIONS}
        self.seconds: dict = {s: 0.0 for s in STATIONS}
        self.samples = 0
        self.max_residual = 0.0
        self._pending: list = []   # recent samples awaiting the CC fold

    def note_arrival(self, token, now: float) -> None:
        """Stamp a commit's queue entry (batcher pop). setdefault: a
        scheduler-deferred commit re-enters the stream later, and its
        wait in the deferral queue must count as batcher wait."""
        if len(self._arrivals) >= MAX_ARRIVALS and \
                id(token) not in self._arrivals:
            self._arrivals.pop(next(iter(self._arrivals)))
        self._arrivals.setdefault(id(token), now)

    def take_arrival(self, token, default: float) -> float:
        return self._arrivals.pop(id(token), default)

    def record(self, segments: dict, e2e: float) -> None:
        """Fold one transaction's decomposition. `segments` maps every
        station to seconds; their sum equals `e2e` up to rounding."""
        self.samples += 1
        total = 0.0
        for s in STATIONS:
            v = segments.get(s, 0.0)
            total += v
            self.bands[s].record(v)
            self.seconds[s] += v
        self.e2e.record(e2e)
        dom = dominant_station(segments)
        self.dominant[dom] += 1
        residual = abs(total - e2e)
        if residual > self.max_residual:
            self.max_residual = residual
        cap = int(flow.SERVER_KNOBS.critical_path_sample_max)
        if len(self._pending) < cap:
            self._pending.append((dom, segments.get(dom, 0.0), e2e))

    def drain_samples(self) -> list:
        """Hand the buffered (dominant, dominant_seconds, e2e) samples
        to the CC fold and reset the buffer."""
        out, self._pending = self._pending, []
        return out

    def snapshot(self) -> dict:
        return {
            "samples": self.samples,
            "max_residual_seconds": round(self.max_residual, 9),
            "dominant": dict(self.dominant),
            "stations": {s: {"seconds": round(self.seconds[s], 6),
                             "bands": self.bands[s].snapshot()}
                         for s in STATIONS},
            "end_to_end": self.e2e.snapshot(),
        }


class RolePathRecorder:
    """Queue-vs-service split for one serving role (resolver, tlog):
    `wait` is version-ordering / queue time before service starts,
    `service` is the actual work (resolve submit->drain, fsync). The
    tlog also stashes per-request enter stamps here (keyed by request
    identity) to bridge its two-actor accept -> durable path."""

    def __init__(self, name: str):
        self.name = name
        self.wait = flow.LatencyBands("wait")
        self.service = flow.LatencyBands("service")
        self._enter: dict = {}

    def note_enter(self, token, now: float) -> None:
        if len(self._enter) >= MAX_ARRIVALS and \
                id(token) not in self._enter:
            self._enter.pop(next(iter(self._enter)))
        self._enter[id(token)] = now

    def take_enter(self, token, default: float) -> float:
        return self._enter.pop(id(token), default)

    def record(self, wait_s: float, service_s: float) -> None:
        self.wait.record(max(0.0, wait_s))
        self.service.record(max(0.0, service_s))

    def snapshot(self) -> dict:
        return {"wait": self.wait.snapshot(),
                "service": self.service.snapshot()}


class CriticalPathTable:
    """Decaying dominant-station rollup at the cluster controller
    (the ConflictHotSpots shape: exponentially-decayed score + raw
    totals, bounded by construction — the station set is finite)."""

    def __init__(self, half_life: Optional[float] = None):
        self.half_life = half_life
        self._rows: dict = {}   # station -> [score, count, seconds, t]

    def _hl(self) -> float:
        return (self.half_life if self.half_life is not None
                else float(flow.SERVER_KNOBS.critical_path_half_life))

    def _decayed(self, score: float, since: float, now: float) -> float:
        hl = self._hl()
        if now <= since or hl <= 0:
            return score
        return score * 0.5 ** ((now - since) / hl)

    def record(self, station: str, seconds: float, now: float) -> None:
        row = self._rows.get(station)
        if row is None:
            row = self._rows[station] = [0.0, 0, 0.0, now]
        row[0] = self._decayed(row[0], row[3], now) + seconds
        row[1] += 1
        row[2] += seconds
        row[3] = now

    def top(self, now: Optional[float] = None) -> list:
        """Status-ready rows, heaviest decayed cause first."""
        if now is None:
            now = flow.now()
        rows = [(self._decayed(sc, t, now), n, sec, st)
                for st, (sc, n, sec, t) in self._rows.items()]
        rows.sort(key=lambda r: (-r[0], r[3]))
        return [{"station": st, "score": round(score, 6), "count": n,
                 "seconds": round(sec, 6)}
                for score, n, sec, st in rows]
