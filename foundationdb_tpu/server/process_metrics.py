"""Per-OS-process resource telemetry (ISSUE 18 tentpole, part 2).

Reference: the reference's ProcessMetrics trace event (flow/
SystemMonitor.cpp) — CPU seconds, memory, file descriptors, run-loop
lag — sampled on a fixed cadence and carried in status. Here every
OS-process worker (soak/clusterbench) samples itself with stdlib-only
sources and serves the latest sample through its StatusRequest
endpoint and proc.*.json stub, so `federate_status` can line the
processes up side by side: the proxy-vs-resolver CPU-share question
ROADMAP item 2 (role split-out) is judged against these numbers.

Sources, all optional at runtime:
  - ``os.times()``            user+system CPU seconds (portable)
  - ``/proc/self/statm``      RSS pages x page size (Linux), falling
                              back to ``resource.getrusage`` maxrss
  - ``/proc/self/fd``         open descriptor count (Linux, else -1)
  - ``gc.get_stats()``        cumulative collections across gens
  - a wall-clock probe actor  run-loop lag (scheduled delay vs actual)

No RNG anywhere, and nothing here touches the deterministic
simulation clock except `loop_lag_probe`, which is only ever spawned
by real-time workers (never inside a pinned sim).
"""

from __future__ import annotations

import gc
import os
import time
from typing import Optional

from .. import flow

#: sample-dict keys every consumer (exporter, soak timeline, status
#: renderer) may rely on being present
SAMPLE_FIELDS = ("cpu_seconds", "rss_bytes", "open_fds",
                 "gc_collections", "loop_lag_ms", "uptime_seconds")


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # Linux reports KiB, macOS bytes; normalise the common case.
        return int(ru.ru_maxrss) * (1 if ru.ru_maxrss > 1 << 32 else 1024)
    except Exception:
        return -1


def _open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _gc_collections() -> int:
    try:
        return sum(s.get("collections", 0) for s in gc.get_stats())
    except Exception:
        return -1


class ProcessMetrics:
    """One process's resource sampler. `sample()` refreshes and
    returns the latest dict; `latest` keeps it for status serving."""

    def __init__(self, role: str = "", pid: Optional[int] = None):
        self.role = role
        self.pid = os.getpid() if pid is None else pid
        self._t_start = time.time()
        t = os.times()
        self._cpu_start = t.user + t.system
        self.loop_lag_ms = 0.0
        self.latest: dict = {}
        self.samples = 0

    def observe_loop_lag(self, lag_seconds: float) -> None:
        self.loop_lag_ms = max(0.0, lag_seconds) * 1000.0

    def sample(self) -> dict:
        t = os.times()
        self.samples += 1
        self.latest = {
            "role": self.role,
            "pid": self.pid,
            "cpu_seconds": round(t.user + t.system - self._cpu_start, 6),
            "rss_bytes": _rss_bytes(),
            "open_fds": _open_fds(),
            "gc_collections": _gc_collections(),
            "loop_lag_ms": round(self.loop_lag_ms, 3),
            "uptime_seconds": round(time.time() - self._t_start, 3),
            "samples": self.samples,
        }
        return self.latest


async def loop_lag_probe(metrics: ProcessMetrics, interval: float = 0.25):
    """Measure run-loop lag the SystemMonitor way: ask for a fixed
    real-time sleep and report how late it actually fired. Spawn only
    in wall-clock workers — under the sim scheduler `flow.delay` is
    exact by construction and the probe would just read 0."""
    while True:
        t0 = time.time()
        await flow.delay(interval, flow.TaskPriority.LOW_PRIORITY)
        metrics.observe_loop_lag(max(0.0, time.time() - t0 - interval))


def role_cpu_share(task_rows: list) -> dict:
    """Fold SIM_TASK_STATS busy rows ({"task": .., "busy_us": ..},
    flow/scheduler.py task_stats_report) into per-role CPU shares
    inside one host process — the number the role split-out (ROADMAP
    item 2) is judged against. Role is the leading token of the task
    name up to the first '.' with any '-e<epoch>-<idx>' tail cut."""
    busy: dict = {}
    total = 0.0
    for row in task_rows or []:
        name = str(row.get("task", ""))
        b = float(row.get("busy_us", 0.0))
        role = name.split(".")[0].split("-e")[0] or "other"
        busy[role] = busy.get(role, 0.0) + b
        total += b
    if total <= 0:
        return {}
    return {r: round(b / total, 4) for r, b in
            sorted(busy.items(), key=lambda kv: -kv[1])}


def _norm_role(role) -> str:
    """Collapse per-instance role names onto the role family: strip a
    trailing "-<digits>" instance suffix, then the "ext-" prefix a
    role-per-process host (tools/rolehost.py) prepends — so
    "ext-resolver-1" and an in-host "resolver" fold into one row."""
    r = str(role or "other")
    head, _, tail = r.partition(":")
    if tail:
        r = head        # "tcp:41025" / "gateway:<port>" -> family
    head, _, tail = r.rpartition("-")
    if head and tail.isdigit():
        r = head
    if r.startswith("ext-"):
        r = r[4:]
    return r or "other"


def federated_role_cpu_share(host_share: dict, host_cpu_seconds,
                             proc_docs: list) -> dict:
    """Cross-OS-process role CPU shares (ISSUE 19 satellite): the
    host's in-process share (`role_cpu_share` over SIM_TASK_STATS) is
    weighted by the host's measured `cpu_seconds`, and every worker or
    role process contributes its whole `cpu_seconds` under its role —
    so once resolvers and tlogs run in their own OS processes their CPU
    shows up in the same per-role table the in-process split-out was
    judged against, instead of vanishing from the host's fold."""
    busy: dict = {}
    host_cpu = max(0.0, float(host_cpu_seconds or 0.0))
    for role, share in (host_share or {}).items():
        r = _norm_role(role)
        try:
            busy[r] = busy.get(r, 0.0) + float(share) * host_cpu
        except (TypeError, ValueError):
            continue
    for doc in proc_docs or ():
        pm = (doc or {}).get("process_metrics") or {}
        cpu = pm.get("cpu_seconds")
        if not isinstance(cpu, (int, float)) or cpu < 0:
            continue
        r = _norm_role(doc.get("role") or pm.get("role"))
        busy[r] = busy.get(r, 0.0) + float(cpu)
    total = sum(busy.values())
    if total <= 0:
        return {}
    return {r: round(b / total, 4) for r, b in
            sorted(busy.items(), key=lambda kv: -kv[1])}
