"""Shared transaction-subsystem types.

Reference: fdbclient/CommitTransaction.h — `MutationRef` (:49-109, 21
mutation types; the slice carries SetValue/ClearRange, atomic ops land
with the storage engine work) and `CommitTransactionRef` (:136-168:
read/write conflict ranges + mutations + read_snapshot).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

SET_VALUE = 0
CLEAR_RANGE = 1

Range = Tuple[bytes, bytes]


class MutationRef(NamedTuple):
    type: int
    param1: bytes  # key / range begin
    param2: bytes  # value / range end


class CommitRequest(NamedTuple):
    """One transaction's commit payload (ref: CommitTransactionRequest)."""

    read_snapshot: int
    read_conflict_ranges: Tuple[Range, ...]
    write_conflict_ranges: Tuple[Range, ...]
    mutations: Tuple[MutationRef, ...]


class CommitReply(NamedTuple):
    version: int  # the commit version


class GetReadVersionReply(NamedTuple):
    version: int


class ResolveRequest(NamedTuple):
    """Ordered batch for a resolver (ref: ResolveTransactionBatchRequest,
    fdbserver/ResolverInterface.h)."""

    prev_version: int
    version: int
    transactions: Tuple[CommitRequest, ...]


class StorageGetRequest(NamedTuple):
    key: bytes
    version: int


class StorageGetRangeRequest(NamedTuple):
    begin: bytes
    end: bytes
    version: int
    limit: int


class TLogCommitRequest(NamedTuple):
    prev_version: int
    version: int
    mutations: Tuple[MutationRef, ...]


class TLogPeekRequest(NamedTuple):
    begin_version: int


class TLogPeekReply(NamedTuple):
    entries: Tuple[Tuple[int, Tuple[MutationRef, ...]], ...]
    committed_version: int
