"""Shared transaction-subsystem types.

Reference: fdbclient/CommitTransaction.h — `MutationRef` (:49-109, the
full 21-type vocabulary) and `CommitTransactionRef` (:136-168:
read/write conflict ranges + mutations + read_snapshot).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

SET_VALUE = 0
CLEAR_RANGE = 1
ADD_VALUE = 2
DEBUG_KEY_RANGE = 3     # tracing marker: carried, never mutates data
DEBUG_KEY = 4           # tracing marker
NO_OP = 5
AND = 6                 # applied with V2 (absent -> operand) semantics
OR = 7
XOR = 8
APPEND_IF_FITS = 9
AVAILABLE_FOR_REUSE = 10        # never legal in a transaction
RESERVED_LOG_PROTOCOL = 11      # LogProtocolMessage escape, server-only
MAX = 12
MIN = 13                # applied with V2 semantics
SET_VERSIONSTAMPED_KEY = 14
SET_VERSIONSTAMPED_VALUE = 15
BYTE_MIN = 16
BYTE_MAX = 17
MIN_V2 = 18             # explicit V2 code (MIN already applies V2)
AND_V2 = 19
COMPARE_AND_CLEAR = 20

ATOMIC_OPS = frozenset({ADD_VALUE, AND, OR, XOR, APPEND_IF_FITS, MAX, MIN,
                        BYTE_MIN, BYTE_MAX, MIN_V2, AND_V2,
                        COMPARE_AND_CLEAR})
# inert through the pipeline: logged and shipped but mutate nothing
# (ref: DebugKeyRange/DebugKey/NoOp in applyMutation)
INERT_OPS = frozenset({DEBUG_KEY_RANGE, DEBUG_KEY, NO_OP})

Range = Tuple[bytes, bytes]


class KeySelector(NamedTuple):
    """(ref: fdbclient/FDBTypes.h KeySelectorRef — resolves to the key
    `offset` keys past the first key `>=`/`>` the reference key)."""

    key: bytes
    or_equal: bool
    offset: int

    @classmethod
    def last_less_than(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 1)


class MutationRef(NamedTuple):
    type: int
    param1: bytes  # key / range begin
    param2: bytes  # value / range end


def mutation_bytes(m: "MutationRef") -> int:
    """Payload-size estimate for batching/spill/chunking decisions (one
    shared formula so byte limits can't silently diverge)."""
    return len(m.param1) + len(m.param2) + 16


class CommitRequest(NamedTuple):
    """One transaction's commit payload (ref: CommitTransactionRequest)."""

    read_snapshot: int
    read_conflict_ranges: Tuple[Range, ...]
    write_conflict_ranges: Tuple[Range, ...]
    mutations: Tuple[MutationRef, ...]
    # sampled-transaction stitching token (ref: debugTransaction /
    # the debugID riding CommitTransactionRequest)
    debug_id: Optional[int] = None
    # surface the conflicting key ranges on abort (ref: the
    # REPORT_CONFLICTING_KEYS transaction option,
    # fdbclient/CommitTransaction.h report_conflicting_keys flag)
    report_conflicting_keys: bool = False
    # admission priority class + client-supplied transaction tags (ref:
    # TransactionPriority and the TagSet riding
    # CommitTransactionRequest — the proxy's per-tag/priority traffic
    # accounting, and later tag throttling, keys off these)
    priority: int = 1          # PRIORITY_DEFAULT
    tags: Tuple[bytes, ...] = ()
    # transaction-repair contract (server/repair.py): the client
    # declares a covered read-set and value-independent writes, so a
    # conflicted commit may be repaired server-side — the invalidated
    # reads re-read at the conflict version and the commit revalidated
    # — instead of aborting. repair_attempt counts server-side
    # resubmissions (bounded by REPAIR_MAX_ATTEMPTS; also tells the
    # admission scheduler a resubmission already waited its turn)
    repairable: bool = False
    repair_attempt: int = 0


class CommitReply(NamedTuple):
    version: int       # the commit version
    batch_index: int   # transaction's index within the commit batch
                       # (second half of the versionstamp)


class CommitConflictReply(NamedTuple):
    """Reply to a CONFLICTED transaction that asked for
    report_conflicting_keys: the proxy answers with the attributed key
    ranges instead of a bare not_committed error, and the client raises
    not_committed itself after recording them (ref: the conflicting-keys
    special keyspace \\xff\\xff/transaction/conflicting_keys/ the
    reference exposes after a reported conflict)."""

    conflicting_ranges: Tuple[Range, ...]


class MetadataMutations(NamedTuple):
    """Committed mutations under the management system keys
    (\\xff/conf/, \\xff/excluded/), forwarded one-way by the proxy to
    the CC after the log push — the proxy-side applyMetadataMutation
    analogue (ref: fdbserver/ApplyMetadataMutation.h interpreting
    system-key mutations during commit)."""

    version: int
    mutations: tuple   # MutationRefs touching management keys


PRIORITY_BATCH = 0
PRIORITY_DEFAULT = 1
PRIORITY_IMMEDIATE = 2


class GetReadVersionRequest(NamedTuple):
    """(ref: GetReadVersionRequest — carries the number of transactions
    the (client-batched) request admits, so the ratekeeper debit is
    per-transaction, not per-RPC, and the priority class:
    BATCH is throttled first, IMMEDIATE bypasses the rate gate —
    TransactionPriority in fdbclient/FDBTypes.h)"""

    transaction_count: int = 1
    priority: int = PRIORITY_DEFAULT
    # transaction tags for the proxy's per-tag admission gate (ref: the
    # TagSet riding GetReadVersionRequest once tag throttling is on);
    # attached only while TAG_THROTTLING is armed — the request is
    # byte-identical to the pre-subsystem one otherwise
    tags: Tuple[bytes, ...] = ()


class GetReadVersionReply(NamedTuple):
    version: int
    # hot-key conflict windows piggybacked for the client-side early
    # abort (server/scheduler.py ConflictWindowCache): rows of
    # (begin, end, last_conflict_version), shipped only while
    # CLIENT_CONFLICT_WINDOWS is armed — the reply is byte-identical
    # to the pre-subsystem one otherwise
    conflict_windows: Tuple = ()
    # tag-throttle info for the requesting transaction's tags (ref:
    # GetReadVersionReply.tagThrottleInfo): rows of (tag, tps, expiry)
    # the client honors by delaying locally before its next GRV
    # (server/tag_throttler.py ClientTagThrottleCache). Shipped only
    # while TAG_THROTTLING is armed — defaulted empty otherwise, so
    # the reply stays byte-identical
    tag_throttles: Tuple = ()


class ResolveRequest(NamedTuple):
    """Ordered batch for a resolver (ref: ResolveTransactionBatchRequest,
    fdbserver/ResolverInterface.h)."""

    prev_version: int
    version: int
    transactions: Tuple[CommitRequest, ...]
    debug_ids: Tuple[int, ...] = ()


class ResolveReply(NamedTuple):
    """Resolver reply when the batch carried a report_conflicting_keys
    request: verdicts plus, per transaction, the read conflict ranges
    attributed as the conflict's cause (empty for committed/tooOld).
    Batches with no reporting request reply a bare verdict list — the
    common path stays a flat array (ref: ResolveTransactionBatchReply
    growing conflictingKeyRangeMap for this feature)."""

    verdicts: Tuple[int, ...]
    conflicting_ranges: Tuple[Tuple[Range, ...], ...]


class StorageGetRequest(NamedTuple):
    key: bytes
    version: int
    # sampled-read stitching token (ref: the debugID on GetValueRequest
    # driving the GetValueDebug trace-batch stations)
    debug_id: Optional[int] = None
    # transaction tags for the storage server's read-cost accounting
    # (ref: the TagSet on GetValueRequest feeding the per-SS
    # TransactionTagCounter); attached only while STORAGE_HEAT_TRACKING
    # is armed — the request is byte-identical to the pre-plane one
    # otherwise
    tags: Tuple[bytes, ...] = ()


class StorageGetRangeRequest(NamedTuple):
    begin: bytes
    end: bytes
    version: int
    limit: int
    reverse: bool = False
    # read-cost tags, same contract as StorageGetRequest.tags
    tags: Tuple[bytes, ...] = ()


class StorageGetKeyRequest(NamedTuple):
    selector: "KeySelector"
    version: int


class StorageWatchRequest(NamedTuple):
    """Fire when the key's value differs from its value at `version`
    (ref: storageserver watches / fdbclient watch semantics)."""

    key: bytes
    version: int


class TaggedMutation(NamedTuple):
    """A mutation routed to the storage tags that own its keys (ref:
    fdbserver/LogSystem.h LogPushData tag routing — each mutation is
    tagged per destination storage server; clears spanning shards carry
    several tags)."""

    tags: Tuple[int, ...]
    mutation: MutationRef


class TLogCommitRequest(NamedTuple):
    """(ref: TLogCommitRequest, fdbserver/TLogInterface.h — versioned
    tagged mutation payload; known_committed is the highest version the
    proxy knows is replicated on the whole log set, bounding what
    storage may safely make durable.)"""

    prev_version: int
    version: int
    mutations: Tuple[TaggedMutation, ...]
    known_committed: int = 0
    # sampled txns in the batch (ref: the debugID on TLogCommitRequest
    # driving the TLog commit-debug stations)
    debug_ids: Tuple[int, ...] = ()


class TLogPeekRequest(NamedTuple):
    """(ref: TLogPeekRequest :1138 — per-tag long poll). with_tags
    returns TaggedMutations (original tag vectors preserved) instead of
    bare mutations — the region log router needs the full vocabulary to
    re-partition the stream across the remote DC's storage tags (ref:
    LogRouter shipping per-tag streams to the remote log set)."""

    begin_version: int
    tag: int = 0
    with_tags: bool = False


class TLogPopRequest(NamedTuple):
    """Discard this tag's log entries at or below version (ref:
    TLogPopRequest, fdbserver/TLogInterface.h — sent by each replica
    once durable; the tag's effective pop is the MIN across its
    replicas so a lagging replica never loses unpulled data)."""

    version: int
    tag: int = 0
    replica: str = ""


class TLogPeekReply(NamedTuple):
    entries: Tuple[Tuple[int, Tuple[MutationRef, ...]], ...]
    committed_version: int
    known_committed: int = 0


class TLogLockRequest(NamedTuple):
    """Stop the log and report how far it got (ref: TLogLockResult /
    epochEnd locking, TagPartitionedLogSystem.actor.cpp:1265 — a locked
    tlog accepts no further commits but keeps serving peeks so storage
    servers can finish pulling the old generation)."""


class ResolutionMetricsReply(NamedTuple):
    """(ref: ResolutionMetricsRequest — cumulative work + key-space
    sample so the master can pick split points)"""

    work_units: int
    key_hist: Tuple[int, ...]   # 256 first-byte buckets


# -- resolver split/merge handoff (ISSUE 15) ----------------------------
# The balance loop's state-handoff RPCs: checkpoint-and-clip on the
# donor, graft-install on the recipient (models/conflict_set.py
# clip_checkpoint / graft_checkpoint). Both are served by the resolver
# role's `splits` endpoint.


class ResolverCheckpointRequest(NamedTuple):
    """Donor side: checkpoint the conflict-set state and return the
    [begin, end) slice as a ConflictRangePiece. `min_version` gates the
    checkpoint on the resolver's version chain — the donor first
    resolves every batch below the move's effective version, so the
    piece provably covers all pre-move writes in the span."""

    begin: bytes
    end: Optional[bytes]     # None = keyspace tail
    min_version: int = 0


class ResolverCheckpointReply(NamedTuple):
    piece: tuple             # ConflictRangePiece (wire-registered)
    version: int             # donor's version when the piece was cut


class ResolverInstallRequest(NamedTuple):
    """Recipient side: graft the piece into the live conflict-set state
    (pointwise max over the span — exact whatever post-move writes the
    recipient already recorded). Replies the recipient's version."""

    begin: bytes
    end: Optional[bytes]
    piece: tuple             # ConflictRangePiece


class TLogLockReply(NamedTuple):
    end_version: int        # highest durable version in this log
    known_committed: int    # highest version known replicated log-set-wide


class QosSample(NamedTuple):
    """One role's saturation-signal snapshot for the QoS telemetry
    plane (ref: the StorageQueuingMetricsReply / TLogQueuingMetricsReply
    the reference Ratekeeper polls — smoothed queue bytes, durability
    lag, input rates). `signals` maps signal name -> smoothed value;
    the signal inventory per role kind is pinned by
    tests/test_qos_telemetry.py and documented in README's QoS
    telemetry section."""

    kind: str          # storage | tlog | proxy | resolver
    name: str          # role instance name
    sampled_at: float  # sim time of this sample
    signals: dict      # signal name -> value (floats/ints)

# -- typed bare-payload envelopes (ISSUE 12) ----------------------------
# Every request that used to ship a bare ``None`` payload (ratekeeper
# rate polls, failure-monitor pings, raw-committed/durable-frontier
# probes, resolution-metrics polls, status fetches) gets a field-less
# typed envelope instead: the sim network's per-type message accounting
# then attributes them (no more anonymous `NoneType` rows — enforced by
# an armed-mode assert in SimNetwork._count_msg), and the wire layer
# serves field-less messages from a per-type round-trip cache, so the
# typed envelope is CHEAPER than the None it replaces. Send the module
# singletons below; receivers that dispatch match on the type.


class GetRateRequest(NamedTuple):
    """Proxy -> ratekeeper GetRateInfo poll (ref: GetRateInfoRequest)."""


class PingRequest(NamedTuple):
    """CC failure monitor -> worker liveness ping."""


class RawCommittedRequest(NamedTuple):
    """Proxy -> peer proxy raw committed-version probe (GRV causal
    confirmation, ref: getLiveCommittedVersion)."""


class DurableFrontierRequest(NamedTuple):
    """Proxy -> TLog durable-frontier probe (degraded-GRV fallback)."""


class ResolutionMetricsRequest(NamedTuple):
    """Master -> resolver work/key-histogram poll (ref:
    ResolutionMetricsRequest)."""


class StatusRequest(NamedTuple):
    """Client -> CC status-document fetch (ref: StatusRequest)."""


# -- storage heat plane (ISSUE 13) --------------------------------------
# Field-less probes served by the storage role's metrics endpoint —
# module singletons per the PR 12 envelope convention (typed, so the
# sim network's message accounting attributes them and the wire layer
# round-trip cache applies).


class StorageMetricsRequest(NamedTuple):
    """-> StorageMetricsReply: the shard's sampled bytes + smoothed
    read/write bandwidth + busiest read tag (ref: GetStorageMetrics /
    StorageQueuingMetrics read-side fields)."""


class ReadHotRangesRequest(NamedTuple):
    """-> ReadHotRangesReply: read-hot sub-ranges of the owned shard
    (ref: ReadHotSubRangeRequest density math)."""


class SplitMetricsRequest(NamedTuple):
    """-> SplitMetricsReply: the byte-balanced interior split key
    (ref: SplitMetricsRequest / splitMetrics)."""


class StorageMetricsReply(NamedTuple):
    sampled_bytes: int
    write_bytes_per_sec: float
    read_bytes_per_sec: float
    read_ops_per_sec: float
    busiest_read_tag: Optional[bytes]
    busiest_read_tag_rate: float


class ReadHotRangesReply(NamedTuple):
    """Rows of (begin, end, density_ratio, read_bytes_per_sec) — the
    sub-ranges whose read-bandwidth ÷ sampled-byte density exceeds
    READ_HOT_RANGE_RATIO × the shard's own density."""

    ranges: Tuple = ()


class SplitMetricsReply(NamedTuple):
    split_key: Optional[bytes]


GET_RATE_REQUEST = GetRateRequest()
STORAGE_METRICS_REQUEST = StorageMetricsRequest()
READ_HOT_RANGES_REQUEST = ReadHotRangesRequest()
SPLIT_METRICS_REQUEST = SplitMetricsRequest()
PING_REQUEST = PingRequest()
RAW_COMMITTED_REQUEST = RawCommittedRequest()
DURABLE_FRONTIER_REQUEST = DurableFrontierRequest()
RESOLUTION_METRICS_REQUEST = ResolutionMetricsRequest()
STATUS_REQUEST = StatusRequest()

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
