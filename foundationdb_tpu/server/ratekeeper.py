"""Ratekeeper: cluster-wide transaction admission control.

Reference: fdbserver/Ratekeeper.actor.cpp — a controller computes the
cluster's transactions-per-second budget from storage queue depths /
durability lag and TLog queue depth (updateRate, :150-635); proxies
fetch the rate periodically (GetRateInfoRequest served to proxies,
MasterProxyServer.actor.cpp:79) and release batched GRV requests no
faster than their share of it (transactionStarter :1102).

The controller here is the proportional core of the reference's: full
speed while the worst storage lag is inside the target window, scaling
down linearly to a survival trickle as lag approaches the MVCC window
size (beyond which reads start failing with transaction_too_old), and
a trickle while any shard is dead or a TLog's unpopped backlog grows
past its threshold. Stats are read from the role registry directly —
the simulated stand-in for StorageQueuingMetricsRequest /
TLogQueuingMetricsRequest polling.
"""

from __future__ import annotations

from typing import NamedTuple

from .. import flow
from ..flow import SERVER_KNOBS, TaskPriority
from ..rpc import RequestStream, SimProcess

# rate bounds + backlog threshold live in the RK_* knobs (ref:
# Ratekeeper.actor.cpp limit computation)


class GetRateReply(NamedTuple):
    tps: float


class Ratekeeper:
    def __init__(self, process: SimProcess, cc):
        self.process = process
        self.cc = cc
        self.rate = flow.SERVER_KNOBS.rk_max_rate
        self.get_rate = RequestStream(process)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        for coro, name in ((self._update_loop(), "update"),
                           (self._serve_loop(), "getRate")):
            self._actors.add(flow.spawn(coro, TaskPriority.RATEKEEPER,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()
        self.get_rate.close()

    async def _serve_loop(self):
        while True:
            _req, reply = await self.get_rate.pop()
            reply.send(GetRateReply(self.rate))

    async def _update_loop(self):
        while True:
            await flow.delay(flow.SERVER_KNOBS.rk_update_interval,
                             TaskPriority.RATEKEEPER)
            self.rate = self._compute_rate()

    def _compute_rate(self) -> float:
        info = self.cc.dbinfo.get()
        window = SERVER_KNOBS.max_write_transaction_life_versions
        # a storage holds durability AT its configured lag by design;
        # only lag IN EXCESS of that intent signals distress (the first
        # controller compared raw lag against a window equal to the
        # intent, throttling healthy clusters — code review r3)
        worst_excess = 0
        for s in info.storages:
          for rep in s.replicas:
            obj = self.cc._storage_objs.get(rep.name)
            if obj is None or not obj.process.alive:
                # a dead replica: lag is unbounded until it rejoins
                return flow.SERVER_KNOBS.rk_min_rate
            if obj.kv is None:
                continue  # no engine: the durability loop is inert and
                # lag is meaningless (defensive; cluster-recruited
                # storages always have at least an ephemeral engine)
            excess = (obj.version.get() - obj.durable_version.get()
                      - obj._lag)
            worst_excess = max(worst_excess, excess)
        backlog = max((len(t.entries) for t in self.cc.tlog_objs()),
                      default=0)
        if backlog > flow.SERVER_KNOBS.rk_tlog_backlog_limit:
            return flow.SERVER_KNOBS.rk_min_rate
        target = window // 5    # distress threshold for excess lag
        if worst_excess <= target:
            return flow.SERVER_KNOBS.rk_max_rate
        if worst_excess >= window:
            return flow.SERVER_KNOBS.rk_min_rate
        frac = 1.0 - (worst_excess - target) / max(1, window - target)
        return max(flow.SERVER_KNOBS.rk_min_rate, flow.SERVER_KNOBS.rk_max_rate * frac * frac)

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
