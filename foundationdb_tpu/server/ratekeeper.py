"""Ratekeeper: cluster-wide transaction admission control.

Reference: fdbserver/Ratekeeper.actor.cpp — a controller computes the
cluster's transactions-per-second budget from SMOOTHED per-storage
queue bytes, TLog queue bytes, and durability lag (updateRate,
:176-635), with a SEPARATE, lower limit for batch-priority traffic so
background work throttles before interactive work; proxies fetch both
rates periodically (GetRateInfoRequest, MasterProxyServer.actor.cpp:79)
and release batched GRV requests no faster than their share
(transactionStarter :1102).

Per-input controller (the reference's spring-zone shape): each storage
replica's MVCC-window bytes and each TLog's unpopped memory bytes are
exponentially smoothed (ref: fdbrpc/Smoother.h) and mapped through a
spring zone — full speed below (target - spring), linear decay inside
the zone, the survival trickle above target. Durability lag in excess
of the configured intent scales the result quadratically toward the
trickle as it approaches the MVCC window (beyond which reads fail with
transaction_too_old). Batch limits use a fraction of the targets, so
batch admission collapses first. A dead replica pins everything to the
trickle until it rejoins.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

from .. import flow
from ..flow import TaskPriority
# promoted to flow/smoother.py (with the non-increasing-clock clamp);
# re-exported here because the Smoother is historically this module's
# vocabulary and importers reach for it here
from ..flow.smoother import SmoothedRate, Smoother  # noqa: F401
from ..rpc import RequestStream, SimProcess
from .types import mutation_bytes

# limiting_reason vocabulary (ref: limitReason_t in Ratekeeper.actor.cpp
# — the reason string RkUpdate publishes beside the computed rate).
# Pinned by tests/test_qos_telemetry.py and the status.cluster.qos schema.
LIMIT_REASONS = ("none", "storage_queue", "tlog_queue", "durability_lag",
                 "pipeline_occupancy", "conflict_deferrals")


def _camel(s: str) -> str:
    """snake_case signal name -> the CamelCase TraceEvent detail key
    (RkUpdate fields read like the reference's)."""
    return "".join(p.capitalize() for p in s.split("_"))


class GetRateReply(NamedTuple):
    tps: float
    batch_tps: float = -1.0   # -1: pre-batch-limit peer (defaults to tps)


class Ratekeeper:
    def __init__(self, process: SimProcess, cc):
        self.process = process
        self.cc = cc
        self.rate = flow.SERVER_KNOBS.rk_max_rate
        self.batch_rate = flow.SERVER_KNOBS.rk_max_rate
        self.get_rate = RequestStream(process)
        self._storage_smooth: Dict[str, Smoother] = {}
        self._tlog_smooth: Dict[str, Smoother] = {}
        # resolve-pipeline forced-drain rate per resolver (PR 4's
        # backpressure counters as a throttle input)
        self._pipeline_smooth: Dict[str, SmoothedRate] = {}
        # admission-scheduler deferred-commit depth per proxy (the
        # conflict-prediction plane's pressure as a throttle input)
        self._sched_smooth: Dict[str, Smoother] = {}
        # the last decision with its input signals and limiting reason
        # — what RkUpdate traces and status.cluster.qos publish
        self.last_decision: dict = {}
        # the storage heat plane's observe-only inputs (ISSUE 13): the
        # hex tag behind busiest_read_tag_busyness, traced beside the
        # numeric inputs (enforcement stays ROADMAP item 3's follow-up)
        self._busiest_read_tag = ""
        # tag auto-throttler (server/tag_throttler.py, ROADMAP item 3):
        # busy tags per the proxies' TransactionTagCounter get throttle
        # rows written into \xff\x02/throttledTags/; idle (one knob
        # read per interval) while AUTO_TAG_THROTTLING is off
        from .tag_throttler import TagThrottler
        self.throttler = TagThrottler(process, cc)
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        for coro, name in ((self._update_loop(), "update"),
                           (self._serve_loop(), "getRate"),
                           (self.throttler.run(), "tagThrottler")):
            self._actors.add(flow.spawn(coro, TaskPriority.RATEKEEPER,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()
        self.get_rate.close()

    def _served_rates(self):
        """What one polling proxy may admit. With enforced admission
        armed, the cluster budget is SPLIT across the current epoch's
        proxies (ref: GetRateInfoReply's transactionRate divided by
        proxy count in Ratekeeper.actor.cpp) — without the split, N
        proxies would each enforce the full budget and the cluster
        would admit N× what the controller computed. Off-posture
        serves the undivided rate, exactly as before."""
        tps, batch_tps = self.rate, self.batch_rate
        if flow.SERVER_KNOBS.grv_admission_control:
            n = max(1, len(self.cc.dbinfo.get().proxies))
            tps = tps / n
            if batch_tps >= 0:
                batch_tps = batch_tps / n
        return tps, batch_tps

    async def _serve_loop(self):
        while True:
            _req, reply = await self.get_rate.pop()
            reply.send(GetRateReply(*self._served_rates()))

    async def _update_loop(self):
        while True:
            await flow.delay(flow.SERVER_KNOBS.rk_update_interval,
                             TaskPriority.RATEKEEPER)
            self.rate, self.batch_rate = self._compute_rates()
            d = self.last_decision
            if d:
                # decision trace every interval (ref: the RkUpdate
                # TraceEvent updateRate emits: the computed rate, every
                # input signal, and WHY the limit is what it is)
                flow.TraceEvent("RkUpdate", self.process.name).detail(
                    TPSLimit=round(d["tps"], 1),
                    BatchTPSLimit=round(d["batch_tps"], 1),
                    LimitingReason=d["limiting_reason"],
                    BusiestReadTag=d.get("busiest_read_tag", ""),
                    **{_camel(kk): vv
                       for kk, vv in d["inputs"].items()}).log()

    @staticmethod
    def _spring_limit(queue: float, target: float, spring: float,
                      max_rate: float, min_rate: float) -> float:
        """Full speed below (target - spring); linear decay through the
        spring zone; the trickle at/above target (ref: the
        storage/tlog limit shape in updateRate)."""
        head = target - queue
        if head >= spring:
            return max_rate
        if head <= 0:
            return min_rate
        return max(min_rate, max_rate * head / spring)

    def _compute_rates(self):
        k = flow.SERVER_KNOBS
        info = self.cc.dbinfo.get()
        now = flow.now()
        window = k.max_write_transaction_life_versions
        min_rate, max_rate = k.rk_min_rate, k.rk_max_rate
        batch_frac = k.rk_batch_target_fraction
        tau = k.rk_smoothing_seconds
        limit, batch_limit = max_rate, max_rate
        # every input signal the decision saw, for RkUpdate + status
        inputs = {"worst_storage_queue_bytes": 0.0,
                  "worst_tlog_queue_bytes": 0.0,
                  "worst_durability_lag_versions": 0,
                  "pipeline_occupancy": 0.0,
                  "pipeline_forced_drain_rate": 0.0,
                  "sched_deferred_depth": 0.0,
                  # storage heat plane (ISSUE 13), observe-only: the
                  # worst read-hot density ratio and busiest per-SS
                  # read-tag busyness — zeros while the plane is off
                  "worst_read_hot": 0.0,
                  "busiest_read_tag_busyness": 0.0,
                  "dead_replicas": 0}
        self._busiest_read_tag = ""
        reason = "none"
        # the batch bucket has its own binding constraint (its spring
        # zone starts at target*batch_frac, well before the normal
        # one) — track its reason separately so a batch-only throttle
        # is never reported as "none"
        batch_reason = "none"

        def lower(new_limit, new_batch, why):
            nonlocal limit, batch_limit, reason, batch_reason
            if new_limit < limit:
                limit, reason = new_limit, why
            if new_batch < batch_limit:
                batch_limit, batch_reason = new_batch, why

        worst_excess = 0
        # one pass per REPLICA, not per (shard x replica): a server
        # holding many shards appears once (dedupe by name), and the
        # smoother dicts are pruned to the names seen this tick so
        # recoveries/moves cannot grow them without bound
        replicas = {rep.name for s in info.storages for rep in s.replicas}
        for name in sorted(replicas):
            obj = self.cc._storage_objs.get(name)
            if obj is None or not obj.process.alive:
                # a dead replica: lag is unbounded until it rejoins
                inputs["dead_replicas"] += 1
                inputs["worst_durability_lag_versions"] = window
                return self._decide(min_rate, min_rate,
                                    "durability_lag", inputs, now)
            if obj.kv is None:
                continue  # no engine: durability is inert (defensive)
            excess = (obj.version.get() - obj.durable_version.get()
                      - obj._lag)
            worst_excess = max(worst_excess, excess)
            # MVCC-window bytes not yet durable (ref: the smoothed
            # storage queue bytes in StorageQueuingMetrics)
            qbytes = sum(mutation_bytes(m)
                         for _v, ms in obj._pending for m in ms)
            sm = self._storage_smooth.get(name)
            if sm is None:
                sm = self._storage_smooth[name] = Smoother()
            q = sm.sample(qbytes, now, tau)
            inputs["worst_storage_queue_bytes"] = max(
                inputs["worst_storage_queue_bytes"], round(q, 1))
            t = k.rk_target_storage_queue_bytes
            sp = k.rk_spring_storage_queue_bytes
            lower(self._spring_limit(q, t, sp, max_rate, min_rate),
                  self._spring_limit(q, t * batch_frac, sp, max_rate,
                                     min_rate),
                  "storage_queue")
        for stale in set(self._storage_smooth) - replicas:
            del self._storage_smooth[stale]

        # storage heat inputs (ISSUE 13): observe-only — they ride
        # RkUpdate and status so an operator (and item 3's follow-up
        # enforcement) can SEE which sub-range and tag is hot before
        # any throttle acts on it; never an input to lower(). Read
        # from the CC's rollup (refreshed each QOS_SAMPLE_INTERVAL by
        # _roll_storage_heat) rather than rescanning every replica's
        # sample per ratekeeper tick — the update loop runs ~10x the
        # sampler cadence and must not multiply the scan cost
        if k.storage_heat_tracking:
            heat = getattr(self.cc, "storage_heat", None)
            if heat is not None:
                for row in heat.top():
                    inputs["worst_read_hot"] = max(
                        inputs["worst_read_hot"], row["density"])
            for _srv, (tag_hex, busy) in sorted(
                    getattr(self.cc, "_heat_tags", {}).items()):
                if busy > inputs["busiest_read_tag_busyness"]:
                    inputs["busiest_read_tag_busyness"] = round(busy, 2)
                    self._busiest_read_tag = tag_hex

        live_logs = set()
        for t_obj in self.cc.tlog_objs():
            live_logs.add(t_obj.name)
            sm = self._tlog_smooth.get(t_obj.name)
            if sm is None:
                sm = self._tlog_smooth[t_obj.name] = Smoother()
            q = sm.sample(t_obj.mem_bytes, now, tau)
            inputs["worst_tlog_queue_bytes"] = max(
                inputs["worst_tlog_queue_bytes"], round(q, 1))
            tt = k.rk_target_tlog_queue_bytes
            sp = k.rk_spring_tlog_queue_bytes
            lower(self._spring_limit(q, tt, sp, max_rate, min_rate),
                  self._spring_limit(q, tt * batch_frac, sp, max_rate,
                                     min_rate),
                  "tlog_queue")
            if len(t_obj.entries) > k.rk_tlog_backlog_limit:
                return self._decide(min_rate, min_rate, "tlog_queue",
                                    inputs, now)
        for stale in set(self._tlog_smooth) - live_logs:
            del self._tlog_smooth[stale]

        # resolve-pipeline backpressure (PR 4's forced-drain counters):
        # a sustained forced-drain rate means submits outrun the device
        # drain — the same spring-zone shape as the queue-byte inputs
        fd_target = k.rk_pipeline_forced_drain_limit
        if fd_target > 0:
            live_res = set()
            for rn, role in self._resolver_roles(info):
                live_res.add(rn)
                pipe = role.pipeline_stats()
                sm = self._pipeline_smooth.get(rn)
                if sm is None:
                    sm = self._pipeline_smooth[rn] = SmoothedRate()
                # tau per sample, like the storage/tlog smoothers — a
                # construction-time tau would freeze the knob
                fd_rate = sm.sample_total(pipe.get("forced_drains", 0),
                                          now, tau)
                inputs["pipeline_forced_drain_rate"] = max(
                    inputs["pipeline_forced_drain_rate"],
                    round(fd_rate, 2))
                inputs["pipeline_occupancy"] = max(
                    inputs["pipeline_occupancy"],
                    pipe.get("occupancy") or 0.0)
                sp = k.rk_pipeline_forced_drain_spring
                lower(self._spring_limit(fd_rate, fd_target, sp,
                                         max_rate, min_rate),
                      self._spring_limit(fd_rate, fd_target * batch_frac,
                                         sp, max_rate, min_rate),
                      "pipeline_occupancy")
            for stale in set(self._pipeline_smooth) - live_res:
                del self._pipeline_smooth[stale]

        # admission-scheduler deferral pressure (ISSUE 8): a deep
        # deferred-commit queue means admission is outrunning what the
        # hot ranges can serialize — throttle at the GRV gate BEFORE
        # the per-range queues overflow into racing aborts (same
        # spring-zone shape as the queue-byte inputs; 0 disables)
        sd_target = k.rk_sched_defer_limit
        if sd_target > 0:
            live_px = set()
            for pn, role in self._proxy_roles(info):
                live_px.add(pn)
                sm = self._sched_smooth.get(pn)
                if sm is None:
                    sm = self._sched_smooth[pn] = Smoother()
                q = sm.sample(role.scheduler.queue_depth(), now, tau)
                inputs["sched_deferred_depth"] = max(
                    inputs["sched_deferred_depth"], round(q, 2))
                sp = k.rk_sched_defer_spring
                lower(self._spring_limit(q, sd_target, sp,
                                         max_rate, min_rate),
                      self._spring_limit(q, sd_target * batch_frac, sp,
                                         max_rate, min_rate),
                      "conflict_deferrals")
            for stale in set(self._sched_smooth) - live_px:
                del self._sched_smooth[stale]

        # durability-lag excess scales everything quadratically toward
        # the trickle as it approaches the MVCC window
        inputs["worst_durability_lag_versions"] = max(0, worst_excess)
        target = window // 5    # distress threshold for excess lag
        if worst_excess >= window:
            return self._decide(min_rate, min_rate, "durability_lag",
                                inputs, now)
        if worst_excess > target:
            frac = 1.0 - (worst_excess - target) / max(1, window - target)
            lower(max(min_rate, max_rate * frac * frac), limit,
                  "durability_lag")
            if limit < batch_limit:
                # batch now binds on whatever binds the normal bucket
                batch_limit, batch_reason = limit, reason
        if limit >= max_rate:
            # normal-priority unthrottled — but the batch bucket may
            # still be engaged; report ITS reason rather than claiming
            # the cluster is unlimited while batch traffic is shed
            reason = batch_reason if batch_limit < max_rate else "none"
        return self._decide(limit, min(batch_limit, limit), reason,
                            inputs, now)

    def _epoch_roles(self, info, cls):
        """Live current-epoch roles of `cls` from the CC's registry —
        the shared cluster_controller.epoch_roles walk (lazy import:
        no module cycle, and fake-CC unit tests still only need a
        `workers` dict)."""
        from .cluster_controller import epoch_roles
        return epoch_roles(self.cc.workers, info.epoch, cls)

    def _resolver_roles(self, info):
        from .resolver_role import Resolver
        return self._epoch_roles(info, Resolver)

    def _proxy_roles(self, info):
        """The deferral-pressure input's source."""
        from .proxy import Proxy
        return self._epoch_roles(info, Proxy)

    def _decide(self, tps, batch_tps, reason, inputs, now):
        """Record the decision (rate + batch rate + limiting reason +
        every input signal) for RkUpdate tracing and status.cluster.qos,
        then return the (tps, batch_tps) pair the update loop expects."""
        self.last_decision = {
            "tps": tps, "batch_tps": batch_tps,
            "limiting_reason": reason, "inputs": inputs,
            "busiest_read_tag": self._busiest_read_tag,
            "computed_at": now}
        return tps, batch_tps


from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
