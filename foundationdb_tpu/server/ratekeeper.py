"""Ratekeeper: cluster-wide transaction admission control.

Reference: fdbserver/Ratekeeper.actor.cpp — a controller computes the
cluster's transactions-per-second budget from SMOOTHED per-storage
queue bytes, TLog queue bytes, and durability lag (updateRate,
:176-635), with a SEPARATE, lower limit for batch-priority traffic so
background work throttles before interactive work; proxies fetch both
rates periodically (GetRateInfoRequest, MasterProxyServer.actor.cpp:79)
and release batched GRV requests no faster than their share
(transactionStarter :1102).

Per-input controller (the reference's spring-zone shape): each storage
replica's MVCC-window bytes and each TLog's unpopped memory bytes are
exponentially smoothed (ref: fdbrpc/Smoother.h) and mapped through a
spring zone — full speed below (target - spring), linear decay inside
the zone, the survival trickle above target. Durability lag in excess
of the configured intent scales the result quadratically toward the
trickle as it approaches the MVCC window (beyond which reads fail with
transaction_too_old). Batch limits use a fraction of the targets, so
batch admission collapses first. A dead replica pins everything to the
trickle until it rejoins.
"""

from __future__ import annotations

import math
from typing import Dict, NamedTuple

from .. import flow
from ..flow import TaskPriority
from ..rpc import RequestStream, SimProcess
from .types import mutation_bytes


class Smoother:
    """Exponential smoothing toward the newest sample with time
    constant `tau` seconds (ref: fdbrpc/Smoother.h)."""

    __slots__ = ("_t", "value")

    def __init__(self):
        self._t = None
        self.value = 0.0

    def sample(self, x: float, now: float, tau: float) -> float:
        # tau comes in per sample so a live knob change applies to
        # existing smoothers (a frozen tau would make the knob a no-op)
        if self._t is None or tau <= 0:
            self.value = x
        else:
            a = math.exp(-(now - self._t) / tau)
            self.value = x + (self.value - x) * a
        self._t = now
        return self.value


class GetRateReply(NamedTuple):
    tps: float
    batch_tps: float = -1.0   # -1: pre-batch-limit peer (defaults to tps)


class Ratekeeper:
    def __init__(self, process: SimProcess, cc):
        self.process = process
        self.cc = cc
        self.rate = flow.SERVER_KNOBS.rk_max_rate
        self.batch_rate = flow.SERVER_KNOBS.rk_max_rate
        self.get_rate = RequestStream(process)
        self._storage_smooth: Dict[str, Smoother] = {}
        self._tlog_smooth: Dict[str, Smoother] = {}
        self._actors = flow.ActorCollection()

    def start(self) -> None:
        for coro, name in ((self._update_loop(), "update"),
                           (self._serve_loop(), "getRate")):
            self._actors.add(flow.spawn(coro, TaskPriority.RATEKEEPER,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    def stop(self) -> None:
        self._actors.cancel_all()
        self.get_rate.close()

    async def _serve_loop(self):
        while True:
            _req, reply = await self.get_rate.pop()
            reply.send(GetRateReply(self.rate, self.batch_rate))

    async def _update_loop(self):
        while True:
            await flow.delay(flow.SERVER_KNOBS.rk_update_interval,
                             TaskPriority.RATEKEEPER)
            self.rate, self.batch_rate = self._compute_rates()

    @staticmethod
    def _spring_limit(queue: float, target: float, spring: float,
                      max_rate: float, min_rate: float) -> float:
        """Full speed below (target - spring); linear decay through the
        spring zone; the trickle at/above target (ref: the
        storage/tlog limit shape in updateRate)."""
        head = target - queue
        if head >= spring:
            return max_rate
        if head <= 0:
            return min_rate
        return max(min_rate, max_rate * head / spring)

    def _compute_rates(self):
        k = flow.SERVER_KNOBS
        info = self.cc.dbinfo.get()
        now = flow.now()
        window = k.max_write_transaction_life_versions
        min_rate, max_rate = k.rk_min_rate, k.rk_max_rate
        batch_frac = k.rk_batch_target_fraction
        tau = k.rk_smoothing_seconds
        limit, batch_limit = max_rate, max_rate

        worst_excess = 0
        # one pass per REPLICA, not per (shard x replica): a server
        # holding many shards appears once (dedupe by name), and the
        # smoother dicts are pruned to the names seen this tick so
        # recoveries/moves cannot grow them without bound
        replicas = {rep.name for s in info.storages for rep in s.replicas}
        for name in sorted(replicas):
            obj = self.cc._storage_objs.get(name)
            if obj is None or not obj.process.alive:
                # a dead replica: lag is unbounded until it rejoins
                return min_rate, min_rate
            if obj.kv is None:
                continue  # no engine: durability is inert (defensive)
            excess = (obj.version.get() - obj.durable_version.get()
                      - obj._lag)
            worst_excess = max(worst_excess, excess)
            # MVCC-window bytes not yet durable (ref: the smoothed
            # storage queue bytes in StorageQueuingMetrics)
            qbytes = sum(mutation_bytes(m)
                         for _v, ms in obj._pending for m in ms)
            sm = self._storage_smooth.get(name)
            if sm is None:
                sm = self._storage_smooth[name] = Smoother()
            q = sm.sample(qbytes, now, tau)
            t = k.rk_target_storage_queue_bytes
            sp = k.rk_spring_storage_queue_bytes
            limit = min(limit, self._spring_limit(
                q, t, sp, max_rate, min_rate))
            batch_limit = min(batch_limit, self._spring_limit(
                q, t * batch_frac, sp, max_rate, min_rate))
        for stale in set(self._storage_smooth) - replicas:
            del self._storage_smooth[stale]

        live_logs = set()
        for t_obj in self.cc.tlog_objs():
            live_logs.add(t_obj.name)
            sm = self._tlog_smooth.get(t_obj.name)
            if sm is None:
                sm = self._tlog_smooth[t_obj.name] = Smoother()
            q = sm.sample(t_obj.mem_bytes, now, tau)
            tt = k.rk_target_tlog_queue_bytes
            sp = k.rk_spring_tlog_queue_bytes
            limit = min(limit, self._spring_limit(
                q, tt, sp, max_rate, min_rate))
            batch_limit = min(batch_limit, self._spring_limit(
                q, tt * batch_frac, sp, max_rate, min_rate))
            if len(t_obj.entries) > k.rk_tlog_backlog_limit:
                return min_rate, min_rate
        for stale in set(self._tlog_smooth) - live_logs:
            del self._tlog_smooth[stale]

        # durability-lag excess scales everything quadratically toward
        # the trickle as it approaches the MVCC window
        target = window // 5    # distress threshold for excess lag
        if worst_excess >= window:
            return min_rate, min_rate
        if worst_excess > target:
            frac = 1.0 - (worst_excess - target) / max(1, window - target)
            limit = min(limit, max(min_rate, max_rate * frac * frac))
            batch_limit = min(batch_limit, limit)
        return limit, min(batch_limit, limit)


from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
