"""The cluster chaos plane: named, seeded, replayable scenario storms.

Reference: fdbrpc/sim2.actor.cpp (swizzling, link clogging, machine
reboots, connection failures) and the simulation workload stacking in
fdbserver/workloads/ (MachineAttrition, RandomClogging, DiskFailure) —
the part of the reference's robustness story PR 5's device-fault seams
did not cover: tearing the WHOLE CLUSTER apart mid-commit and requiring
it to heal.

Three layers live here:

- **Station hooks**: the commit-debug stations in the proxy/tlog double
  as chaos kill points. `arm_station(location, fn)` installs a one-shot
  callback fired synchronously when the pipeline reaches that station,
  so a scenario can kill a role at an EXACT commit station (GRV handed
  out, commit version assigned, resolve answered, fsync pending, log
  push acked) instead of "roughly around a commit".
- **Format-aware corruption helpers**: `corrupt_record_payload` flips
  payload bytes of a committed DiskQueue record (header + CRC intact
  chain ⇒ DETECTED at recovery as checksum_failed ⇒ recoverable role
  death); `corrupt_value_bytes` flips bytes AND fixes the record CRC —
  corruption the disk format cannot see, which exists precisely so
  tests can prove check_consistency catches it.
- **Scenarios**: named `ChaosScenario`s (`SCENARIOS`) that a
  `ChaosStorm` workload (server/workloads.py) applies mid-flight under
  open-loop traffic, then heals and verifies. Every random choice draws
  from the seeded sim RNG and every injected fault lands in the
  network's `chaos_log`, so one seed replays one identical storm — the
  determinism tests pin `chaos_log` + the post-quiesce consistency
  digest across runs.

`chaos_status(net)` is the shared accounting schema
(status.cluster.chaos): network/disk/kill counters merged with the
device-fault injector's seam totals (ops/fault_injection.py), so
"did the storm actually fire, and what did it inject" is a status
query per fault kind — no trace grepping.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .. import flow

# -- station hooks -------------------------------------------------------

#: location -> list of one-shot callbacks (process-global, like the
#: knobs; SimCluster clears it when a new simulation starts)
_stations: Dict[str, List[Callable[[str], None]]] = {}

#: commit-pipeline stations a scenario can arm (the proxy/tlog fire
#: these via fire_station on every batch)
COMMIT_STATIONS = (
    "MasterProxyServer.GRV.AfterReply",
    "MasterProxyServer.commitBatch.Before",
    "MasterProxyServer.commitBatch.GotCommitVersion",
    "MasterProxyServer.commitBatch.AfterResolution",
    "TLog.tLogCommit.AfterWaitForVersion",
    "TLog.tLogCommit.AfterTLogCommit",
    "MasterProxyServer.commitBatch.AfterLogPush",
)


def arm_station(location: str, fn: Callable[[str], None]) -> None:
    """Install a ONE-SHOT callback at a commit-pipeline station; it
    fires synchronously inside the role actor that reaches the station
    (so a kill lands at exactly that point of the batch)."""
    _stations.setdefault(location, []).append(fn)


def clear_stations() -> None:
    _stations.clear()


def fire_station(location: str) -> None:
    """Called by the pipeline roles at their stations. Free while
    nothing is armed (one dict check on an empty dict)."""
    if not _stations:
        return
    hooks = _stations.get(location)
    if not hooks:
        return
    fn = hooks.pop(0)
    if not hooks:
        del _stations[location]
    fn(location)


# -- shared chaos accounting schema --------------------------------------

def chaos_status(net) -> dict:
    """The status.cluster.chaos document: one schema over every fault
    source — network ops, kills, disk corruption (SimNetwork
    chaos_counters) AND the device-fault injector's per-seam totals."""
    from ..ops.fault_injection import g_device_faults
    injected = dict(getattr(net, "chaos_counters", ()) or {})
    for point, n in g_device_faults.injected.items():
        if n:
            injected[f"device_{point}"] = n
    return {
        "injected": injected,
        "events": (len(getattr(net, "chaos_log", ()))
                   + getattr(net, "chaos_log_dropped", 0)),
        "messages_dropped": getattr(net, "messages_dropped", 0),
        "messages_duplicated": getattr(net, "messages_duplicated", 0),
        "scenarios": dict(getattr(net, "chaos_scenarios", ()) or {}),
    }


def record_scenario(net, name: str) -> None:
    net.chaos_scenarios[name] = net.chaos_scenarios.get(name, 0) + 1
    net.chaos_note("scenario", name=name)


# -- format-aware disk corruption ----------------------------------------

def _parse_dq_records(raw):
    """Committed records of a DiskQueue file image, via the ONE shared
    format walker (diskqueue.walk_records — the corruption helpers and
    recovery's scan must never disagree on what a record is):
    [(seq, payload_off, length, record_off)] — the walker's materialized
    payload is dropped here; the helpers only patch bytes in place."""
    from .diskqueue import walk_records
    return [(seq, poff, length, off)
            for seq, _payload, poff, length, off in walk_records(raw)[0]]


def corrupt_record_payload(simfile, rng) -> bool:
    """DETECTABLE corruption: flip payload bytes of a committed record
    that has a valid successor (header + CRC chain left intact), so the
    next recovery's checksum scan reports checksum_failed instead of
    quietly shortening the log. Returns False if the file holds fewer
    than two committed records (nothing to confirm the hole against)."""
    recs = _parse_dq_records(simfile._durable)
    recs = [r for r in recs[:-1] if r[2] > 0]   # need a valid successor
    if not recs:
        return False
    _seq, poff, length, _off = recs[rng.random_int(0, len(recs))]
    flip = poff + rng.random_int(0, length)
    simfile._durable[flip] ^= rng.random_int(1, 256)
    if simfile.disk.net is not None:
        simfile.disk.net.chaos_note(
            "disk_corruption", file=simfile.name,
            machine=simfile.disk.machine, bytes=1, detectable=True)
    return True


def corrupt_value_bytes(simfile, pattern: bytes, rng) -> bool:
    """UNDETECTABLE corruption: flip a byte inside `pattern` wherever it
    occurs in a committed record's payload, then RECOMPUTE that
    record's CRC — bit rot the storage format cannot see. The only net
    left to catch it is check_consistency's replica comparison, which
    is exactly what the corruption tests prove."""
    import struct
    import zlib
    from .diskqueue import _REC_HDR
    raw = simfile._durable
    hit = bytes(raw).find(pattern)
    if hit < 0:
        return False
    for _seq, poff, length, off in _parse_dq_records(raw):
        if poff <= hit and hit + len(pattern) <= poff + length:
            flip = hit + rng.random_int(0, len(pattern))
            raw[flip] ^= rng.random_int(1, 256)
            # the crc is _REC_HDR's trailing u32 ("<QII")
            struct.pack_into(
                "<I", raw, off + _REC_HDR.size - 4,
                zlib.crc32(bytes(raw[poff:poff + length])))
            if simfile.disk.net is not None:
                simfile.disk.net.chaos_note(
                    "disk_corruption_undetected", file=simfile.name,
                    machine=simfile.disk.machine, bytes=1)
            return True
    return False


# -- scenario helpers ----------------------------------------------------

def worker_machines(cluster) -> list:
    return sorted({w.process.machine for w in cluster.workers.values()})


async def wait_fully_recovered(cluster, timeout: float = 60.0) -> bool:
    from .dbinfo import FULLY_RECOVERED
    deadline = flow.now() + timeout
    while flow.now() < deadline:
        if cluster.cc.dbinfo.get().recovery_state == FULLY_RECOVERED:
            return True
        await flow.delay(0.25)
    return False


async def database_digest(db, page_rows: int = 500) -> str:
    """SHA-256 over the full user keyspace read through the client
    surface — the "identical final state" half of the seed-replay
    determinism contract."""
    import hashlib
    from ..client.transaction import run_transaction
    h = hashlib.sha256()
    cursor = b""
    while True:
        async def page(tr, cursor=cursor):
            return await tr.get_range(cursor, b"\xff", limit=page_rows)
        rows = await run_transaction(db, page, max_retries=500)
        for k, v in rows:
            h.update(b"%d:%b=%d:%b;" % (len(k), k, len(v), v))
        if len(rows) < page_rows:
            return h.hexdigest()
        cursor = rows[-1][0] + b"\x00"


def _role_stores(cluster, prefix: str) -> list:
    """Live (machine, store_name) pairs for durable role stores whose
    name starts with `prefix`."""
    out = []
    for w in cluster.workers.values():
        if not w.process.alive:
            continue
        disk = cluster.net.disks.get(w.process.machine)
        if disk is None:
            continue
        for fname in sorted(disk.files):
            if fname.startswith(prefix) and fname.endswith(".dq0"):
                out.append((w.process.machine, fname))
    return out


async def _kill_role_safely(cluster, kind: str) -> Optional[str]:
    try:
        return cluster.kill_role(kind)
    except KeyError:
        return None


# -- scenarios -----------------------------------------------------------

class ChaosScenario:
    """One named, seeded chaos recipe. `cluster_kwargs` are the
    SimCluster arguments the scenario needs (the harness builds the
    cluster from them); `run` applies the faults, HEALS, and returns a
    report dict. A scenario that moves the surviving database (region
    failover) returns the client to verify under "check_db"."""

    name = "?"
    cluster_kwargs: dict = {"durable": True, "n_workers": 6,
                            "n_logs": 2, "n_storage": 2}

    async def run(self, cluster, rng) -> dict:
        raise NotImplementedError


class PartitionMinority(ChaosScenario):
    """Isolate a strict minority of worker machines from EVERYTHING
    (majority workers, CC, coordinators, clients) for
    CHAOS_PARTITION_SECONDS, then heal. Ping-based failure detection
    sees the minority as down; the unreachability watchdog ends the
    epoch if a critical role was inside; the majority recovers and
    keeps committing; after the heal the minority rejoins and catches
    up (ref: sim2's connection-failure partitions)."""

    name = "partition_minority"

    async def run(self, cluster, rng) -> dict:
        machines = worker_machines(cluster)
        pick = list(machines)
        rng.random_shuffle(pick)
        minority = sorted(pick[:max(1, (len(machines) - 1) // 2)])
        seconds = float(flow.SERVER_KNOBS.chaos_partition_seconds)
        pid = cluster.net.partition(minority)
        await flow.delay(seconds)
        cluster.net.heal(pid)
        await wait_fully_recovered(cluster)
        return {"partitioned": minority, "seconds": seconds}


class SwizzleLinks(ChaosScenario):
    """Swizzled-clogging storm (ref: the swizzle dance in sim2): open
    reorder/duplicate windows on random links while one-sided send/recv
    clogs with staggered expiries churn the rest of the mesh. Pure
    message-schedule hostility — nothing dies, so the oracle is that
    NOTHING needed to: same consistency, same liveness."""

    name = "swizzle_links"

    async def run(self, cluster, rng) -> dict:
        machines = worker_machines(cluster) + [cluster.cc.process.machine]
        window = float(flow.SERVER_KNOBS.chaos_swizzle_seconds)
        rounds = int(flow.SERVER_KNOBS.chaos_kill_rounds)
        swizzled = clogged = 0
        for _ in range(rounds):
            a = rng.random_choice(machines)
            b = rng.random_choice(machines)
            if a != b:
                cluster.net.swizzle(a, b, window)
                swizzled += 1
            # the clog dance: a seeded subset clogs with staggered
            # durations, so the unclog order differs from the clog order
            dance = list(machines)
            rng.random_shuffle(dance)
            for m in dance[:len(machines) // 2]:
                if rng.coinflip():
                    cluster.net.clog_send(m, rng.random01() * window)
                else:
                    cluster.net.clog_recv(m, rng.random01() * window)
                clogged += 1
            await flow.delay(window * (0.5 + rng.random01()))
        await flow.delay(window)   # let the last windows expire
        return {"swizzles": swizzled, "clogs": clogged}


class KillMidCommit(ChaosScenario):
    """Kill the role under a commit batch at an EXACT pipeline station
    (GRV handed out / commit version assigned / resolve answered /
    tlog fsync pending / log push acked) via the station hooks, once
    per round, letting recovery land between rounds. The atomicity
    oracle: every client observes commit-or-abort, never a partial
    write — enforced by the storm's check_consistency plus the
    directed marker-exactness tests."""

    name = "kill_mid_commit"

    #: (station, victim role kind) — which role dying at that station
    #: hurts the most
    STATION_VICTIMS = (
        ("MasterProxyServer.GRV.AfterReply", "proxy"),
        ("MasterProxyServer.commitBatch.GotCommitVersion", "proxy"),
        ("MasterProxyServer.commitBatch.AfterResolution", "resolver"),
        ("TLog.tLogCommit.AfterWaitForVersion", "tlog"),
        ("MasterProxyServer.commitBatch.AfterLogPush", "storage"),
    )

    async def run(self, cluster, rng) -> dict:
        kills = []
        for _ in range(int(flow.SERVER_KNOBS.chaos_kill_rounds)):
            station, kind = self.STATION_VICTIMS[
                rng.random_int(0, len(self.STATION_VICTIMS))]
            done = flow.Promise()

            def on_station(loc, kind=kind, done=done):
                victim = None
                try:
                    victim = cluster.kill_role(kind)
                except KeyError:
                    pass
                if not done.is_set:
                    done.send(victim)

            arm_station(station, on_station)
            got = await flow.catch_errors(
                flow.timeout_error(done.future, 15.0))
            victim = got.get() if not got.is_error else None
            kills.append((station, kind, victim))
            await wait_fully_recovered(cluster)
            await flow.delay(0.5 + rng.random01())
        clear_stations()   # an unfired arm must not leak past the storm
        return {"kills": kills}


class MachinePowerLoss(ChaosScenario):
    """Whole-machine power loss with co-located workers: every process
    on the machine dies at once, unsynced writes independently survive,
    are dropped, or TEAR (SIM_TORN_WRITE_PROB); auto-reboot brings the
    workers back onto the same disks and recovery must reassemble the
    cluster from whatever the CRC scan salvages (ref: killMachine +
    AsyncFileNonDurable)."""

    name = "machine_power_loss"
    cluster_kwargs = {"durable": True, "n_workers": 8,
                      "workers_per_machine": 2, "n_zones": 4,
                      "n_logs": 2, "n_storage": 2}

    async def run(self, cluster, rng) -> dict:
        lost = []
        for _ in range(2):
            machines = worker_machines(cluster)
            m = rng.random_choice(machines)
            lost.append((m, cluster.kill_machine(m)))
            await flow.delay(flow.SERVER_KNOBS.sim_reboot_delay + 1.0)
            await wait_fully_recovered(cluster)
        return {"lost": lost}


class DiskCorruptionRecovery(ChaosScenario):
    """Seeded sector corruption into committed DiskQueue records of a
    live tlog store AND a storage replica store, then power-fail the
    machines. Recovery's checksum scan detects the damage
    (checksum_failed), the worker drops the store — a recoverable role
    death — and replication heals: the log generation recovers from its
    peer, DD rebuilds the replica. check_consistency is the final
    oracle that nothing silently regressed."""

    name = "disk_corruption_recovery"
    cluster_kwargs = {"durable": True, "n_workers": 7, "n_logs": 2,
                      "n_storage": 2, "storage_replicas": 2}

    async def run(self, cluster, rng) -> dict:
        corrupted = []
        for prefix in ("tlog-", "storage-"):
            stores = _role_stores(cluster, prefix)
            if not stores:
                continue
            machine, fname = stores[rng.random_int(0, len(stores))]
            disk = cluster.net.disks[machine]
            f = disk.files.get(fname)
            alt = disk.files.get(fname[:-1] + "1")   # the .dq1 twin
            target = max((x for x in (f, alt) if x is not None),
                         key=lambda x: len(_parse_dq_records(x._durable)),
                         default=None)
            if target is None or not corrupt_record_payload(target, rng):
                continue
            corrupted.append((machine, target.name))
            cluster.kill_machine(machine)
            await flow.delay(flow.SERVER_KNOBS.sim_reboot_delay + 1.0)
        await wait_fully_recovered(cluster)
        return {"corrupted": corrupted}


class CoordinatorLossRecoveryStorm(ChaosScenario):
    """Kill a strict minority of the coordinators (the quorum
    survives), then force repeated master recoveries by killing a
    commit-pipeline role per round — the recovery state machine churns
    while coordination runs degraded (ref: the coordinators quorum
    contract + masterProcessFailure restart storms)."""

    name = "coordinator_loss_recovery_storm"
    cluster_kwargs = {"durable": True, "n_workers": 6, "n_logs": 2,
                      "n_storage": 2, "n_coordinators": 3}

    async def run(self, cluster, rng) -> dict:
        # a strict minority of coordinators dies (quorum lives)
        n_lose = (len(cluster.coordinators) - 1) // 2
        victims = list(range(len(cluster.coordinators)))
        rng.random_shuffle(victims)
        for i in victims[:n_lose]:
            cluster.net.kill(cluster.coordinators[i].process)
        kinds = ("tlog", "proxy", "resolver")
        killed = []
        for r in range(int(flow.SERVER_KNOBS.chaos_kill_rounds)):
            killed.append(await _kill_role_safely(cluster, kinds[r % 3]))
            await wait_fully_recovered(cluster)
            await flow.delay(0.5 + rng.random01() * 0.5)
        return {"coordinators_lost": n_lose, "killed": killed}


class RegionFailover(ChaosScenario):
    """Attach an async remote region (a recovery), replicate the storm
    through the log router, then BLACK OUT the primary — workers, CC,
    and a coordinator MINORITY (the surviving majority models the
    fearless layouts that place a coordinator quorum outside the
    primary DC; without one, promotion is impossible by design) — and
    promote the region through the coordinated recovery path
    (server/region.py). The verified database is the promoted one
    ("check_db"); losing the advertised replication lag is the
    async-region contract, losing anything else is a bug."""

    name = "region_failover"
    cluster_kwargs = {"durable": True, "auto_reboot": False,
                      "n_workers": 6, "n_storage": 2,
                      "n_coordinators": 5}

    async def run(self, cluster, rng) -> dict:
        from .region import RemoteRegion
        region = RemoteRegion(cluster)
        await region.start()
        # let the storm's traffic flow through the router, then give
        # the shipped frontier a bounded settle window (the lag never
        # reaches 0 while the version clock advances — the leftover IS
        # what the blackout is allowed to lose)
        await flow.delay(1.5)
        for _ in range(20):
            if region.lag() <= 0:
                break
            await flow.delay(0.25)
        lag_at_blackout = region.lag()
        for w in list(cluster.workers.values()):
            if w.process.alive:
                cluster.net.kill(w.process)
        cluster.net.kill(cluster.cc.process)
        # a coordinator MINORITY dies with the primary; the quorum
        # survives outside it (drawn seeded so replay kills the same set)
        coords = list(range(len(cluster.coordinators)))
        rng.random_shuffle(coords)
        for i in coords[:(len(coords) - 1) // 2]:
            if cluster.coordinators[i].process.alive:
                cluster.net.kill(cluster.coordinators[i].process)
        cluster.net.chaos_note("region_blackout",
                               lag_versions=lag_at_blackout)
        promoted = await region.promote()
        return {"check_db": promoted.client("chaos-region-check"),
                "promoted_epoch": promoted.cc.dbinfo.get().epoch,
                "lag_at_blackout": lag_at_blackout}


SCENARIOS: Dict[str, type] = {
    s.name: s for s in (
        PartitionMinority, SwizzleLinks, KillMidCommit, MachinePowerLoss,
        DiskCorruptionRecovery, CoordinatorLossRecoveryStorm,
        RegionFailover)
}


def get_scenario(name: str) -> ChaosScenario:
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise ValueError(
            f"unknown chaos scenario {name!r}; "
            f"known: {sorted(SCENARIOS)}") from None
