"""ClusterController: the elected brain — worker registry, recruitment,
ServerDBInfo broadcast, failure monitoring, master lifecycle.

Reference: fdbserver/ClusterController.actor.cpp — leader-elected via
the coordinators (LeaderElection.actor.cpp:78), keeps the worker
registry (registrationClient handshakes), recruits the transaction
subsystem per configuration (clusterRecruitFromConfiguration :1593),
broadcasts ServerDBInfo, runs the failure detection server, and
restarts the master — which re-runs the whole epoch recovery — whenever
any transaction-subsystem role fails (masterProcessFailure paths).
Failure detection here is the waitFailure heartbeat pattern
(fdbserver/WaitFailure.actor.cpp): ping every critical process; a
broken or timed-out ping is a failure.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

from .. import flow
from ..flow import AsyncVar, TaskPriority, error
from ..rpc import RequestStream, SimProcess
from .chaos import chaos_status as _chaos_status
from .coordination import CoordinatedState, elect_leader
from .dbinfo import (EMPTY_DBINFO, FULLY_RECOVERED, ServerDBInfo,
                     StorageRefs, StorageShard)
from .master import MasterRecovery
from .types import (CLEAR_RANGE, PING_REQUEST, SET_VALUE,
                    MetadataMutations)
from .worker import RegisterWorkerRequest


def _global_kernel_counters() -> dict:
    """Process-wide jitted-kernel profile for status. Guarded through
    sys.modules: a python-backend cluster never imported the ops layer,
    and status must not be the thing that drags jax in."""
    import sys
    out: dict = {}
    for mod in ("foundationdb_tpu.ops.conflict_kernel",
                "foundationdb_tpu.ops.point_kernel"):
        m = sys.modules.get(mod)
        if m is not None:
            out.update(m.g_kernel_counters.snapshot())
    return out


def epoch_roles(workers, epoch: int, cls):
    """Live current-epoch roles of `cls` from a CC worker registry:
    skip dead processes, match role class + the -e<epoch>- name
    convention. THE implementation of this walk — the CC's hot-spot
    push/merge and the Ratekeeper's input gathering both delegate
    here, so a change to role liveness or epoch naming lands once."""
    for wi in workers.values():
        if not wi.worker.process.alive:
            continue
        for rn, role in wi.worker.roles.items():
            if isinstance(role, cls) and f"-e{epoch}-" in rn:
                yield rn, role


class StorageHeatTable:
    """Decaying cluster-wide top-K of read-hot sub-ranges (ISSUE 13;
    ref: the DD/ratekeeper view over per-SS ReadHotSubRange replies).
    Same bounded-decay shape as ConflictHotSpots: each flagged range's
    read-bandwidth score halves every STORAGE_HEAT_HALF_LIFE seconds,
    the table caps at STORAGE_HEAT_MAX_ENTRIES (coldest evicted), so
    per-range state stays O(active hot ranges), never O(keyspace)."""

    __slots__ = ("_entries",)

    def __init__(self):
        # (server, begin, end) -> [decayed read-bps score, last density
        #                          ratio, last update time, sightings]
        self._entries: dict = {}

    @staticmethod
    def _decayed(score: float, since: float, now: float) -> float:
        hl = flow.SERVER_KNOBS.storage_heat_half_life
        if now <= since or hl <= 0:
            return score
        return score * 0.5 ** ((now - since) / hl)

    def record(self, server: str, begin: bytes, end: bytes,
               density: float, read_bps: float) -> None:
        now = flow.now()
        key = (server, begin, end)
        ent = self._entries.get(key)
        if ent is None:
            self._entries[key] = [float(read_bps), float(density), now, 1]
        else:
            ent[0] = self._decayed(ent[0], ent[2], now) + float(read_bps)
            ent[1] = float(density)
            ent[2] = now
            ent[3] += 1
        while len(self._entries) > \
                int(flow.SERVER_KNOBS.storage_heat_max_entries):
            worst = min(self._entries,
                        key=lambda k: self._decayed(
                            self._entries[k][0], self._entries[k][2], now))
            del self._entries[worst]

    def clear(self) -> None:
        self._entries.clear()

    def prune(self, live_servers) -> None:
        """Drop rows of retired replicas — a dead server's stale heat
        must not keep naming split candidates."""
        for key in [k for k in self._entries if k[0] not in live_servers]:
            del self._entries[key]

    def top(self, k: int = None) -> list:
        if k is None:
            k = int(flow.SERVER_KNOBS.storage_heat_top_k)
        now = flow.now()
        rows = [(self._decayed(s, t, now), d, srv, b, e, n)
                for (srv, b, e), (s, d, t, n) in self._entries.items()]
        rows.sort(key=lambda r: (-r[0], r[2], r[3]))
        return [{"server": srv, "begin": b.hex(), "end": e.hex(),
                 "density": round(d, 4), "read_bps": round(score, 2),
                 "sightings": n}
                for score, d, srv, b, e, n in rows[:k]]


def _client_profile_counters() -> dict:
    """Process-wide sampled-transaction profiler counters. Same
    sys.modules guard: a cluster that never sampled anything must not
    import the profiling module just to report zeros."""
    import sys
    m = sys.modules.get("foundationdb_tpu.client.profiling")
    return m.profiler_counters() if m is not None else {}


def _run_loop_status() -> dict:
    """status.cluster.run_loop: the run-loop profiler rollup — step and
    busy-time totals, the wall-vs-sim ratio, the slow-task table (each
    entry carrying the coroutine suspension stack captured at the slow
    step), and the SIM_TASK_STATS attribution table when armed."""
    sched = flow.g()
    busy = sched.busy_seconds     # one read: the property may flush
    doc = {
        "tasks_run": sched.tasks_run,
        "busy_seconds": round(busy, 3),
        # how many sim-seconds each busy wall-second buys — the
        # sim-scale headline ROADMAP item 6 optimizes (None until the
        # loop has done any measurable work)
        "sim_seconds": round(sched.now(), 3),
        "sim_per_busy": (round(sched.now() / busy, 2) if busy > 0
                         else None),
        "slow_task_count": sched.slow_task_count,
        "slow_task_threshold": (
            sched.slow_task_threshold
            if sched.slow_task_threshold is not None
            else float(flow.SERVER_KNOBS.slow_task_threshold)),
        "slow_tasks": [
            {"task": n, "seconds": round(s, 4), "stack": stack}
            for n, s, stack in sorted(sched.slow_tasks,
                                      key=lambda t: -t[1])[:5]],
    }
    if sched.task_stats_armed:
        doc["task_stats"] = sched.task_stats_report(
            top_k=int(flow.SERVER_KNOBS.sim_task_stats_top_k))
    return doc


class ClusterConfig(NamedTuple):
    """(ref: DatabaseConfiguration — the subset this slice understands)"""

    n_proxies: int = 1
    n_resolvers: int = 1
    n_logs: int = 1            # log replication factor
    n_storage: int = 1         # storage shards
    storage_replicas: int = 1  # replicas per shard (same-tag teams)
    conflict_backend: str = "python"
    durable: bool = False
    storage_engine: str = "memory"   # memory | btree (ref: ssd engine)
    # 1 = single region; 2 = a remote region may attach (ref:
    # DatabaseConfiguration usable_regions — the fearless gate). The
    # region OBJECT still comes from the attach seam (cc.region);
    # this row is the committed operator intent that recruitment obeys.
    usable_regions: int = 1
    # explicit storage-team placement policy (a ReplicationPolicy over
    # processid/machineid/zoneid/dcid localities). None = the default
    # Across(storage_replicas, zoneid, One()). When set explicitly,
    # team construction is STRICT: an unsatisfiable policy refuses the
    # team instead of degrading (ref: DatabaseConfiguration
    # storagePolicy driving DDTeamCollection team building).
    storage_policy: object = None


class OpenDatabaseRequest(NamedTuple):
    """Client handshake: long-polls until the broadcast sequence exceeds
    known_seq and recovery is complete (ref: openDatabase in
    ClusterController + MonitorLeader client polling)."""

    known_seq: int


class ChangeCoordinatorsRequest(NamedTuple):
    """Move the coordinated state to a new coordinator set (ref:
    ManagementAPI changeQuorum + MovableCoordinatedState,
    CoordinatedState.actor.cpp:220). `coordinators` is the new set's
    ref 4-tuples (reads, writes, candidacies, forwards)."""

    coordinators: tuple


class _WorkerInfo(NamedTuple):
    name: str
    machine: str
    worker: object
    roles: Tuple[str, ...]
    # always non-empty: registration falls back to machine / "dc0"
    zone: str
    dc: str


class ClusterController:
    def __init__(self, process: SimProcess, coordinators,
                 config: ClusterConfig, dbinfo_var=None,
                 takeover_from_region: bool = False,
                 leader_priority: int = 0):
        self.process = process
        self.config = config
        self.coordinators = coordinators   # ref 4-tuples:
        # (reads, writes, candidacies, forwards) — see SimCluster._coord_refs
        # dbinfo_var lets a promoted region's controller adopt the
        # broadcast var its storage servers already follow; a fresh CC
        # creates its own (ref: the remote DC's workers following the
        # same ServerDBInfo stream after failover)
        self.dbinfo = dbinfo_var if dbinfo_var is not None \
            else AsyncVar(EMPTY_DBINFO)
        # explicit region takeover (operator failover, ref: fdbcli
        # force_recovery_with_data_loss): recovery may end the previous
        # epoch by locking the REGION's log when no primary log survives
        self.takeover_from_region = takeover_from_region
        self.leader_priority = leader_priority
        self.workers: dict = {}            # name -> _WorkerInfo
        self.log_stores: dict = {}         # store name -> LogRefs (live)
        self.registrations = RequestStream(process)
        self.open_db = RequestStream(process)
        self.status_requests = RequestStream(process)
        self.management = RequestStream(process)
        self.excluded: set = set()         # worker names barred from roles
        # level-triggered: a change that lands mid-recovery is noticed
        # when the monitor next looks, never lost (code review r3)
        self._config_dirty = False
        self._move_inflight = False        # one shard move at a time
        self._vacate_seq = 0               # unique vacate-replica names
        self._vacate_retry_at = 0.0        # backoff for stuck vacates
        self._team_unhealthy_since: dict = {}  # tag -> first-seen time
        self._replica_progress: dict = {}  # name -> (version, since)
        self._dd_last_committed = -1       # idle detection for DD nudges
        self._max_tag_ever = max(config.n_storage - 1, 0)  # no tag reuse
        self.probe_paused = False          # quiet_database pauses probes
        self.backup_active = False         # continuous-backup tagging
        self.backup_agent = None           # the live agent, when any
        self.region = None                 # attached RemoteRegion, if any
        # authoritative shard boundaries (ref: the keyServers system
        # keyspace as ground truth); rebooted servers whose persisted
        # meta disagrees — e.g. crashed mid-move — are clamped to this
        self.shard_map: dict = {}          # name -> (tag, begin, end)
        self._recovery: Optional[MasterRecovery] = None
        self._recovery_task = None
        self._cstate: Optional[CoordinatedState] = None  # set once elected
        self._storage_objs: dict = {}      # name -> StorageServer (registry)
        # latest probe round + banded history per probe stage (ref: the
        # latencyProbe section of clusterGetStatus, Status.actor.cpp:983
        # — probes are real transactions, so the bands measure what a
        # client would actually experience)
        self._latency_probe: dict = {}
        self._probe_bands = {k: flow.RequestLatency(f"probe_{k}")
                             for k in ("grv", "read", "commit")}
        # the QoS telemetry plane: role name -> latest QosSample
        # (collected by _qos_sampler_loop at QOS_SAMPLE_INTERVAL; empty
        # when the knob is 0 — the plane then costs nothing anywhere)
        self.qos_samples: dict = {}
        # the storage heat plane's cluster rollup (ISSUE 13): decaying
        # top-K of read-hot sub-ranges across every storage replica +
        # the latest busiest-read-tag per server, fed by the QoS
        # sampler while STORAGE_HEAT_TRACKING is armed (empty — and
        # costless — otherwise)
        self.storage_heat = StorageHeatTable()
        self._heat_tags: dict = {}  # server -> (tag hex, busyness)
        # resolver split/merge accounting (ISSUE 15): the master's
        # balance loop records every split/merge/release/handoff
        # outcome here, so skew response is a status query
        # (`status.cluster.resolver_balance`), not a trace grep
        self.balance_stats = flow.CounterCollection("resolver_balance")
        self.balance_last: "dict | None" = None
        # the longitudinal plane (ISSUE 17, armed via METRIC_HISTORY):
        # the metric-history recorder, the SLO engine's latest verdict,
        # and TimeKeeper accounting. All stay empty/zero while the knob
        # is 0 — the plane's loops are then never even spawned, so the
        # off posture is byte-identical to pre-plane behavior
        self.metric_recorder = None
        self.slo_verdict: dict = {}
        self.slo_breaches = 0
        self._timekeeper_rows = 0
        # latency-forensics plane (ISSUE 18, armed via CRITICAL_PATH):
        # the decaying dominant-station table fed by the proxies' path
        # recorders, plus the host process's resource sampler. Same
        # off discipline as the longitudinal plane above.
        self.critical_path_table = None
        self._path_samples_folded = 0
        self.host_process_metrics = None
        # (instance name, counter) -> TimeSeries (ref: TDMetric levels)
        self.metrics: dict = {}
        self._metric_gauges: set = set()   # (rn, cn) sampled via set()
        self._rr = 0                       # recruitment round-robin
        self._seq = 0                      # dbinfo broadcast counter
        self._actors = flow.ActorCollection()

    def publish(self, info: ServerDBInfo) -> None:
        """Broadcast a new ServerDBInfo with a fresh sequence number —
        clients long-poll on the sequence so same-epoch updates (e.g. a
        rebooted storage's new endpoints) also reach them."""
        self._seq += 1
        self.dbinfo.set(info._replace(seq=self._seq))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        loops = [(self._run(), "run"),
                 (self._registration_loop(), "register"),
                 (self._open_db_loop(), "openDatabase"),
                 (self._status_loop(), "status"),
                 (self._management_loop(), "management"),
                 (self._dd_loop(), "dataDistribution"),
                 (self._failure_monitor_loop(), "failureMonitor"),
                 (self._metric_sampler_loop(), "metricSampler"),
                 (self._qos_sampler_loop(), "qosSampler"),
                 (self._hot_spot_push_loop(), "hotSpotPush"),
                 (self._trace_counters_loop(), "traceCounters"),
                 (self._latency_probe_loop(), "latencyProbe"),
                 (self._conf_sync_loop(), "confSync")]
        # the longitudinal plane's loops exist ONLY while armed: gating
        # at spawn time (not inside the loop) keeps the METRIC_HISTORY=0
        # posture byte-identical — zero extra actors, zero extra timers,
        # identical scheduler step counts (the pinned off posture)
        if flow.SERVER_KNOBS.metric_history:
            loops += [(self._timekeeper_loop(), "timeKeeper"),
                      (self._metric_history_loop(), "metricHistory"),
                      (self._slo_loop(), "sloEngine")]
        # latency-forensics fold loop (ISSUE 18): same spawn-time
        # gating — CRITICAL_PATH=0 means the loop never exists
        if flow.SERVER_KNOBS.critical_path:
            from .critical_path import CriticalPathTable
            from .process_metrics import ProcessMetrics
            self.critical_path_table = CriticalPathTable()
            self.host_process_metrics = ProcessMetrics(role="cc")
            loops += [(self._critical_path_loop(), "criticalPath")]
        for coro, name in loops:
            self._actors.add(flow.spawn(coro, TaskPriority.CLUSTER_CONTROLLER,
                                        name=f"{self.process.name}.{name}"))
        self.process.on_kill(self._actors.cancel_all)

    async def _run(self) -> None:
        # an election against a moved-away quorum follows the forwards
        # to the live coordinator set. The nomination carries this CC's
        # client endpoints so a client can re-find the controller
        # through the coordinators after a failover (ref: LeaderInfo
        # reaching clients via MonitorLeader)
        from .coordination import LeaderInfo
        self.coordinators = await elect_leader(
            self.coordinators, b"\xff/clusterLeader",
            LeaderInfo(self.leader_priority, self.process.name,
                       self.open_db.ref(), self.status_requests.ref(),
                       self.management.ref()),
            self.process)
        self._cstate = CoordinatedState(
            [(c[0], c[1]) for c in self.coordinators], self.process)
        while True:
            await self._wait_for_workers()
            self._recovery = MasterRecovery(self.process, self,
                                            self._cstate, self.config)
            self._recovery_task = flow.spawn(
                self._recovery.run(), TaskPriority.CLUSTER_CONTROLLER,
                name=f"master-recovery-e{self._recovery.epoch}")
            # wait for recovery to fail, or for any critical role to die
            # after recovery completes (ref: masterFailure handling)
            failed = await self._watch_epoch(self._recovery_task)
            flow.cover("cc.epoch_failed")
            flow.TraceEvent("MasterEpochFailed", self.process.name).detail(
                Reason=failed).log()
            self._recovery_task.cancel()
            self._recovery.aux.cancel_all()
            if self._recovery.master is not None:
                self._recovery.master.stop()
            self._cancel_old_roles()

    async def _metric_sampler_loop(self) -> None:
        """Sample every live role's counters into multi-resolution time
        series (ref: flow/TDMetric.actor.h levels + the SystemMonitor
        periodic events): recent history fine-grained, old history
        cheap, all served through status."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.metric_sample_interval,
                             TaskPriority.LOW_PRIORITY)
            now = flow.now()
            known: set = set()
            for wi in self.workers.values():
                # a rebooting worker's roles keep their HISTORY (its
                # registry entry persists through the reboot window);
                # only roles gone from the registry entirely are pruned
                known.update(wi.worker.roles.keys())
                if not wi.worker.process.alive:
                    continue
                for rn, role in wi.worker.roles.items():
                    stats = getattr(role, "stats", None)
                    if stats is None:
                        continue
                    for cname, c in stats.counters.items():
                        ts = self.metrics.get((rn, cname))
                        if ts is None:
                            ts = self.metrics[(rn, cname)] = \
                                flow.TimeSeries()
                        if c.gauge:
                            self._metric_gauges.add((rn, cname))
                        ts.append(now, c.value)
            # prune series of retired roles (old epochs, vacated
            # replicas): unbounded growth and stale 'latest' values
            # otherwise leak into every status document
            for key in [k for k in self.metrics if k[0] not in known]:
                del self.metrics[key]
                self._metric_gauges.discard(key)

    async def _qos_sampler_loop(self) -> None:
        """Collect every live role's QosSample (smoothed queue/lag/rate
        saturation signals) at QOS_SAMPLE_INTERVAL — the measurement
        half of the Ratekeeper feedback loop (ref: updateRate polling
        StorageQueuingMetrics/TLogQueuingMetrics; here the roles
        publish through one QosSample vocabulary and the controller
        holds the latest snapshot for status/exporter/ratekeeper).
        Interval 0 disables the plane: the dict empties and no role
        pays a thing (signals are pull-computed, never hot-path)."""
        while True:
            interval = flow.SERVER_KNOBS.qos_sample_interval
            if interval <= 0:
                if self.qos_samples:
                    self.qos_samples.clear()
                await flow.delay(1.0, TaskPriority.LOW_PRIORITY)
                continue
            await flow.delay(interval, TaskPriority.LOW_PRIORITY)
            now = flow.now()
            known: set = set()
            for wi in self.workers.values():
                if not wi.worker.process.alive:
                    continue
                for rn, role in wi.worker.roles.items():
                    fn = getattr(role, "qos_sample", None)
                    if fn is None:
                        continue
                    known.add(rn)
                    self.qos_samples[rn] = fn(now)
            # prune retired roles (old epochs, vacated replicas) so the
            # status document never reports a dead role's stale signals
            for rn in [r for r in self.qos_samples if r not in known]:
                del self.qos_samples[rn]
            self._roll_storage_heat()

    def _roll_storage_heat(self) -> None:
        """Fold every live replica's read-hot ranges + busiest read tag
        into the cluster rollup (one pull per QOS_SAMPLE_INTERVAL —
        the per-range state is the roles' own samples, never a second
        copy of the keyspace). Disarmed: empty both tables and pay one
        knob read per tick."""
        if not flow.SERVER_KNOBS.storage_heat_tracking:
            if self._heat_tags or self.storage_heat._entries:
                self.storage_heat.clear()
                self._heat_tags.clear()
            return
        live: set = set()
        for name, obj in self._storage_objs.items():
            if not obj.process.alive:
                continue
            live.add(name)
            for b, e, density, read_bps in obj.read_hot_ranges():
                self.storage_heat.record(name, b, e, density, read_bps)
            tag, busy = obj.busiest_read_tag()
            if tag is not None:
                self._heat_tags[name] = (tag.hex(), round(busy, 4))
            else:
                self._heat_tags.pop(name, None)
        self.storage_heat.prune(live)
        for name in [n for n in self._heat_tags if n not in live]:
            del self._heat_tags[name]

    def _epoch_roles(self, info, cls):
        """Live current-epoch roles of `cls` from the registry — the
        walk shared by the hot-spot merge/push and the ratekeeper's
        input gathering (module-level `epoch_roles` is the single
        implementation)."""
        return epoch_roles(self.workers, info.epoch, cls)

    def _merged_hot_rows(self, info) -> tuple:
        """Cluster-merged raw hot-spot rows across the current epoch's
        resolvers, hottest first: (begin, end, score, total,
        last_conflict_version). Keyspace-sharded resolvers each see
        disjoint causes; after a split-resolver move both owners may
        report the same range — scores sum, versions max."""
        from .resolver_role import Resolver
        merged: dict = {}
        for _rn, role in self._epoch_roles(info, Resolver):
            for b, e, s, t, v in role.hot_spots.rows():
                ent = merged.get((b, e))
                if ent is None:
                    merged[(b, e)] = [s, t, v]
                else:
                    ent[0] += s
                    ent[1] += t
                    ent[2] = max(ent[2], v)
        rows = [(b, e, s, t, v)
                for (b, e), (s, t, v) in merged.items()]
        rows.sort(key=lambda r: (-r[2], r[0], r[1]))
        return tuple(rows[:int(flow.SERVER_KNOBS.hot_spot_max_entries)])

    async def _hot_spot_push_loop(self) -> None:
        """Feed the conflict-prediction plane (ISSUE 8 / ROADMAP item
        2): the cluster-merged hot-spot rows — the only place the
        per-resolver attribution tables meet — are pushed to every
        current-epoch proxy at SCHED_HOT_PUSH_INTERVAL, where they
        drive the admission scheduler's ConflictPredictor and the GRV
        conflict-window piggyback. Idle (one knob read per interval)
        while both consuming planes are off."""
        while True:
            interval = flow.SERVER_KNOBS.sched_hot_push_interval
            await flow.delay(interval if interval > 0 else 1.0,
                             TaskPriority.LOW_PRIORITY)
            k = flow.SERVER_KNOBS
            if not (k.conflict_scheduling or k.client_conflict_windows
                    or k.txn_repair):
                continue
            from .proxy import Proxy
            info = self.dbinfo.get()
            rows = self._merged_hot_rows(info)
            for _rn, role in self._epoch_roles(info, Proxy):
                role.update_hot_spots(rows)

    async def _trace_counters_loop(self) -> None:
        """Roll every live role's CounterCollection into a periodic
        `*Metrics` TraceEvent with per-interval rates (ref:
        traceCounters, flow/Stats.actor.cpp — the reference's roles each
        run their own loop; the sim's registry lets one loop cover all
        of them). This is what turns the raw counters into rates an
        operator — or a later BENCH round — can actually read."""
        prev: dict = {}          # role name -> (snapshot, taken_at)
        while True:
            await flow.delay(flow.SERVER_KNOBS.trace_counters_interval,
                             TaskPriority.LOW_PRIORITY)
            now = flow.now()
            known: set = set()
            for wi in self.workers.values():
                # a rebooting worker's rate baselines survive the
                # reboot window (same rule as the metric sampler): a
                # missed tick must not blank the very rates an operator
                # reads around a fault. Only registry-departed roles
                # are pruned. Rates divide by each baseline's own age,
                # so a role that missed ticks reports a correct average
                # instead of an inflated one.
                known.update(wi.worker.roles.keys())
                if not wi.worker.process.alive:
                    continue
                for rn, role in wi.worker.roles.items():
                    stats = getattr(role, "stats", None)
                    if stats is None:
                        continue
                    p = prev.get(rn)
                    snap = stats.trace(
                        id=rn, elapsed=now - p[1] if p else None,
                        prev=p[0] if p else None)
                    prev[rn] = (snap, now)
            for key in [k for k in prev if k not in known]:
                del prev[key]

    async def _failure_monitor_loop(self) -> None:
        """Heartbeat every registered worker over the network and PUSH
        the failed set through the dbinfo broadcast (ref: the failure
        detection server + FailureMonitorClient — clients learn about
        down or unreachable machines without burning per-request
        timeouts; catches clogged-but-alive processes a liveness flag
        would miss)."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.failure_detection_interval,
                             TaskPriority.FAILURE_MONITOR)
            pinged, futs = [], []
            for name, wi in self.workers.items():
                # snapshot the incarnation AND its roles with the ping:
                # a worker that reboots mid-round must not have its
                # freshly recovered roles blamed for the old ping
                pinged.append((name, tuple(wi.worker.roles.keys())))
                futs.append(flow.catch_errors(flow.timeout_error(
                    wi.worker.pings.ref().get_reply(PING_REQUEST,
                                                    self.process),
                    flow.SERVER_KNOBS.failure_monitor_ping_timeout)))
            settled = await flow.all_of(futs)
            failed: set = set()
            for (name, roles), f in zip(pinged, settled):
                if f.is_error:
                    failed.add(name)
                    # the roles a down worker hosts are down too —
                    # replica names are what clients route by
                    failed.update(roles)
            cur = self.dbinfo.get()
            if tuple(sorted(failed)) != cur.failed:
                flow.cover("cc.failure_state_pushed")
                self.publish(cur._replace(failed=tuple(sorted(failed))))

    async def _wait_for_workers(self) -> None:
        need = max(self.config.n_logs, 1)
        while self._live_included_workers() < need:
            await flow.delay(flow.SERVER_KNOBS.cc_worker_poll_delay,
                             TaskPriority.CLUSTER_CONTROLLER)

    async def _watch_epoch(self, recovery_task) -> str:
        """Resolve when this epoch is over: recovery errored, or a
        critical process died post-recovery."""
        # phase 1: wait for full recovery (or recovery failure)
        while True:
            info = self.dbinfo.get()
            if info.recovery_state == FULLY_RECOVERED:
                break
            got = await flow.first_of(flow.catch_errors(recovery_task),
                                      self.dbinfo.on_change())
            if got[0] == 0:
                inner = got[1]
                if inner.is_error:
                    return f"recovery_error:{inner.exception()}"
                return "recovery_returned"
        # phase 2: monitor the recruited processes (ref: waitFailure
        # heartbeats; the sim checks liveness directly — a ping RPC to a
        # dead process would report the same thing a beat later) and
        # management-driven config changes (level-triggered so a change
        # that raced the recovery is still honored). A critical process
        # that is ALIVE but ping-unreachable (a partitioned or wedged
        # machine — the failure monitor's set) for a sustained window
        # ends the epoch exactly like a death: the reference's failure
        # detection is network-based, so a partition triggers a real
        # recovery, not an indefinite stall (ref: waitFailureServer
        # timeouts). The window sits above every ordinary BUGGIFY clog
        # so transient clogging never thrashes epochs.
        unreachable_since: dict = {}
        while True:
            if self._config_dirty:
                self._config_dirty = False
                return "configuration_changed"
            failed = set(self.dbinfo.get().failed)
            limit = float(flow.SERVER_KNOBS.failure_unreachable_seconds)
            now = flow.now()
            for proc in self._recovery.critical_procs:
                if not proc.alive:
                    return f"process_failed:{proc.name}"
                if limit > 0 and proc is not self.process \
                        and proc.name in failed:
                    since = unreachable_since.setdefault(proc.name, now)
                    if now - since >= limit:
                        flow.cover("cc.epoch_unreachable")
                        return f"process_unreachable:{proc.name}"
                else:
                    unreachable_since.pop(proc.name, None)
            await flow.delay(flow.SERVER_KNOBS.failure_detection_interval,
                             TaskPriority.FAILURE_MONITOR)

    def _cancel_old_roles(self) -> None:
        """Cancel surviving roles of the failed epoch so stale proxies
        and resolvers stop answering (ref: the old generation's actors
        dying with the master's lifetime)."""
        epoch = self._recovery.epoch if self._recovery else 0
        for wi in self.workers.values():
            w = wi.worker
            for name, role in list(w.roles.items()):
                if name.startswith((f"proxy-e{epoch}", f"resolver-e{epoch}",
                                    f"ratekeeper-e{epoch}")):
                    stop = getattr(role, "stop", None)
                    if stop is not None:
                        stop()
                    else:
                        role._actors.cancel_all()
                    del w.roles[name]

    # -- worker registry -------------------------------------------------
    async def _registration_loop(self):
        while True:
            req, reply = await self.registrations.pop()
            assert isinstance(req, RegisterWorkerRequest)
            p = req.worker.process
            self.workers[req.name] = _WorkerInfo(
                req.name, req.machine, req.worker, (),
                getattr(p, "zone", req.machine),
                getattr(p, "dc", "dc0"))
            for lr in req.recovered_logs:
                self.log_stores[lr.store] = lr
            if req.recovered_logs:
                self._merge_recovered_logs(req.recovered_logs)
            if req.recovered_storages:
                for r in req.recovered_storages:
                    obj = req.worker.roles.get(r.name)
                    if obj is not None:
                        self._storage_objs[r.name] = obj
                self._merge_storages(req.recovered_storages)
            reply.send(None)

    def _merge_recovered_logs(self, refs) -> None:
        """A rebooted worker re-registered old-generation log stores:
        swap the fresh endpoints into the broadcast picture by store
        name, or a behind storage server could never finish draining
        that generation — its peeks would hit the dead pre-reboot refs
        until the next full recovery (found by the DD-under-attrition
        workload). Current-generation refs are recovery's job: a
        current tlog death already ends the epoch."""
        info = self.dbinfo.get()
        by_store = {lr.store: lr for lr in refs}
        changed = False
        new_old = []
        for gen in info.old_logs:
            logs = tuple(by_store.get(lr.store, lr) for lr in gen.logs)
            # a store that was UNREACHABLE when this generation's
            # picture was built rejoins by name — without this, a
            # reader needing the generation would wait forever (and
            # before the strict-coverage rule, it silently skipped)
            present = {lr.store for lr in logs}
            for store, _machine in gen.stores:
                lr = by_store.get(store)
                if lr is not None and store not in present:
                    flow.cover("cc.old_log_rejoined")
                    logs = logs + (lr,)
                    present.add(store)
            if logs != gen.logs:
                changed = True
            new_old.append(gen._replace(logs=logs))
        if changed:
            self.publish(info._replace(old_logs=tuple(new_old)))

    def _merge_storages(self, refs: Tuple[StorageRefs, ...]) -> None:
        """A rebooted worker re-registered storage shards: swap the new
        endpoints into the broadcast map, clamping each server's bounds
        to the authoritative shard map (its persisted meta may be stale
        if it crashed mid-move; the clamp also makes it shed data it no
        longer owns)."""
        info = self.dbinfo.get()
        shards = list(info.storages)
        changed = False
        for r in refs:
            auth = self.shard_map.get(r.name)
            if auth is None:
                continue
            _tag, b, e = auth
            if (r.begin, r.end) != (b, e):
                obj = self._storage_objs.get(r.name)
                if obj is not None:
                    flow.spawn(obj.set_bounds(b, e),
                               TaskPriority.DATA_DISTRIBUTION,
                               name=f"{r.name}.clampBounds")
                r = r._replace(begin=b, end=e)
            for si, shard in enumerate(shards):
                if any(rep.name == r.name for rep in shard.replicas):
                    shards[si] = shard._replace(replicas=tuple(
                        r if rep.name == r.name else rep
                        for rep in shard.replicas))
                    changed = True
        if changed:
            self.publish(info._replace(storages=tuple(shards)))

    # -- recruitment helpers (used by MasterRecovery) -------------------
    @staticmethod
    def _locality_of(wi) -> "Locality":
        from .replication_policy import Locality
        return Locality(processid=wi.name, machineid=wi.machine,
                        zoneid=wi.zone, dcid=wi.dc)

    def storage_policy(self, n: int):
        """The storage-team policy and whether it is strict: an
        explicitly configured policy refuses unsatisfiable teams; the
        default Across(n, zoneid, One()) degrades (ref:
        DatabaseConfiguration storagePolicy)."""
        from .replication_policy import PolicyAcross, PolicyOne
        if self.config.storage_policy is not None:
            return self.config.storage_policy, True
        return PolicyAcross(n, "zoneid", PolicyOne()), False

    def pick_workers(self, n: int, role: str, policy=None,
                     strict: bool = False):
        """Policy-placed selection over live, non-excluded workers:
        replicas land in distinct failure domains when the worker pool
        allows it, degrading to round-robin when it cannot — unless
        `strict`, in which case an unsatisfiable policy raises
        no_more_servers (a policy-violating team is unconstructible)
        (ref: clusterRecruitFromConfiguration applying the
        configuration's storagePolicy/tLogPolicy;
        fdbrpc/ReplicationPolicy.h). Candidate order rotates so
        consecutive recruitments spread roles the way the reference's
        fitness ranking does."""
        from .replication_policy import PolicyAcross, PolicyOne
        # recruitment is DC-local (ref: clusterRecruitFromConfiguration
        # recruiting the transaction subsystem in the primary DC):
        # satellite log workers register for lock/rejoin visibility but
        # must never be handed proxy/resolver/storage roles
        my_dc = getattr(self.process, "dc", "dc0")
        live = [wi for name, wi in self.workers.items()
                if wi.worker.process.alive and name not in self.excluded
                and wi.dc == my_dc]
        # prefer ping-REACHABLE workers: recruiting onto an alive but
        # partitioned machine hands the new epoch a role nobody can
        # talk to, and the unreachability watchdog immediately ends it
        # again — recovery-storms for the whole partition. Fall back to
        # the full live set when the reachable pool is too small (the
        # failure monitor may simply be behind)
        unreachable = set(self.dbinfo.get().failed)
        reachable = [wi for wi in live if wi.name not in unreachable]
        if len(reachable) >= n:
            live = reachable
        if not live:
            raise error("no_more_servers")
        rot = self._rr % len(live)
        self._rr += n
        ordered = live[rot:] + live[:rot]
        cands = [(wi.worker, self._locality_of(wi)) for wi in ordered]
        if policy is None:
            policy = PolicyAcross(n, "zoneid", PolicyOne())
        team = policy.select(cands)
        if team is not None:
            return team
        if strict:
            flow.TraceEvent("RecruitmentPolicyUnsatisfiable",
                            self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Role=role, Policy=repr(policy),
                Zones=len({wi.zone for wi in live})).log()
            raise error("no_more_servers")
        # not enough failure domains: place anyway, spread round-robin
        # (the reference recruits in degraded mode rather than stall)
        flow.TraceEvent("RecruitmentPolicyDegraded", self.process.name,
                        severity=flow.trace.SevWarn).detail(
            Role=role, Needed=n,
            Zones=len({wi.zone for wi in live})).log()
        return [ordered[i % len(ordered)].worker for i in range(n)]

    def storage_splits(self) -> Tuple[bytes, ...]:
        info = self.dbinfo.get()
        if info.storages:
            return tuple(s.begin for s in info.storages[1:])
        return tuple(bytes([(i * 256) // self.config.n_storage])
                     for i in range(1, self.config.n_storage))

    def storage_tags(self) -> Tuple[int, ...]:
        """Tags in shard (begin) order — explicit because splits mint
        fresh tags mid-keyspace."""
        info = self.dbinfo.get()
        if info.storages:
            return tuple(s.tag for s in info.storages)
        return tuple(range(self.config.n_storage))

    def recruit_initial_storages(self) -> None:
        """First boot only: create the shard set (ref: the initial
        `configure new` creating storage servers via DD; static shards
        here until DataDistribution arrives)."""
        info = self.dbinfo.get()
        if info.storages:
            return
        splits = list(self.storage_splits())
        bounds = [b""] + splits + [None]
        nrep = max(1, self.config.storage_replicas)
        pol, strict = self.storage_policy(nrep)
        storages = []
        for i in range(self.config.n_storage):
            team = self.pick_workers(nrep, role="storage", policy=pol,
                                     strict=strict)
            replicas = []
            for j, w in enumerate(team):
                refs = w.recruit_storage(f"storage-{i}-r{j}", i, bounds[i],
                                         bounds[i + 1])
                replicas.append(refs)
                self._storage_objs[refs.name] = w.roles[refs.name]
                self.shard_map[refs.name] = (i, bounds[i], bounds[i + 1])
            storages.append(StorageShard(i, bounds[i], bounds[i + 1],
                                         tuple(replicas)))
        self.publish(info._replace(storages=tuple(storages)))

    def tlog_objs(self):
        """Live TLog role objects of the current generation (stats feed
        for the ratekeeper; sim stand-in for TLogQueuingMetrics)."""
        out = []
        info = self.dbinfo.get()
        for lr in info.logs.logs:
            for wi in self.workers.values():
                obj = wi.worker.roles.get(lr.store)
                if obj is not None and wi.worker.process.alive:
                    out.append(obj)
        return out

    def min_storage_version(self) -> int:
        """Smallest DURABLE version across shards — the floor for
        retiring old log generations. A dead or unregistered shard
        pins the floor at 0: it may come back needing everything the
        old generation still holds (code review r3)."""
        info = self.dbinfo.get()
        vs = []
        for s in info.storages:
            for rep in s.replicas:
                obj = self._storage_objs.get(rep.name)
                if obj is None or not obj.process.alive:
                    return 0
                vs.append(obj.durable_version.get())
        return min(vs) if vs else 0

    # -- management -------------------------------------------------------
    async def _management_loop(self):
        """(ref: ManagementAPI + ApplyMetadataMutation.h — management
        state changes arrive as COMMITTED \\xff/conf//\\xff/excluded
        mutations forwarded by the proxies; a config change ends the
        epoch so recovery rebuilds the transaction subsystem with the
        new shape. Only the coordinators change — which needs the
        quorum-move dance, not a key write — remains a direct request.)"""
        while True:
            req, reply = await self.management.pop()
            if isinstance(req, MetadataMutations):
                self._apply_metadata_mutations(req)
            elif isinstance(req, ChangeCoordinatorsRequest):
                try:
                    await self._change_coordinators(
                        tuple(req.coordinators))
                    reply.send(None)
                except flow.FdbError as e:
                    reply.send_error(e)
                except Exception:
                    # a malformed payload (non-ref elements) must fail
                    # the REQUEST, never the management loop
                    reply.send_error(error("operation_failed"))
            else:
                reply.send_error(error("client_invalid_operation"))

    def _apply_metadata_mutations(self, req) -> None:
        """React to committed management keys (ref:
        ApplyMetadataMutation.h + the CC watching configuration: the
        committed rows are the medium; this interprets them — the
        low-latency trigger; _conf_sync_loop reconciles from the
        stored rows, so a lost notice only delays, never diverges).
        Invalid values are IGNORED with a SevWarnAlways trace rather
        than bricking recovery in a retry loop — mirroring the
        reference, where an unrecruitable \\xff/conf shape needs
        operator repair."""
        from .systemkeys import CONFLICT_BACKENDS, CONF_MUTABLE, \
            CONF_PREFIX, CONF_ROWS, EXCLUDED_PREFIX
        updates: dict = {}
        # worker -> desired excluded state, LAST mutation wins — a
        # single transaction may set then clear the same row and the
        # committed (ordered) outcome is what must apply
        excl_state: dict = {}
        for m in req.mutations:
            if m.type == CLEAR_RANGE:
                known = set(self.excluded) | \
                    {w for w, v in excl_state.items() if v}
                for w in known:
                    if m.param1 <= EXCLUDED_PREFIX + w.encode() \
                            < m.param2:
                        excl_state[w] = False
                for row in CONF_MUTABLE:
                    if m.param1 <= CONF_PREFIX + row.encode() < m.param2:
                        field = CONF_ROWS[row]
                        updates[field] = getattr(ClusterConfig(), field)
            elif m.type != SET_VALUE:
                # atomics on management keys have storage-side results
                # the proxy does not evaluate: leave them to the
                # reconcile loop, which reads the actual rows back
                flow.cover("cc.metadata.non_set_deferred")
            elif m.param1.startswith(CONF_PREFIX):
                row = m.param1[len(CONF_PREFIX):].decode(errors="replace")
                if row not in CONF_MUTABLE:
                    continue  # informational/unknown rows: inert
                field = CONF_ROWS[row]
                if row == "conflict_backend":
                    updates[field] = m.param2.decode(errors="replace")
                else:
                    try:
                        updates[field] = int(m.param2)
                    except ValueError:
                        flow.TraceEvent(
                            "MetadataConfValueIgnored", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                            Key=row, Value=repr(m.param2)).log()
            elif m.param1.startswith(EXCLUDED_PREFIX):
                w = m.param1[len(EXCLUDED_PREFIX):].decode(
                    errors="replace")
                excl_state[w] = True
        for w, want in excl_state.items():
            if not want:
                self.excluded.discard(w)
        for w, want in excl_state.items():
            if not want:
                continue
            need = max(self.config.n_logs, self.config.n_proxies,
                       self.config.n_resolvers, 1)
            if self._live_included_workers(without=w) < need:
                # honoring it would strand recovery in a retry loop;
                # the committed row stays (operator repair, like the
                # reference's FORCE-mode exclusions)
                flow.cover("cc.metadata.exclusion_unrecruitable")
                flow.TraceEvent(
                    "MetadataExclusionIgnored", self.process.name,
                    severity=flow.trace.SevWarnAlways).detail(
                    Worker=w).log()
                continue
            self.excluded.add(w)
            if self._hosts_current_txn_role(w):
                self._config_dirty = True
        if updates:
            cand = self.config._replace(**updates)
            live = self._live_included_workers()
            if (cand.n_proxies < 1 or cand.n_resolvers < 1
                    or cand.n_logs < 1 or cand.n_logs > live
                    or cand.n_resolvers > live or cand.n_proxies > live
                    or cand.usable_regions not in (1, 2)
                    or cand.conflict_backend not in CONFLICT_BACKENDS):
                flow.cover("cc.metadata.config_unrecruitable")
                flow.TraceEvent(
                    "MetadataConfigIgnored", self.process.name,
                    severity=flow.trace.SevWarnAlways).detail(
                    Config=repr(updates)).log()
            elif cand != self.config:
                self.config = cand
                self._config_dirty = True

    async def _conf_sync_loop(self) -> None:
        """The committed \\xff/conf//\\xff/excluded rows are
        AUTHORITATIVE (ref: the reference reading its configuration
        from the system keyspace during recovery): every sync round
        (a) ADOPTS valid divergent rows into the live config and
        exclusion set — so a lost proxy notice (the one-way datagram
        is only the low-latency trigger) delays a change, never loses
        it; (b) REPAIRS unparsable/unrecruitable rows back to the live
        values — an acked-but-invalid row must not sit forever; and
        (c) SEEDS missing rows (the initial `configure new`
        analogue)."""
        from ..client import Database
        db = Database(self.process, self.open_db.ref(),
                      status_ref=self.status_requests.ref(),
                      management_ref=self.management.ref())
        self.process.on_kill(db.close)
        while True:
            await flow.delay(flow.SERVER_KNOBS.conf_sync_interval,
                             TaskPriority.CLUSTER_CONTROLLER)
            if self.dbinfo.get().recovery_state != FULLY_RECOVERED:
                continue
            try:
                await self._conf_sync_once(db)
            except flow.FdbError as e:
                if e.name == "operation_cancelled":
                    raise
                flow.TraceEvent("ConfSyncRetry", self.process.name,
                                severity=flow.trace.SevWarn).detail(
                    Error=e.name).log()

    async def _conf_sync_once(self, db) -> None:
        from ..client import run_transaction
        from .systemkeys import (CONF_END, CONF_MUTABLE, CONF_PREFIX,
                                 CONF_ROWS, CONFLICT_BACKENDS,
                                 EXCLUDED_END, EXCLUDED_PREFIX)

        async def read(tr):
            tr.set_option("read_system_keys")
            conf = dict(await tr.get_range(CONF_PREFIX, CONF_END))
            excl = dict(await tr.get_range(EXCLUDED_PREFIX, EXCLUDED_END))
            return conf, excl

        conf_rows, excl_rows = await run_transaction(db, read,
                                                     max_retries=50)
        repairs: dict = {}       # key -> value to set (None = clear)
        updates: dict = {}
        for row, field in CONF_ROWS.items():
            key = CONF_PREFIX + row.encode()
            live = str(getattr(self.config, field)).encode()
            val = conf_rows.get(key)
            if val is None:
                repairs[key] = live          # seed missing row
                continue
            if row not in CONF_MUTABLE:
                if val != live:
                    repairs[key] = live      # informational: follow live
                continue
            if row == "conflict_backend":
                updates[field] = val.decode(errors="replace")
            else:
                try:
                    updates[field] = int(val)
                except ValueError:
                    repairs[key] = live
        cand = self.config._replace(**updates)
        live_workers = self._live_worker_names()
        n_live = sum(1 for name in live_workers
                     if name not in self.excluded)
        if (cand.n_proxies < 1 or cand.n_resolvers < 1
                or cand.n_logs < 1 or cand.n_logs > n_live
                or cand.n_resolvers > n_live or cand.n_proxies > n_live
                or cand.usable_regions not in (1, 2)
                or cand.conflict_backend not in CONFLICT_BACKENDS):
            flow.cover("cc.metadata.sync_repair_config")
            flow.TraceEvent("ConfRowsRepaired", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                Config=repr(updates)).log()
            for row in CONF_MUTABLE:
                field = CONF_ROWS[row]
                repairs[CONF_PREFIX + row.encode()] = \
                    str(getattr(self.config, field)).encode()
        elif cand != self.config:
            flow.cover("cc.metadata.sync_adopted")
            self.config = cand
            self._config_dirty = True
        # exclusions: the rows are the truth; refuse (and repair) only
        # rows that would leave recruitment impossible
        desired_excl: set = set()
        need = max(self.config.n_logs, self.config.n_proxies,
                   self.config.n_resolvers, 1)
        for key in sorted(excl_rows):
            w = key[len(EXCLUDED_PREFIX):].decode(errors="replace")
            remaining = sum(1 for name in live_workers
                            if name not in desired_excl and name != w)
            if remaining < need:
                flow.cover("cc.metadata.sync_repair_exclusion")
                flow.TraceEvent(
                    "ExclusionRowRepaired", self.process.name,
                    severity=flow.trace.SevWarnAlways).detail(
                    Worker=w).log()
                repairs[key] = None
                continue
            desired_excl.add(w)
        if desired_excl != self.excluded:
            added = desired_excl - self.excluded
            self.excluded = desired_excl
            if any(self._hosts_current_txn_role(w) for w in added):
                self._config_dirty = True
        if repairs:
            async def fix(tr):
                tr.set_option("access_system_keys")
                for k, v in repairs.items():
                    if v is None:
                        tr.clear(k)
                    else:
                        tr.set(k, v)
            await run_transaction(db, fix, max_retries=50)

    @staticmethod
    def _coord_id(c) -> tuple:
        """Stable identity of a coordinator ref-tuple (refs deserialize
        into fresh objects, so compare (process, token) pairs)."""
        return tuple((r.endpoint.process.name, r.endpoint.token)
                     for r in c[:4])

    async def _change_coordinators(self, new_coords: tuple) -> None:
        """MovableCoordinatedState (ref: CoordinatedState.actor.cpp:220
        + ManagementAPI changeQuorum): seed the NEW quorum with the
        current core state, then EXCLUSIVELY tombstone the old quorum
        with a MovedValue (a concurrent recovery's write makes this
        conflict and the whole change aborts cleanly), then decommission
        the old coordinators so everything redirects. Ends the epoch so
        the next recovery commits through the new quorum."""
        from .coordination import ForwardRequest, MovedValue
        # validate BEFORE touching anything: a malformed request must
        # fail the request, never the management loop
        if len(new_coords) < 1 or any(len(c) < 4 for c in new_coords):
            raise error("invalid_option_value")
        if getattr(self, "_cstate", None) is None:
            raise error("operation_failed")   # not elected yet
        new_ids = {self._coord_id(c) for c in new_coords}
        if new_ids == {self._coord_id(c) for c in self.coordinators}:
            flow.cover("coordination.change.noop")
            return  # already the active set (ref: changeQuorum no-op);
                    # re-running the move would forward the live quorum
                    # at itself and brick the cluster
        # the move runs on a PRIVATE handle over the old quorum: the
        # epoch machinery shares self._cstate, and sharing its
        # generation would let the tombstone commit at a generation
        # this mover never read — breaking the exclusivity that makes
        # a racing recovery abort the change
        old_cs = CoordinatedState(
            [(c[0], c[1]) for c in self.coordinators], self.process)
        # 0. rejoin broadcast: members of the new set clear any STALE
        # forward left from a previous decommissioning (a change-back
        # to once-retired hosts must not chase their old forwards)
        await flow.all_of([flow.catch_errors(flow.timeout_error(
            c[3].get_reply(ForwardRequest(new_coords), self.process),
            flow.SERVER_KNOBS.coordinator_forward_timeout))
            for c in new_coords])
        # 1. current state through the current quorum (raises read gens)
        cur = await old_cs.read()
        # 2. seed the new quorum
        new_cs = CoordinatedState(
            [(c[0], c[1]) for c in new_coords], self.process)
        await new_cs.read()
        await new_cs.set_exclusive(cur)
        # 3. exclusive tombstone on the old quorum — the linearization
        # point: past this await the change IS committed
        await old_cs.set_exclusive(MovedValue(new_coords, cur))
        # the change is durable: adopt the new quorum unconditionally
        # before the best-effort decommissioning below
        old_set = [c for c in self.coordinators
                   if self._coord_id(c) not in new_ids]
        self.coordinators = list(new_coords)
        self._cstate = new_cs
        flow.TraceEvent("CoordinatorsChanged", self.process.name).detail(
            N=len(new_coords)).log()
        # force a recovery: the next epoch's core state commits through
        # the new quorum (ref: changeQuorum triggering recovery)
        self._config_dirty = True
        # 4. decommission old coordinators NOT in the new set. Pure
        # best effort: the MovedValue tombstone already redirects any
        # reader that reaches a non-forwarded old coordinator
        await flow.all_of([flow.catch_errors(flow.timeout_error(
            c[3].get_reply(ForwardRequest(new_coords), self.process),
            flow.SERVER_KNOBS.coordinator_forward_timeout))
            for c in old_set])

    def _live_worker_names(self) -> list:
        """Alive workers in THIS controller's DC — the same filter
        pick_workers applies: cross-DC satellite workers can hold log
        replicas but never transaction roles, so recruitable-shape
        checks counting them would approve configs the primary DC
        cannot actually host."""
        my_dc = getattr(self.process, "dc", "dc0")
        return [name for name, wi in self.workers.items()
                if wi.worker.process.alive and wi.dc == my_dc]

    def _live_included_workers(self, without: str = None) -> int:
        return sum(1 for name in self._live_worker_names()
                   if name not in self.excluded and name != without)

    def _hosts_current_txn_role(self, worker_name: str) -> bool:
        """Does the worker host a CURRENT-epoch transaction role?
        Storage shards and retained old-generation logs don't count —
        exclusion can't vacate them without data distribution."""
        wi = self.workers.get(worker_name)
        if wi is None:
            return False
        ep = self.dbinfo.get().epoch
        prefixes = (f"tlog-e{ep}-", f"proxy-e{ep}-", f"resolver-e{ep}-",
                    f"ratekeeper-e{ep}")
        return any(rn.startswith(prefixes) for rn in wi.worker.roles)

    async def _latency_probe_loop(self):
        """Measure real GRV/read/commit latency through an ordinary
        client transaction and surface it in status (ref: the latency
        probe section of clusterGetStatus, Status.actor.cpp:983 —
        operators read these, and the bands feed alerting)."""
        from ..client import Database
        db = Database(self.process, self.open_db.ref())
        probe_seen_committed = -1
        rounds = 0
        while True:
            await flow.delay(flow.SERVER_KNOBS.latency_probe_interval,
                             TaskPriority.LOW_PRIORITY)
            if self.dbinfo.get().recovery_state != FULLY_RECOVERED or \
                    self.probe_paused:
                continue
            try:
                probe_key = b"\xff\x02/status/latency_probe"
                tr = db.create_transaction()
                tr.set_option("read_system_keys")
                t0 = flow.now()
                await tr.get_read_version()
                grv_s = flow.now() - t0
                t1 = flow.now()
                await tr.get(probe_key)
                read_s = flow.now() - t1
                self._probe_bands["grv"].record(grv_s)
                self._probe_bands["read"].record(read_s)
                rounds += 1
                probe = {
                    "transaction_start_seconds": round(grv_s, 6),
                    "read_seconds": round(read_s, 6),
                    "probed_at": round(flow.now(), 3),
                    "rounds": rounds,
                }
                # the COMMIT probe only runs while the cluster is
                # seeing commits: an idle cluster must be able to go
                # fully quiet (quiet_database drains the log to zero),
                # which a 5s probe write would forever prevent
                committed = max((p.committed_version.get()
                                 for p in self._current_proxies()),
                                default=-1)
                if committed != probe_seen_committed:
                    tr2 = db.create_transaction()
                    tr2.set_option("access_system_keys")
                    tr2.set(probe_key, b"%d" % int(flow.now() * 1000))
                    t2 = flow.now()
                    probe_seen_committed = await tr2.commit()
                    self._probe_bands["commit"].record(flow.now() - t2)
                    probe["commit_seconds"] = round(flow.now() - t2, 6)
                elif "commit_seconds" in self._latency_probe:
                    probe["commit_seconds"] = \
                        self._latency_probe["commit_seconds"]
                self._latency_probe = probe
            except flow.FdbError:
                pass  # a probe racing a recovery just skips a round

    # -- the longitudinal plane (ISSUE 17; spawned only when armed) ------
    async def _timekeeper_loop(self):
        """Commit the version<->wallclock map row by row through the
        ordinary pipeline (ref: fdbserver/TimeKeeper.actor.cpp). Writes
        only while the cluster is seeing OTHER commits — the latency
        probe's quiescence pattern: the row's own commit version is
        remembered so an idle cluster can still go fully quiet."""
        from ..client import Database
        from .systemkeys import timekeeper_key
        db = Database(self.process, self.open_db.ref())
        seen_committed = -1
        while True:
            await flow.delay(flow.SERVER_KNOBS.timekeeper_interval,
                             TaskPriority.LOW_PRIORITY)
            if self.dbinfo.get().recovery_state != FULLY_RECOVERED or \
                    self.probe_paused:
                continue
            committed = max((p.committed_version.get()
                             for p in self._current_proxies()),
                            default=-1)
            if committed < 0 or committed == seen_committed:
                continue
            try:
                tr = db.create_transaction()
                tr.set_option("access_system_keys")
                tr.set(timekeeper_key(int(flow.now() * 1000)),
                       b"%d" % committed)
                seen_committed = await tr.commit()
                self._timekeeper_rows += 1
            except flow.FdbError:
                pass  # a row racing a recovery just skips a round

    async def _metric_history_loop(self):
        """Sample the status signals into the recorder each tick and
        flush full chunks into \\xff\\x02/metrics/ (schema:
        systemkeys.py; recorder: server/metric_history.py). Sampling
        always runs (the SLO engine reads the in-memory tail even
        mid-recovery); flushing needs a recovered pipeline."""
        from ..client import Database
        from .metric_history import MetricHistoryRecorder
        self.metric_recorder = rec = MetricHistoryRecorder(self)
        db = Database(self.process, self.open_db.ref())
        while True:
            await flow.delay(flow.SERVER_KNOBS.metric_history_interval,
                             TaskPriority.LOW_PRIORITY)
            rec.record(flow.now())
            if self.dbinfo.get().recovery_state != FULLY_RECOVERED or \
                    self.probe_paused:
                continue
            try:
                await rec.flush(db)
            except flow.FdbError:
                pass  # buffered samples flush on a later round

    async def _slo_loop(self):
        """Evaluate the SLO rule table over the recorder's in-memory
        tail every SLO_EVAL_INTERVAL (server/slo.py — the same pure
        math the soak's post-hoc read-back runs over the persisted
        series). Breach transitions are counted and traced; the
        verdict rides status.cluster.slo + health messages."""
        from . import slo as slo_mod
        rules = slo_mod.default_rules()
        prev_state = "ok"
        while True:
            await flow.delay(flow.SERVER_KNOBS.slo_eval_interval,
                             TaskPriority.LOW_PRIORITY)
            rec = self.metric_recorder
            if rec is None:
                continue
            verdict = slo_mod.evaluate(rules, rec.tail_series(),
                                       int(flow.now() * 1000))
            self.slo_verdict = verdict
            if verdict["state"] == "breach" and prev_state != "breach":
                self.slo_breaches += 1
                flow.cover("slo.breach")
                flow.TraceEvent("SLOBreach", self.process.name).detail(
                    Rules=",".join(verdict["breached"])).log()
            prev_state = verdict["state"]

    # -- the latency-forensics plane (ISSUE 18; spawned only armed) ------
    async def _critical_path_loop(self):
        """Fold the proxies' buffered decomposition samples into the
        decaying dominant-station table every CRITICAL_PATH_INTERVAL,
        and refresh the host process's resource sample on the same
        cadence (the status doc serves the latest without re-sampling
        per request)."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.critical_path_interval,
                             TaskPriority.LOW_PRIORITY)
            now = flow.now()
            for p in self._current_proxies():
                for dom, seconds, _e2e in p.path.drain_samples():
                    self.critical_path_table.record(dom, seconds, now)
                    self._path_samples_folded += 1
            if self.host_process_metrics is not None:
                self.host_process_metrics.sample()

    def _current_ratekeeper(self):
        """The current epoch's Ratekeeper role, or None mid-recovery
        (the recorder's rk/* signals read its rate + last decision)."""
        from .ratekeeper import Ratekeeper
        ep = self.dbinfo.get().epoch
        for wi in self.workers.values():
            if not wi.worker.process.alive:
                continue
            for rn, role in wi.worker.roles.items():
                if isinstance(role, Ratekeeper) and rn.endswith(f"-e{ep}"):
                    return role
        return None

    def _health_messages(self, info) -> list:
        """Event-driven health rollup: the status document's `messages`
        array (ref: the messages JSON clusterGetStatus assembles —
        operators and alerting read these, not raw counters). Each
        entry: name, severity, human description, plus the numbers
        behind the judgment. Conditions surfaced: a resolver holding
        more conflict-history rows than its memory limit (the window GC
        is losing to the write rate), a pathological conflict fraction
        over the recent metric-sample window, and storage trailing the
        log frontier by more than a healthy MVCC window."""
        msgs: list = []
        from .resolver_role import Resolver
        ep = info.epoch
        limit = flow.SERVER_KNOBS.resolver_state_memory_limit
        for wi in self.workers.values():
            if not wi.worker.process.alive:
                continue
            for rn, role in wi.worker.roles.items():
                if isinstance(role, Resolver) and f"-e{ep}-" in rn:
                    size = role.state_size()
                    if size > limit:
                        msgs.append({
                            "name": "saturated_resolver",
                            "severity": flow.trace.SevWarnAlways,
                            "description":
                                f"Resolver {rn} holds {size} conflict-"
                                f"history rows (limit {limit})",
                            "resolver": rn, "state_rows": size,
                            "limit": limit})
                    fo = role.failover_stats()
                    if fo and not fo.get("on_primary", True):
                        msgs.append({
                            "name": "conflict_backend_degraded",
                            "severity": flow.trace.SevWarnAlways,
                            "description":
                                f"Resolver {rn} failed over to the "
                                f"{fo.get('active_backend')} backend "
                                f"({fo.get('failovers')} failovers, "
                                f"{fo.get('device_faults')} device "
                                "faults); reattach pending",
                            "resolver": rn,
                            "failovers": fo.get("failovers", 0),
                            "device_faults": fo.get("device_faults", 0)})
                    mismatches = (fo.get("shadow", {}) or {}).get(
                        "mismatches", 0) if fo else 0
                    if mismatches:
                        # the corruption-grade message: shadow verdicts
                        # diverged — serializability is suspect (ref:
                        # how check_consistency reports replica
                        # divergence)
                        msgs.append({
                            "name": "shadow_resolve_mismatch",
                            "severity": flow.trace.SevError,
                            "description":
                                f"Resolver {rn}: {mismatches} sampled "
                                "batches re-resolved on the CPU shadow "
                                "disagreed with the "
                                f"{fo.get('active_backend')} backend",
                            "resolver": rn,
                            "mismatches": mismatches})
        # conflict fraction over the sampled tail (the metric sampler is
        # the event source; status just reads the window)
        conflicted = committed = 0.0
        sampled = False
        for (rn, cn), ts in self.metrics.items():
            if not rn.startswith("proxy"):
                continue
            tail = ts.series(0)
            if len(tail) < 2:
                continue
            delta = tail[-1][1] - tail[0][1]
            if cn == "transactions_conflicted":
                conflicted += max(delta, 0)
                sampled = True
            elif cn == "transactions_committed":
                committed += max(delta, 0)
        total = conflicted + committed
        if sampled and total >= 10 and \
                conflicted / total > flow.SERVER_KNOBS.health_conflict_rate:
            msgs.append({
                "name": "high_conflict_rate",
                "severity": flow.trace.SevWarnAlways,
                "description":
                    f"{conflicted / total:.0%} of recent transactions "
                    "aborted on conflicts (see conflict_hot_spots)",
                "conflict_rate": round(conflicted / total, 4),
                "window_transactions": int(total)})
        frontier = max((t.version.get() for t in self.tlog_objs()),
                       default=0)
        lag_limit = flow.SERVER_KNOBS.health_storage_lag_versions
        behind = []
        for s in info.storages:
            for rep in s.replicas:
                obj = self._storage_objs.get(rep.name)
                if obj is None or not obj.process.alive:
                    continue
                lag = frontier - obj.version.get()
                if lag > lag_limit:
                    behind.append((rep.name, lag))
        for name, lag in behind:
            msgs.append({
                "name": "storage_behind_tlog",
                "severity": flow.trace.SevWarnAlways,
                "description":
                    f"Storage {name} trails the log frontier by "
                    f"{lag} versions",
                "storage": name, "lag_versions": lag})
        # SLO breaches (ISSUE 17): one message per tripped rule while
        # the longitudinal plane is armed and breaching — empty (and
        # free) otherwise, so the off posture's messages are unchanged
        if self.slo_verdict.get("state") == "breach":
            for r in self.slo_verdict.get("rules", ()):
                if r.get("ok"):
                    continue
                msgs.append({
                    "name": "slo_breach",
                    "severity": flow.trace.SevWarnAlways,
                    "description":
                        f"SLO rule {r['name']} breached "
                        f"(value {r.get('value')}, "
                        f"threshold {r.get('threshold')})",
                    "rule": r["name"], "value": r.get("value"),
                    "threshold": r.get("threshold")})
        return msgs

    # -- status ----------------------------------------------------------
    async def _status_loop(self):
        while True:
            _req, reply = await self.status_requests.pop()
            try:
                reply.send(self.get_status())
            except Exception:  # noqa: BLE001 — status must never wedge
                reply.send({"cluster": {"error": "status_incomplete"}})

    def get_status(self) -> dict:
        """Assemble the cluster status document (ref: clusterGetStatus,
        fdbserver/Status.actor.cpp:1802 — the JSON consumed by fdbcli
        `status` and StatusClient). Role stats are read from the
        registry; a real deployment would gather them via RPC."""
        info = self.dbinfo.get()
        cfg = self.config
        workers = {
            name: {"machine": wi.machine,
                   "zone": wi.zone,
                   "dc": wi.dc,
                   "alive": wi.worker.process.alive,
                   "roles": sorted(wi.worker.roles)}
            for name, wi in self.workers.items()}
        logs = []
        for lr in info.logs.logs:
            entry = {"store": lr.store, "machine": lr.machine}
            for wi in self.workers.values():
                obj = wi.worker.roles.get(lr.store)
                if obj is not None:
                    entry.update(
                        durable_version=obj.version.get(),
                        queue_length=len(obj.entries),
                        counters=obj.stats.snapshot(),
                        latency_bands={
                            "commit": obj.commit_bands.snapshot()})
                    if flow.SERVER_KNOBS.critical_path:
                        # queue-vs-service split: version-ordering wait
                        # vs fsync service (ISSUE 18)
                        entry["path"] = obj.path.snapshot()
            logs.append(entry)
        storages = []
        for s in info.storages:
            entry = {"tag": s.tag, "begin": s.begin.hex(),
                     "end": s.end.hex() if s.end is not None else None,
                     "replicas": []}
            for rep in s.replicas:
                rentry = {"name": rep.name}
                obj = self._storage_objs.get(rep.name)
                if obj is not None:
                    rentry.update(alive=obj.process.alive,
                                  version=obj.version.get(),
                                  durable_version=obj.durable_version.get(),
                                  sampled_bytes=obj.sampled_bytes(),
                                  write_bytes_per_sec=round(
                                      obj.write_bandwidth(), 1),
                                  # read-side heat meters (zeros while
                                  # the plane is disarmed — the fields
                                  # stay so dashboards are stable)
                                  read_bytes_per_sec=round(
                                      obj.read_bandwidth(), 1),
                                  read_ops_per_sec=round(
                                      obj.read_ops_rate(), 1),
                                  counters=obj.stats.snapshot(),
                                  latency_bands={
                                      "read": obj.read_bands.snapshot()})
                entry["replicas"].append(rentry)
            storages.append(entry)
        from .proxy import Proxy
        from .ratekeeper import Ratekeeper
        from .resolver_role import Resolver
        path_armed = bool(flow.SERVER_KNOBS.critical_path)
        proxies = []
        resolvers = []
        rate = None
        rk_role = None
        proxy_roles = []
        for wi in self.workers.values():
            for rn, role in wi.worker.roles.items():
                if isinstance(role, Proxy) and f"-e{info.epoch}-" in rn:
                    proxy_roles.append(role)
                    proxies.append({
                        "name": rn,
                        "committed_version": role.committed_version.get(),
                        "counters": role.stats.snapshot(),
                        "latency_bands": {
                            "grv": role.grv_bands.snapshot(),
                            "commit": role.commit_bands.snapshot()},
                        # conflict prediction & repair decision plane
                        # (server/scheduler.py + server/repair.py):
                        # deferral and repair accounting per proxy
                        "scheduler": role.scheduler_status(),
                        "repair": role.repair_status(),
                        # enforced admission control (server/
                        # admission.py): per-class admission counters,
                        # queue bounds, and the live tag-throttle rows
                        "admission": role.admission_status()})
                    if path_armed:
                        # per-proxy critical-path decomposition
                        # (ISSUE 18): station bands, dominant counts,
                        # residual bound — the raw feed behind
                        # cluster.critical_path
                        proxies[-1]["path"] = role.path.snapshot()
                elif isinstance(role, Resolver) and \
                        f"-e{info.epoch}-" in rn:
                    kern = role.kernel_stats()
                    rsnap = role.stats.snapshot()
                    resolvers.append({
                        "name": rn,
                        "version": role.version.get(),
                        "counters": rsnap,
                        # split/merge visibility (ISSUE 15 satellite):
                        # state rows + handoff counters per resolver;
                        # owned_ranges is patched in below from a live
                        # proxy's keyResolvers map
                        "splits": {
                            "state_rows": role.state_size(),
                            "checkpoints_served":
                                rsnap.get("split_checkpoints", 0),
                            "installs": rsnap.get("range_installs", 0),
                            "last_handoff": role.last_handoff},
                        "latency_bands": {
                            "resolve": role.resolve_bands.snapshot()},
                        # decaying conflict-attribution table: which
                        # key ranges are aborting transactions HERE
                        "hot_spots": role.hot_spots.top(),
                        # device-kernel profile: pad occupancy +
                        # compile/execute accounting ({} off-device)
                        "kernel": kern,
                        # split submit/drain resolve-pipeline window:
                        # in-flight depth, forced drains, submit-vs-
                        # drain latency bands (every backend has it;
                        # reuse the snapshot the device kernel stats
                        # already embed rather than recomputing)
                        "pipeline": (kern.get("pipeline")
                                     or role.pipeline_stats()),
                        # backend fault tolerance: checkpoint cadence,
                        # device faults/failovers/replay, shadow
                        # validation ({} for bare host backends)
                        "failover": role.failover_stats()})
                    if path_armed:
                        # queue-vs-service split: version-ordering wait
                        # vs resolve service (ISSUE 18)
                        resolvers[-1]["path"] = role.path.snapshot()
                elif isinstance(role, Ratekeeper) and \
                        rn.endswith(f"-e{info.epoch}"):
                    rate = role.rate
                    rk_role = role
        # per-resolver owned-range counts off a live proxy's
        # keyResolvers map (every proxy applies moves at the same
        # version, so any one is representative)
        if proxy_roles and resolvers:
            owned = proxy_roles[0].key_resolvers.owned_ranges(
                len(resolvers))
            for r in resolvers:
                try:
                    ridx = int(r["name"].rsplit("-", 1)[1])
                except (ValueError, IndexError):
                    continue
                if 0 <= ridx < len(owned):
                    r["splits"]["owned_ranges"] = owned[ridx]
        # cluster-level hot-spot view: merge every resolver's table by
        # range (keyspace-sharded resolvers each see disjoint causes)
        merged_hot: dict = {}
        for r in resolvers:
            for row in r["hot_spots"]:
                ent = merged_hot.setdefault(
                    (row["begin"], row["end"]), {"score": 0.0, "total": 0})
                ent["score"] += row["score"]
                ent["total"] += row["total"]
        hot_rows = [{"begin": b, "end": e,
                     "score": round(v["score"], 4), "total": v["total"]}
                    for (b, e), v in merged_hot.items()]
        hot_rows.sort(key=lambda r: (-r["score"], r["begin"]))
        # the QoS telemetry plane: ratekeeper decision + per-role
        # smoothed saturation signals + tag/priority traffic accounting
        # (ref: the qos section of clusterGetStatus, grown here with
        # the full measurement plane ROADMAP item 3's throttling needs)
        qos_roles: dict = {}
        for s in self.qos_samples.values():
            qos_roles.setdefault(s.kind, {})[s.name] = dict(
                s.signals, sampled_at=round(s.sampled_at, 3))
        merged_tags: dict = {}
        prio_counts: dict = {}
        for p_role in proxy_roles:
            for row in p_role.tag_counter.top():
                ent = merged_tags.setdefault(row["tag"], {
                    "busyness": 0.0, "started": 0, "committed": 0,
                    "conflicted": 0})
                ent["busyness"] += row["busyness"]
                for f in ("started", "committed", "conflicted"):
                    ent[f] += row[f]
            snap = p_role.stats.snapshot()
            for prio in ("batch", "default", "immediate"):
                ent = prio_counts.setdefault(prio, {
                    "started": 0, "committed": 0, "conflicted": 0})
                ent["started"] += snap.get(
                    f"transactions_started_{prio}", 0)
                ent["committed"] += snap.get(
                    f"transactions_committed_{prio}", 0)
                ent["conflicted"] += snap.get(
                    f"transactions_conflicted_{prio}", 0)
        tag_rows = [dict(tag=t, busyness=round(v["busyness"], 4),
                         started=v["started"], committed=v["committed"],
                         conflicted=v["conflicted"])
                    for t, v in merged_tags.items()]
        tag_rows.sort(key=lambda r: (-r["busyness"], r["tag"]))
        decision = dict(rk_role.last_decision) if rk_role is not None \
            else {}
        qos_doc = {
            "transactions_per_second_limit": rate,
            "batch_transactions_per_second_limit":
                rk_role.batch_rate if rk_role is not None else None,
            "limiting_reason": decision.get("limiting_reason", "none"),
            "inputs": decision.get("inputs", {}),
            # the hex tag behind busiest_read_tag_busyness ("" while
            # the heat plane is off or no tagged reads were seen)
            "busiest_read_tag": decision.get("busiest_read_tag", ""),
            "roles": qos_roles,
            "tags": tag_rows[
                :int(flow.SERVER_KNOBS.qos_tag_top_k)],
            "priorities": prio_counts,
        }
        from ..flow import coverage as _coverage
        cov = _coverage.report()
        probe = dict(self._latency_probe)
        if probe:
            # banded history only beside a published round: consumers
            # key on the scalar fields ("if probe: probe[...]"), so a
            # mid-round bands-only dict must not make probe truthy
            bands = {k: v.snapshot()
                     for k, v in self._probe_bands.items()
                     if v.bands.total}
            if bands:
                probe["bands"] = bands
        return {
            "cluster": {
                "epoch": info.epoch,
                "recovery_state": info.recovery_state,
                "recovery_version": info.recovery_version,
                "coordinators": len(self.coordinators),
                "workers": workers,
                "logs": logs,
                "storages": storages,
                "proxies": proxies,
                "resolvers": resolvers,
                # process-global jitted-kernel accounting (compiles,
                # compile-vs-execute time per shape bucket): reported
                # once — the compiled kernels are shared across every
                # backend instance in this process
                "kernels": _global_kernel_counters(),
                "qos": qos_doc,
                # dynamic resolver split/merge rollup (ISSUE 15): the
                # balance loop's split/merge/release/handoff counters
                # and the last split it made — skew response as a
                # status query
                "resolver_balance": self._balance_doc(),
                # conflict prediction & transaction repair rollup:
                # the armed planes, cluster totals across the proxies,
                # and the client-side conflict-window cache counters
                # (process-wide, like client_profile)
                "conflict_scheduling": self._sched_doc(proxies),
                # enforced admission control & tag throttling rollup:
                # armed knobs, per-class admission totals across the
                # proxies, the merged live throttle-row table, the
                # ratekeeper's auto-throttler counters, and the
                # client-side backoff counters (process-wide)
                "admission_control": self._admission_doc(proxies,
                                                         rk_role),
                "latency_probe": probe,
                # the longitudinal plane's rollup (ISSUE 17): SLO
                # verdict + recorder/TimeKeeper accounting while
                # METRIC_HISTORY is armed; {"enabled": 0} otherwise
                "slo": self._slo_doc(),
                # latency forensics (ISSUE 18): the decaying dominant-
                # station table + per-station splits while
                # CRITICAL_PATH is armed; {"enabled": 0} otherwise
                "critical_path": self._critical_path_doc(proxies, logs,
                                                         resolvers),
                # per-process resource telemetry: the host's sampler
                # here; OS-process workers report their own via
                # federation into cluster.processes
                "process_metrics": self._process_metrics_doc(),
                # hottest conflict-causing key ranges, cluster-wide
                # (per-resolver tables under resolvers[*].hot_spots)
                "conflict_hot_spots": hot_rows[
                    :int(flow.SERVER_KNOBS.hot_spot_top_k)],
                # the storage heat plane's rollup (ISSUE 13): decaying
                # top-K read-hot sub-ranges across the storage replicas
                # + the busiest read tag per server — the feature
                # stream ROADMAP items 3 and 5 consume (which shard to
                # split, which tenant to throttle)
                "storage_heat": {
                    "tracking_enabled": int(bool(
                        flow.SERVER_KNOBS.storage_heat_tracking)),
                    "ranges": self.storage_heat.top(),
                    "busiest_read_tags": [
                        {"server": n, "tag": t, "busyness": b}
                        for n, (t, b) in sorted(self._heat_tags.items())],
                },
                # event-driven health rollup (ref: the status document's
                # messages array operators alert on)
                "messages": self._health_messages(info),
                # the chaos plane's shared fault accounting: every
                # injected fault — network ops, disk corruption, kills,
                # PLUS the device-fault injector's seam totals — under
                # one schema, so "did the storm actually fire" is a
                # status query, not a trace grep (server/chaos.py)
                "chaos": _chaos_status(self.process.net),
                # TEST() coverage summary (ref: the coverage tool over
                # annotated rare paths; full dump rides the CI artifact)
                "coverage": {"declared": len(cov["declared"]),
                             "hit": len(cov["hit"]),
                             "unhit": cov["unhit"]},
                # multi-resolution counter time series (ref: TDMetric):
                # newest sample + a short fine-grained tail per metric
                "metrics": {
                    f"{rn}/{cn}": {
                        "latest": ts.latest(),
                        "tail": ts.series(0)[-5:],
                        "levels": [len(lv) for lv in ts.levels],
                        "gauge": (rn, cn) in self._metric_gauges,
                    }
                    for (rn, cn), ts in sorted(self.metrics.items())},
                # run-loop profiler (ref: Net2 slow-task sampling /
                # SystemMonitor machine metrics in status) + the
                # SIM_TASK_STATS attribution table when armed
                "run_loop": _run_loop_status(),
                # sim-network message accounting (the plane's network
                # half): per-request-type counts when armed, plus the
                # always-available population gauges and totals
                "network": self.process.net.message_stats_report(
                    top_k=int(flow.SERVER_KNOBS.sim_task_stats_top_k)),
                # sampled-transaction profiler counters (process-wide,
                # like the kernel profile: every client in this sim
                # shares the sampler's CounterCollection)
                "client_profile": _client_profile_counters(),
                "configuration": {
                    "proxies": cfg.n_proxies,
                    "resolvers": cfg.n_resolvers,
                    "logs": cfg.n_logs,
                    "storage_shards": cfg.n_storage,
                    "conflict_backend": cfg.conflict_backend,
                    "durable": cfg.durable,
                    "excluded": sorted(self.excluded),
                },
            },
        }

    def _slo_doc(self) -> dict:
        """status.cluster.slo: the engine's latest verdict + the
        recorder's and TimeKeeper's accounting."""
        enabled = int(bool(flow.SERVER_KNOBS.metric_history))
        if not enabled:
            return {"enabled": 0}
        return {
            "enabled": 1,
            "state": self.slo_verdict.get("state", "ok"),
            "breached": self.slo_verdict.get("breached", []),
            "breaches": self.slo_breaches,
            "rules": self.slo_verdict.get("rules", []),
            "recorder": (self.metric_recorder.status()
                         if self.metric_recorder is not None else {}),
            "timekeeper_rows": self._timekeeper_rows,
        }

    def _critical_path_doc(self, proxies: list, logs: list,
                           resolvers: list) -> dict:
        """status.cluster.critical_path: the decaying top-cause table
        plus a cluster-wide fold of the per-role path sections already
        assembled for this status doc (proxy station segments, and the
        resolver/tlog queue-vs-service splits)."""
        if not flow.SERVER_KNOBS.critical_path or \
                self.critical_path_table is None:
            return {"enabled": 0}
        from .critical_path import STATIONS
        samples = 0
        max_residual = 0.0
        dominant = {s: 0 for s in STATIONS}
        station_seconds = {s: 0.0 for s in STATIONS}
        for p in proxies:
            path = p.get("path") or {}
            samples += path.get("samples", 0)
            max_residual = max(max_residual,
                               path.get("max_residual_seconds", 0.0))
            for s, n in (path.get("dominant") or {}).items():
                dominant[s] = dominant.get(s, 0) + n
            for s, ent in (path.get("stations") or {}).items():
                station_seconds[s] = (station_seconds.get(s, 0.0)
                                      + ent.get("seconds", 0.0))

        def _split(entries):
            wait = {"total": 0, "sum_seconds": 0.0}
            service = {"total": 0, "sum_seconds": 0.0}
            for e in entries:
                path = e.get("path") or {}
                for kind, acc in (("wait", wait), ("service", service)):
                    snap = path.get(kind) or {}
                    acc["total"] += snap.get("total", 0)
                    acc["sum_seconds"] += snap.get("sum_seconds", 0.0)
            wait["sum_seconds"] = round(wait["sum_seconds"], 6)
            service["sum_seconds"] = round(service["sum_seconds"], 6)
            return {"wait": wait, "service": service}

        top = self.critical_path_table.top()
        return {
            "enabled": 1,
            "samples": samples,
            "samples_folded": self._path_samples_folded,
            "max_residual_seconds": round(max_residual, 9),
            "tolerance": flow.SERVER_KNOBS.critical_path_tolerance,
            "dominant": dominant,
            "dominant_now": top[0]["station"] if top else None,
            "top": top,
            "station_seconds": {s: round(v, 6)
                                for s, v in station_seconds.items()},
            # queue-vs-service from the serving side: did the time go
            # to version-ordering (upstream pressure) or to the work
            "splits": {"resolve": _split(resolvers),
                       "tlog_fsync": _split(logs)},
        }

    def _process_metrics_doc(self) -> dict:
        """status.cluster.process_metrics: the host process's latest
        resource sample (per-OS-process docs federate into
        cluster.processes, tools/exporter.py)."""
        if not flow.SERVER_KNOBS.critical_path or \
                self.host_process_metrics is None:
            return {"enabled": 0}
        return {"enabled": 1,
                "interval": flow.SERVER_KNOBS.process_metrics_interval,
                "host": dict(self.host_process_metrics.latest),
                "role_cpu_share": self._role_cpu_share()}

    def _role_cpu_share(self) -> dict:
        """Per-role CPU share inside this host process, folded from the
        SIM_TASK_STATS busy table when armed ({} otherwise) — the
        proxy-vs-resolver number ROADMAP item 2 is judged against."""
        from .process_metrics import role_cpu_share
        rl = _run_loop_status()
        return role_cpu_share((rl.get("task_stats") or {}).get("tasks"))

    def _balance_doc(self) -> dict:
        """status.cluster.resolver_balance: knob posture + the balance
        loop's event counters + the last split made."""
        snap = self.balance_stats.snapshot()
        return {
            "enabled": int(bool(flow.SERVER_KNOBS.resolver_balance)),
            "splits": snap.get("splits", 0),
            "merges": snap.get("merges", 0),
            "releases": snap.get("releases", 0),
            "handoff_timeouts": snap.get("handoff_timeouts", 0),
            "last_split": self.balance_last,
        }

    @staticmethod
    def _admission_doc(proxies: list, rk_role) -> dict:
        """status.cluster.admission_control: knob posture + totals over
        the per-proxy admission sections + the merged throttle table +
        the ratekeeper auto-throttler + client backoff counters."""
        from .tag_throttler import client_throttle_counters
        k = flow.SERVER_KNOBS
        totals = {"admitted": {"immediate": 0, "default": 0, "batch": 0},
                  "queued_now": 0, "rejected": 0, "timed_out": 0,
                  "throttle_delayed": 0, "throttle_released": 0,
                  "throttle_rejected": 0, "confirm_rounds": 0}
        rows: dict = {}
        for p in proxies:
            a = p.get("admission") or {}
            for cls, n in (a.get("admitted") or {}).items():
                totals["admitted"][cls] = totals["admitted"].get(cls,
                                                                 0) + n
            totals["queued_now"] += sum((a.get("queued") or {}).values())
            for f in ("rejected", "timed_out", "throttle_delayed",
                      "throttle_released", "throttle_rejected",
                      "confirm_rounds"):
                totals[f] += a.get(f, 0)
            for r in a.get("tag_rows", ()):
                # every proxy enforces the same durable rows; keep the
                # freshest picture per tag
                if r["tag"] not in rows or \
                        r["expiry"] > rows[r["tag"]]["expiry"]:
                    rows[r["tag"]] = dict(r)
        return {
            "grv_admission_enabled": int(bool(k.grv_admission_control)),
            "tag_throttling_enabled": int(bool(k.tag_throttling)),
            "auto_tag_throttling_enabled": int(
                bool(k.auto_tag_throttling)),
            **totals,
            "throttled_tags": sorted(rows.values(),
                                     key=lambda r: r["tag"]),
            "auto_throttler": (rk_role.throttler.status()
                               if rk_role is not None else {}),
            "client": client_throttle_counters(),
        }

    @staticmethod
    def _sched_doc(proxies: list) -> dict:
        """status.cluster.conflict_scheduling: knob posture + totals
        over the per-proxy scheduler/repair sections + the client
        early-abort counters."""
        from .scheduler import client_window_counters
        k = flow.SERVER_KNOBS
        totals = {"deferrals": 0, "released": 0, "overflow": 0,
                  "deferred_now": 0, "repair_attempts": 0,
                  "repair_committed": 0, "repair_conflicted": 0,
                  "repair_fallbacks": 0}
        for p in proxies:
            s = p.get("scheduler") or {}
            r = p.get("repair") or {}
            totals["deferrals"] += s.get("deferrals", 0)
            totals["released"] += s.get("released", 0)
            totals["overflow"] += s.get("overflow", 0)
            totals["deferred_now"] += s.get("deferred_now", 0)
            totals["repair_attempts"] += r.get("attempts", 0)
            totals["repair_committed"] += r.get("committed", 0)
            totals["repair_conflicted"] += r.get("conflicted", 0)
            totals["repair_fallbacks"] += r.get("fallbacks", 0)
        return {
            "scheduling_enabled": int(bool(k.conflict_scheduling)),
            "repair_enabled": int(bool(k.txn_repair)),
            "client_windows_enabled": int(bool(k.client_conflict_windows)),
            **totals,
            "client": client_window_counters(),
        }

    # -- data distribution (ref: DataDistribution + MoveKeys) ------------
    async def _dd_loop(self):
        """Shift shard boundaries toward balanced row counts (ref:
        dataDistributionTracker splitting on size +
        dataDistributionQueue scheduling moveKeys). One move at a time;
        only when the cluster is healthy."""
        while True:
            await flow.delay(flow.SERVER_KNOBS.dd_poll_interval,
                             TaskPriority.DATA_DISTRIBUTION)
            info = self.dbinfo.get()
            if info.recovery_state != FULLY_RECOVERED or self._move_inflight:
                continue
            # exclusion-driven vacates first: data must leave excluded
            # workers before balance moves matter (ref: the exclusion
            # check in dataDistribution — removeKeysFromFailedServers /
            # teams containing excluded servers get rebuilt)
            if await self._vacate_excluded(info):
                continue
            # team health: a team missing a replica past the rebuild
            # delay gets a replacement built from a live teammate
            if await self._heal_unhealthy_teams(info):
                continue
            teams = [[self._storage_objs.get(rep.name)
                      for rep in s.replicas] for s in info.storages]
            if any(o is None or not o.process.alive or o._adding
                   for team in teams for o in team):
                continue
            objs0 = [self._storage_objs.get(s.replicas[0].name)
                     for s in info.storages]
            if (len(info.storages) > self.config.n_storage and info.proxies
                    and all(o is not None for o in objs0)):
                # post-split watch state: row counts only reflect
                # reality once the storages SETTLE — pending un-durable
                # mutations folded in and the MVCC window drained — and
                # both only advance with commits. Nudge until settled,
                # then the cluster goes fully quiet again.
                if any(o._pending or o.data._keys for o in objs0):
                    await self._nudge_commit()
            objs = [team[0] for team in teams]   # per-shard spokesman
            counts = [o.sampled_bytes() for o in objs]
            from ..flow import SERVER_KNOBS as _K
            # split a hot shard: too many sampled bytes OR sustained
            # write bandwidth past the per-shard ceiling (ref:
            # shardSplitter on getStorageMetrics bytes +
            # SHARD_MAX_BYTES_PER_KSEC bandwidth splits)
            hot = [i for i, n in enumerate(counts)
                   if (n > _K.dd_shard_split_bytes
                       or objs[i].write_bandwidth() * 1000.0
                       > _K.dd_shard_split_bytes_per_ksec)
                   # splittable only: a one-key hotspot has no interior
                   # split point — retrying would livelock DD and
                   # starve merges/balance moves
                   and objs[i].split_key_estimate() is not None]
            if hot:
                try:
                    await self._split_shard(hot[0])
                except Exception as e:  # noqa: BLE001 — DD survives
                    flow.TraceEvent(
                        "ShardSplitError", self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
                        Error=repr(e)).log()
                continue
            # merge adjacent cold shards — never below the configured
            # baseline count (ref: shardMerger; SHARD_MIN_BYTES floor)
            cold = [i for i in range(len(counts) - 1)
                    if counts[i] + counts[i + 1] < _K.dd_shard_merge_bytes]
            if cold and len(info.storages) > self.config.n_storage:
                try:
                    await self._merge_shards(cold[0])
                except Exception as e:  # noqa: BLE001 — DD survives
                    flow.TraceEvent(
                        "ShardMergeError", self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
                        Error=repr(e)).log()
                continue
            if len(info.storages) < 2:
                continue
            for i in range(len(objs) - 1):
                big, small = counts[i], counts[i + 1]
                src, direction = (i, "right") if big > small else (i + 1,
                                                                   "left")
                hi, lo = max(big, small), min(big, small)
                if hi < _K.dd_min_balance_bytes or hi <= 2 * lo:
                    continue
                split = objs[src].split_key_estimate()
                if split is None:
                    continue
                # moving [split, src.end) right, or [src.begin, split)
                # left — only when the split lands strictly inside src
                s_begin = objs[src].shard_begin
                s_end = objs[src].shard_end
                if not (split > s_begin
                        and (s_end is None or split < s_end)):
                    continue
                try:
                    await self._move_boundary(i, direction, split)
                except Exception as e:  # noqa: BLE001 — DD must survive
                    flow.TraceEvent(
                        "MoveKeysError", self.process.name,
                        severity=flow.trace.SevWarnAlways).detail(
                        Error=repr(e)).log()
                break

    def _worker_of_role(self, role_name: str):
        for name, wi in self.workers.items():
            if role_name in wi.worker.roles:
                return name, wi
        return None, None

    async def _heal_unhealthy_teams(self, info) -> bool:
        """Team-health tracking (ref: DDTeamCollection,
        DataDistribution.actor.cpp:539 — teams are continuously
        monitored; a team below its replication target is rebuilt).
        A dead replica is given DD_TEAM_REBUILD_DELAY to come back (the
        auto-reboot path); past that, a fresh replica is built from a
        live teammate with the same fetchKeys machinery exclusion
        vacates use. Returns True when a rebuild ran this tick."""
        now = flow.now()
        healthy_tags = set()
        acted = False
        frontier = max((t.version.get() for t in self.tlog_objs()),
                       default=0)
        for si, shard in enumerate(info.storages):
            dead = []
            for rep in shard.replicas:
                obj = self._storage_objs.get(rep.name)
                if obj is None or not obj.process.alive:
                    # reset the stuck clock: time spent DEAD must not
                    # count as "no progress", or a rebooted replica
                    # gets rebuilt as stuck before it can catch up
                    self._replica_progress.pop(rep.name, None)
                    dead.append(rep.name)
                    continue
                # STUCK detection: alive, far behind the frontier, and
                # making no progress — e.g. it recovered at a version
                # whose covering log generation retired while it was
                # down; only a rebuild can bring it back
                v = obj.version.get()
                last_v, since = self._replica_progress.get(
                    rep.name, (None, now))
                if v != last_v:
                    self._replica_progress[rep.name] = (v, now)
                elif (frontier - v >
                        flow.SERVER_KNOBS.dd_replica_stuck_versions
                        and now - since >
                        flow.SERVER_KNOBS.dd_team_rebuild_delay):
                    flow.cover("dd.replica_stuck")
                    dead.append(rep.name)
            if not dead:
                healthy_tags.add(shard.tag)
                continue
            live = [rep for rep in shard.replicas
                    if rep.name not in dead]
            if not live:
                # total team loss: only a disk-recovering reboot can
                # bring the data back — nothing to copy from. Keep the
                # grace FRESH: when replicas start reappearing, the
                # remaining dead ones get a full grace window again
                # (a stale timer would rebuild over a reboot in flight)
                self._team_unhealthy_since[shard.tag] = now
                continue
            first = self._team_unhealthy_since.setdefault(shard.tag, now)
            if now - first < flow.SERVER_KNOBS.dd_team_rebuild_delay:
                continue
            flow.cover("dd.team_rebuild")
            flow.TraceEvent("TeamUnhealthyRebuild",
                            self.process.name).detail(
                Tag=shard.tag, Dead=dead[0],
                DegradedSeconds=round(now - first, 1)).log()
            try:
                await self._replace_replica(si, dead[0])
                self._team_unhealthy_since.pop(shard.tag, None)
                acted = True
            except Exception as e:  # noqa: BLE001 — DD survives
                flow.TraceEvent(
                    "TeamRebuildError", self.process.name,
                    severity=flow.trace.SevWarnAlways).detail(
                    Tag=shard.tag, Error=repr(e)).log()
                # re-arm the grace so a stuck rebuild (e.g. no eligible
                # destination yet) retries without a hot loop
                self._team_unhealthy_since[shard.tag] = \
                    now - flow.SERVER_KNOBS.dd_team_rebuild_delay / 2
            break   # one rebuild attempt per tick
        # stale timers: healed teams AND tags retired by merges
        live_tags = {s.tag for s in info.storages}
        for tag in list(self._team_unhealthy_since):
            if tag in healthy_tags or tag not in live_tags:
                del self._team_unhealthy_since[tag]
        current = {rep.name for s in info.storages for rep in s.replicas}
        for n in [n for n in self._replica_progress if n not in current]:
            del self._replica_progress[n]
        return acted

    async def _vacate_excluded(self, info) -> bool:
        """Move one storage replica off an excluded worker (ref:
        exclusion handling in DataDistribution — a team containing an
        excluded server is unhealthy; its data is re-replicated onto an
        included server, then the old server is removed). Returns True
        when a vacate ran (or was attempted) this tick."""
        if flow.now() < self._vacate_retry_at:
            return False
        for si, shard in enumerate(info.storages):
            for rep in shard.replicas:
                wname, _wi = self._worker_of_role(rep.name)
                if wname is not None and wname in self.excluded:
                    try:
                        await self._replace_replica(si, rep.name)
                        return True
                    except Exception as e:  # noqa: BLE001 — DD survives
                        flow.TraceEvent(
                            "VacateExcludedError", self.process.name,
                            severity=flow.trace.SevWarnAlways).detail(
                            Replica=rep.name, Error=repr(e)).log()
                        # back off a stuck vacate (e.g. no eligible
                        # destination) so balance moves aren't starved
                        # by a 2s retry storm
                        self._vacate_retry_at = flow.now() + 30.0
                        return False
        return False

    async def _replace_replica(self, shard_idx: int, old_name: str) -> None:
        """Re-home one replica of a shard onto an included worker: the
        whole-shard fetchKeys — recruit (buffering from the log), add
        the newcomer to every TLog's expected set so its records are
        pinned, snapshot from a live teammate, install, publish the
        swapped team, retire the old role (ref: MoveKeys.actor.cpp
        startMoveKeys/finishMoveKeys over a full server team change)."""
        info = self.dbinfo.get()
        shard = info.storages[shard_idx]
        epoch0 = info.epoch
        team_workers = {self._worker_of_role(rep.name)[0]
                        for rep in shard.replicas}
        # destination: included, live, not already hosting this shard;
        # the replacement must leave a team the replication policy
        # validates (ref: DDTeamCollection rebuilding through the
        # configured storagePolicy, DataDistribution.actor.cpp:539) —
        # candidates producing a policy-violating team are skipped
        cands = [wi for name, wi in self.workers.items()
                 if wi.worker.process.alive and name not in self.excluded
                 and name not in team_workers]
        if not cands:
            raise error("no_more_servers")
        pol, strict = self.storage_policy(len(shard.replicas))
        keep_locs = [self._locality_of(self.workers[w])
                     for rep in shard.replicas if rep.name != old_name
                     for w in [self._worker_of_role(rep.name)[0]]
                     if w in self.workers]
        fits = []
        if len(keep_locs) == len(shard.replicas) - 1:
            fits = [wi for wi in cands
                    if pol.validate(keep_locs + [self._locality_of(wi)])]
            if not fits and strict:
                raise error("no_more_servers")
        # teammates unresolvable (e.g. their workers rebooted with
        # empty role sets) or no policy-fitting candidate: degrade
        # like recruitment does — prefer at least a fresh zone over a
        # doubled-up one, never wedge the heal
        if not fits:
            team_zones = {self.workers[w].zone
                          for w in team_workers if w in self.workers}
            fits = [wi for wi in cands
                    if wi.zone not in team_zones] or cands
        dst_wi = fits[self._rr % len(fits)]
        self._rr += 1
        # source: a LIVE teammate (the excluded server may itself be the
        # only live copy — exclusion is not death)
        src = None
        for rep in shard.replicas:
            obj = self._storage_objs.get(rep.name)
            if obj is not None and obj.process.alive and \
                    rep.name != old_name:
                src = obj
                break
        if src is None:
            src = self._storage_objs.get(old_name)
        if src is None or not src.process.alive:
            raise error("no_more_servers")
        self._move_inflight = True
        self._vacate_seq += 1
        new_name = f"storage-{shard.tag}-v{self._vacate_seq}"
        try:
            # pin the tag's records for the newcomer BEFORE it exists:
            # teammates' pops must not free records it will still need
            for t in self.tlog_objs():
                exp = dict(t.expected_replicas)
                exp[shard.tag] = tuple(exp.get(shard.tag, ())) + (new_name,)
                t.set_expected_replicas(exp)
            refs = dst_wi.worker.recruit_storage(
                new_name, shard.tag, shard.begin, shard.end)
            new_obj = dst_wi.worker.roles[new_name]
            # same-turn: nothing can have been applied yet — buffer all
            # in-range mutations until the snapshot lands
            new_obj.begin_adding(shard.begin, shard.end)
            flow.TraceEvent("VacateReplicaStart", self.process.name).detail(
                Old=old_name, New=new_name, Worker=dst_wi.name).log()
            # the newcomer's engine must finish recovering before a
            # durable install can land on it
            await flow.timeout_error(
                new_obj.recovered,
                flow.SERVER_KNOBS.storage_recruit_recovery_timeout)
            v_s = min(src.known_committed, src.version.get())
            rows = src.snapshot_range(shard.begin, shard.end, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            await new_obj.install_snapshot(rows, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            # publish the swapped team — the commit point
            info2 = self.dbinfo.get()
            shards = list(info2.storages)
            shards[shard_idx] = shard._replace(replicas=tuple(
                refs if rep.name == old_name else rep
                for rep in shards[shard_idx].replicas))
            self._storage_objs[new_name] = new_obj
            self.shard_map[new_name] = (shard.tag, shard.begin, shard.end)
            self.shard_map.pop(old_name, None)
            self.publish(info2._replace(storages=tuple(shards)))
            # release the old replica's pin; keep the newcomer's
            for t in self.tlog_objs():
                exp = dict(t.expected_replicas)
                exp[shard.tag] = tuple(
                    n for n in exp.get(shard.tag, ()) if n != old_name)
                t.set_expected_replicas(exp)
            old_wname, old_wi = self._worker_of_role(old_name)
            self._storage_objs.pop(old_name, None)
            if old_wi is not None:
                old_wi.worker.retire_storage(old_name)
            flow.TraceEvent("VacateReplicaFinish", self.process.name).detail(
                Old=old_name, New=new_name).log()
        except BaseException:
            # roll back the newcomer: drop ITS pin (prior successful
            # vacates' replicas keep theirs) and its half-built role
            for t in self.tlog_objs():
                exp = dict(t.expected_replicas)
                exp[shard.tag] = tuple(
                    n for n in exp.get(shard.tag, ()) if n != new_name)
                t.set_expected_replicas(exp)
            if new_name not in self.shard_map:
                wname, wi = self._worker_of_role(new_name)
                if wi is not None:
                    wi.worker.retire_storage(new_name)
            raise
        finally:
            self._move_inflight = False

    async def _split_shard(self, shard_idx: int) -> None:
        """Split a hot shard: mint a fresh tag, recruit a policy-spread
        team for the upper half, dual-tag it through the transition,
        snapshot + buffered-replay onto the newcomers, publish the
        extra shard (ref: dataDistributionTracker shardSplitter →
        executing moveKeys to a new team; the keyServers map gains a
        boundary)."""
        info = self.dbinfo.get()
        shard = info.storages[shard_idx]
        epoch0 = info.epoch
        src_team = [self._storage_objs.get(rep.name)
                    for rep in shard.replicas]
        if any(o is None or not o.process.alive for o in src_team):
            raise error("operation_failed")
        src = src_team[0]
        split = src.split_key_estimate()
        if split is None or not (shard.begin < split and (
                shard.end is None or split < shard.end)):
            raise error("operation_failed")
        # tags are NEVER reused within a CC lifetime: a merged-away
        # tag's force-pops (1<<60) persist on the epoch's tlogs and
        # would instantly free a re-minted tag's records
        self._max_tag_ever = max(self._max_tag_ever,
                                 max(s.tag for s in info.storages))
        self._max_tag_ever += 1
        new_tag = self._max_tag_ever
        nrep = max(1, self.config.storage_replicas)
        pol, strict = self.storage_policy(nrep)
        team = self.pick_workers(nrep, role="storage", policy=pol,
                                 strict=strict)
        # names follow the team actually built — a mismatched policy
        # must never pin phantom replica names into the tlogs
        names = [f"storage-{new_tag}-r{j}" for j in range(len(team))]
        proxies = self._current_proxies()
        if not proxies:
            raise error("operation_failed")
        self._move_inflight = True
        flow.TraceEvent("ShardSplitStart", self.process.name).detail(
            Tag=shard.tag, NewTag=new_tag, Split=split.hex()).log()
        new_refs = []
        dual_tagged = False
        published = False
        try:
            # pin the fresh tag before any record can exist for it
            for t in self.tlog_objs():
                exp = dict(t.expected_replicas)
                exp[new_tag] = tuple(names)
                t.set_expected_replicas(exp)
            new_objs = []
            for j, w in enumerate(team):
                refs = w.recruit_storage(names[j], new_tag, split,
                                         shard.end)
                obj = w.roles[names[j]]
                obj.begin_adding(split, shard.end)  # same-turn: no gap
                new_refs.append(refs)
                new_objs.append(obj)
            for p in proxies:
                p.start_move(split, shard.end, new_tag)
            dual_tagged = True
            for o in new_objs:
                await flow.timeout_error(
                    o.recovered,
                    flow.SERVER_KNOBS.storage_recruit_recovery_timeout)
            v_s = await self._wait_replication_horizon(src, epoch0, proxies)
            rows = src.snapshot_range(split, shard.end, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            for o in new_objs:
                await o.install_snapshot(rows, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            # publish: the commit point
            info2 = self.dbinfo.get()
            shards = list(info2.storages)
            left = shard._replace(end=split, replicas=tuple(
                rep._replace(end=split) for rep in shard.replicas))
            right = StorageShard(new_tag, split, shard.end, tuple(
                r._replace(begin=split, end=shard.end) for r in new_refs))
            shards[shard_idx] = left
            shards.insert(shard_idx + 1, right)
            for rep in left.replicas:
                self.shard_map[rep.name] = (left.tag, left.begin, left.end)
            for j, rep in enumerate(right.replicas):
                self.shard_map[rep.name] = (new_tag, split, shard.end)
                self._storage_objs[rep.name] = new_objs[j]
            self.publish(info2._replace(storages=tuple(shards)))
            published = True   # the commit point: only roll forward
            for p in self._current_proxies():
                p.finish_move(split, shard.end, new_tag,
                              [s.begin for s in shards[1:]],
                              [s.tag for s in shards])
            for sobj in src_team:
                try:
                    await sobj.shrink_to(sobj.shard_begin, split)
                except flow.FdbError:
                    pass  # a dead replica is clamped on re-register
            flow.TraceEvent("ShardSplitFinish", self.process.name).detail(
                NewTag=new_tag).log()
        except BaseException:
            if not published:
                if dual_tagged:
                    for p in self._current_proxies():
                        p.finish_move(split, shard.end, new_tag,
                                      [s.begin for s in info.storages[1:]],
                                      [s.tag for s in info.storages])
                for t in self.tlog_objs():
                    exp = dict(t.expected_replicas)
                    exp.pop(new_tag, None)
                    t.set_expected_replicas(exp)
                    # commits dual-tagged during the aborted split would
                    # otherwise pin log records for the rest of the epoch
                    t.pop(1 << 60, new_tag, "split-aborted")
                for j, w in enumerate(team[:len(new_refs)]):
                    w.retire_storage(names[j])
                    self._storage_objs.pop(names[j], None)
            raise
        finally:
            self._move_inflight = False

    async def _merge_shards(self, left_idx: int) -> None:
        """Fold shard left_idx+1 into left_idx: the left team absorbs
        the right range (dual-tagged through the transition), the right
        team and its tag retire (ref: dataDistributionTracker
        shardMerger — adjacent cold shards collapse to one)."""
        info = self.dbinfo.get()
        left, right = info.storages[left_idx], info.storages[left_idx + 1]
        epoch0 = info.epoch
        l_team = [self._storage_objs.get(rep.name) for rep in left.replicas]
        r_team = [self._storage_objs.get(rep.name) for rep in right.replicas]
        if any(o is None or not o.process.alive for o in l_team + r_team):
            raise error("operation_failed")
        src = r_team[0]
        proxies = self._current_proxies()
        if not proxies:
            raise error("operation_failed")
        self._move_inflight = True
        flow.TraceEvent("ShardMergeStart", self.process.name).detail(
            Left=left.tag, Right=right.tag).log()
        published = False
        l_old_bounds = [(o.shard_begin, o.shard_end) for o in l_team]
        try:
            for o in l_team:
                o.begin_adding(right.begin, right.end)
            for p in proxies:
                p.start_move(right.begin, right.end, left.tag)
            v_s = await self._wait_replication_horizon(src, epoch0, proxies)
            rows = src.snapshot_range(right.begin, right.end, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            for o in l_team:
                await o.install_snapshot(rows, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            # publish the collapsed map
            info2 = self.dbinfo.get()
            shards = list(info2.storages)
            merged = left._replace(end=right.end, replicas=tuple(
                rep._replace(end=right.end) for rep in left.replicas))
            shards[left_idx] = merged
            del shards[left_idx + 1]
            for rep in merged.replicas:
                self.shard_map[rep.name] = (merged.tag, merged.begin,
                                            merged.end)
            for rep in right.replicas:
                self.shard_map.pop(rep.name, None)
            self.publish(info2._replace(storages=tuple(shards)))
            published = True
            for p in self._current_proxies():
                p.finish_move(right.begin, right.end, left.tag,
                              [s.begin for s in shards[1:]],
                              [s.tag for s in shards])
            # retire the right team; its tag's residual records are
            # covered by the left tag's copies, so pop them fully or
            # the log would pin them forever
            for rep in right.replicas:
                wname, wi = self._worker_of_role(rep.name)
                self._storage_objs.pop(rep.name, None)
                if wi is not None:
                    wi.worker.retire_storage(rep.name)
            for t in self.tlog_objs():
                exp = dict(t.expected_replicas)
                expected = exp.pop(right.tag, ())
                t.set_expected_replicas(exp)
                for name in expected:
                    t.pop(1 << 60, right.tag, name)
            flow.TraceEvent("ShardMergeFinish", self.process.name).detail(
                Tag=merged.tag).log()
        except BaseException:
            if not published:
                for o, old in zip(l_team, l_old_bounds):
                    o.abort_adding()
                    if (o.shard_begin, o.shard_end) != old:
                        # a durable install already extended the claim:
                        # retract it (floor + rows stay, unreachable)
                        await flow.catch_errors(flow.spawn(
                            o.set_bounds(*old)))
                for p in self._current_proxies():
                    p.finish_move(right.begin, right.end, left.tag,
                                  [s.begin for s in info.storages[1:]],
                                  [s.tag for s in info.storages])
            raise
        finally:
            self._move_inflight = False

    async def _nudge_commit(self) -> None:
        """Push one empty commit through — idle clusters advance
        known_committed (and thus durability) only with fresh commits
        (ref: the recovery txn idiom)."""
        from .types import CommitRequest
        info = self.dbinfo.get()
        if info.proxies:
            await flow.catch_errors(flow.timeout_error(
                info.proxies[0].commits.get_reply(
                    CommitRequest(0, (), (), ()), self.process), 1.0))

    async def _wait_replication_horizon(self, src, epoch0: int,
                                        proxies) -> int:
        """Safe snapshot version for a move source: at least v0 — the
        master's issued max, covering batches whose tags were computed
        BEFORE a dual-tag landed — and known replicated on the whole
        log set, so an epoch rollback can never rewind below it and a
        durable install can't capture a phantom timeline. `proxies` is
        the caller's already-validated non-empty list (re-fetching here
        could observe an epoch transition's empty window)."""
        v0 = max(p.committed_version.get() for p in proxies)
        if self._recovery is not None and \
                self._recovery.master is not None:
            v0 = max(v0, self._recovery.master.version)
        deadline = flow.now() + 30.0
        while src.known_committed < v0 or src.version.get() < v0:
            if flow.now() > deadline:
                raise error("timed_out")
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")
            await self._nudge_commit()
            await flow.delay(flow.SERVER_KNOBS.dd_move_nudge_interval,
                             TaskPriority.DATA_DISTRIBUTION)
        return min(src.known_committed, src.version.get())

    async def _move_boundary(self, left_idx: int, direction: str,
                             split: bytes) -> None:
        """Move the boundary between adjacent shards left_idx and
        left_idx+1 to `split` (ref: moveKeys start/finish + fetchKeys).
        Sequence: destination buffers; proxies dual-tag; destination
        backfills a snapshot the source serves at its own version;
        ownership flips durably on the destination, is published, and
        only then do proxies drop the dual tag and the source shrink."""
        info = self.dbinfo.get()
        storages = info.storages
        if direction == "right":
            src_i, dst_i = left_idx, left_idx + 1
            r_begin, r_end = split, storages[dst_i].begin
        else:
            src_i, dst_i = left_idx + 1, left_idx
            r_begin, r_end = storages[src_i].begin, split
        src_team = [self._storage_objs[rep.name]
                    for rep in storages[src_i].replicas]
        dst_team = [self._storage_objs[rep.name]
                    for rep in storages[dst_i].replicas]
        src = src_team[0]
        dst = dst_team[0]
        dst_old_bounds = (dst.shard_begin, dst.shard_end)
        proxies = self._current_proxies()
        if not proxies:
            return
        epoch0 = info.epoch
        self._move_inflight = True
        flow.TraceEvent("MoveKeysStart", self.process.name).detail(
            Begin=r_begin.hex(), End=r_end.hex(),
            Src=storages[src_i].replicas[0].name,
            Dst=storages[dst_i].replicas[0].name).log()
        published = False
        try:
            for d in dst_team:
                d.begin_adding(r_begin, r_end)
            for p in proxies:
                p.start_move(r_begin, r_end, dst.tag)
            v_s = await self._wait_replication_horizon(src, epoch0, proxies)
            rows = src.snapshot_range(r_begin, r_end, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")   # abort pre-install
            for d in dst_team:
                await d.install_snapshot(rows, v_s)
            if self.dbinfo.get().epoch != epoch0:
                raise error("operation_failed")   # abort pre-publish
            # publish: THE commit point — from here the move only rolls
            # forward (a revert after publish would diverge routing from
            # the advertised map; code review r3)
            new_storages = []
            for j, s in enumerate(storages):
                if j == dst_i:
                    ns = (s._replace(begin=split) if direction == "right"
                          else s._replace(end=split))
                elif j == src_i:
                    ns = (s._replace(end=split) if direction == "right"
                          else s._replace(begin=split))
                else:
                    ns = s
                ns = ns._replace(replicas=tuple(
                    rep._replace(begin=ns.begin, end=ns.end)
                    for rep in ns.replicas))
                new_storages.append(ns)
            for s in new_storages:
                for rep in s.replicas:
                    self.shard_map[rep.name] = (s.tag, s.begin, s.end)
            self.publish(self.dbinfo.get()._replace(
                storages=tuple(new_storages)))
            published = True
            for p in self._current_proxies():
                p.finish_move(r_begin, r_end, dst.tag,
                              [s.begin for s in new_storages[1:]],
                              [s.tag for s in new_storages])
            for sobj in src_team:
                try:
                    if direction == "right":
                        await sobj.shrink_to(sobj.shard_begin, split)
                    else:
                        await sobj.shrink_to(split, sobj.shard_end)
                except flow.FdbError:
                    pass  # a dead replica is clamped on re-register
            flow.TraceEvent("MoveKeysFinish", self.process.name).detail(
                Split=split.hex()).log()
        except BaseException:
            if not published:
                for d in dst_team:
                    d.abort_adding()
                    if (d.shard_begin, d.shard_end) != dst_old_bounds:
                        # a durable install already extended the claim:
                        # retract it (floor + fetched rows stay, unreachable)
                        await flow.catch_errors(flow.spawn(
                            d.set_bounds(*dst_old_bounds)))
                for p in self._current_proxies():
                    p.finish_move(r_begin, r_end, dst.tag,
                                  [s.begin for s in storages[1:]],
                                  [s.tag for s in storages])
            raise
        finally:
            self._move_inflight = False

    def _current_proxies(self):
        from .proxy import Proxy
        ep = self.dbinfo.get().epoch
        out = []
        for wi in self.workers.values():
            if not wi.worker.process.alive:
                continue
            for rn, role in wi.worker.roles.items():
                if isinstance(role, Proxy) and f"-e{ep}-" in rn:
                    out.append(role)
        return out

    # -- client handshake -----------------------------------------------
    async def _open_db_loop(self):
        while True:
            req, reply = await self.open_db.pop()
            flow.spawn(self._serve_open_db(req, reply),
                       TaskPriority.CLUSTER_CONTROLLER)

    async def _serve_open_db(self, req: OpenDatabaseRequest, reply):
        while True:
            info = self.dbinfo.get()
            if info.seq > req.known_seq and \
                    info.recovery_state == FULLY_RECOVERED and info.storages:
                reply.send(_client_safe_info(info))
                return
            await self.dbinfo.on_change()


def _client_safe_info(info):
    """The CLIENT-facing dbinfo reply rides the sim's wire round trip
    (the serialization oracle). With externally-hosted tlogs
    (tools/rolehost.py) the log refs are RetryingTcpRefs — process-
    local handles with no wire encoding, and nothing a client could
    use anyway (clients reach tlogs only THROUGH proxies). Blank them
    here; with in-process logs this returns `info` itself untouched,
    so the default posture stays byte-identical."""

    def is_ext(lr):
        return lr.commits is not None and \
            type(lr.commits).__name__ != "NetworkRef"

    def strip(ls):
        if not any(is_ext(lr) for lr in ls.logs):
            return ls
        return ls._replace(logs=tuple(
            lr._replace(commits=None, peeks=None, pops=None, locks=None)
            if is_ext(lr) else lr for lr in ls.logs))

    logs = strip(info.logs)
    old_logs = tuple(strip(ls) for ls in info.old_logs)
    if logs is info.logs and all(
            a is b for a, b in zip(old_logs, info.old_logs)):
        return info
    return info._replace(logs=logs, old_logs=old_logs)

from ..rpc import wire as _wire

_wire.register_module(__name__)  # all NamedTuples here are RPC vocabulary
