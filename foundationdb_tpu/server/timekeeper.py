"""TimeKeeper: the persisted version<->wallclock map.

Reference: fdbserver/TimeKeeper.actor.cpp — a cluster-controller actor
that periodically commits (time -> read version) pairs under
\\xff\\x02/timeKeeper/ through the ordinary pipeline, so any tool with
a database handle can translate between the version axis (what the
commit pipeline speaks) and the wallclock axis (what operators and
incident windows speak). The CC loop itself lives in
cluster_controller._timekeeper_loop; this module is the schema's
read/write/trim vocabulary, shared by the CC, the metrics janitor,
and tools/incident.py.

Lookups interpolate linearly between the two adjacent map rows (the
reference's versionFromTime does the same 1e6-versions-per-second
extrapolation off the nearest sample).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .. import flow
from ..client import run_transaction
from .systemkeys import (TIMEKEEPER_END, TIMEKEEPER_PREFIX,
                         TIMEKEEPER_VERSION, parse_timekeeper_key,
                         timekeeper_cutoff_key, timekeeper_key)

# the reference's fallback slope when extrapolating outside the map
VERSIONS_PER_SECOND = 1_000_000


async def commit_time_row(db, ts: float, version: int,
                          max_retries: int = 100) -> None:
    """Commit one (wallclock -> version) row. `version` is the best
    known recent commit version (the CC uses the max proxy committed
    version); the row is a blind set so it can never conflict."""

    async def body(tr):
        tr.set_option("access_system_keys")
        tr.set(timekeeper_key(int(ts * 1000)), b"%d" % version)

    await run_transaction(db, body, max_retries=max_retries)


async def read_time_map(db, start_ts: float = None, end_ts: float = None,
                        limit: int = 10_000
                        ) -> List[Tuple[float, int]]:
    """The stored map as [(wallclock_seconds, version)], time-ordered,
    optionally bounded to [start_ts, end_ts)."""
    b = (timekeeper_key(int(start_ts * 1000)) if start_ts is not None
         else TIMEKEEPER_PREFIX)
    e = (timekeeper_key(int(end_ts * 1000)) if end_ts is not None
         else TIMEKEEPER_END)

    async def body(tr):
        tr.set_option("access_system_keys")
        return await tr.get_range(b, e, limit=limit)

    rows = await run_transaction(db, body)
    out = []
    for k, v in rows:
        parsed = parse_timekeeper_key(k)
        if parsed is None or parsed[0] != TIMEKEEPER_VERSION:
            continue
        try:
            out.append((parsed[1] / 1000.0, int(v)))
        except ValueError:
            continue
    return out


def _interp(x: float, x0: float, y0: float, x1: float, y1: float) -> float:
    if x1 == x0:
        return y0
    return y0 + (y1 - y0) * (x - x0) / (x1 - x0)


def version_at_time_from_map(time_map: List[Tuple[float, int]],
                             ts: float) -> Optional[int]:
    """Pure lookup over an already-read map (tools that read the map
    once and translate many points — the incident bundler — use this).
    Interpolates between adjacent rows; extrapolates at the reference's
    nominal versions/second slope past either end."""
    if not time_map:
        return None
    if ts <= time_map[0][0]:
        t0, v0 = time_map[0]
        return max(0, int(v0 + (ts - t0) * VERSIONS_PER_SECOND))
    if ts >= time_map[-1][0]:
        t1, v1 = time_map[-1]
        return int(v1 + (ts - t1) * VERSIONS_PER_SECOND)
    for i in range(1, len(time_map)):
        if ts <= time_map[i][0]:
            t0, v0 = time_map[i - 1]
            t1, v1 = time_map[i]
            return int(_interp(ts, t0, v0, t1, v1))
    return time_map[-1][1]


def time_at_version_from_map(time_map: List[Tuple[float, int]],
                             version: int) -> Optional[float]:
    """Inverse lookup (versions are monotone in time, so the map is
    monotone on both axes)."""
    if not time_map:
        return None
    if version <= time_map[0][1]:
        t0, v0 = time_map[0]
        return t0 + (version - v0) / VERSIONS_PER_SECOND
    if version >= time_map[-1][1]:
        t1, v1 = time_map[-1]
        return t1 + (version - v1) / VERSIONS_PER_SECOND
    for i in range(1, len(time_map)):
        if version <= time_map[i][1]:
            t0, v0 = time_map[i - 1]
            t1, v1 = time_map[i]
            return _interp(version, v0, t0, v1, t1)
    return time_map[-1][0]


async def version_at_time(db, ts: float) -> Optional[int]:
    return version_at_time_from_map(await read_time_map(db), ts)


async def time_at_version(db, version: int) -> Optional[float]:
    return time_at_version_from_map(await read_time_map(db), version)


async def trim_timekeeper(db, cutoff_ts: float, max_retries: int = 100,
                          scan_limit: int = 10_000) -> int:
    """Delete map rows older than `cutoff_ts`; returns rows trimmed
    (bounded count + one clear_range, the clientlog-janitor shape)."""
    cutoff = timekeeper_cutoff_key(int(cutoff_ts * 1000))

    async def body(tr):
        tr.set_option("access_system_keys")
        rows = await tr.get_range(TIMEKEEPER_PREFIX, cutoff,
                                  limit=scan_limit)
        if rows:
            tr.clear_range(TIMEKEEPER_PREFIX, cutoff)
        return len(rows)

    trimmed = await run_transaction(db, body, max_retries=max_retries)
    if trimmed:
        flow.TraceEvent("TimeKeeperTrimmed").detail(
            Rows=trimmed, CutoffTs=cutoff_ts).log()
    return trimmed
