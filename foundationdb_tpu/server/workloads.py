"""Model-checked client workloads.

Reference: fdbserver/workloads/WriteDuringRead.actor.cpp:29-143 — a
random operation mix (sets, clears, range clears, atomics, gets,
selector/limit/reverse range reads, watches) driven through the full
client surface and replayed against an in-memory model database, with
every read asserted against the model mid-transaction (read-your-writes
included); stacked with attrition/BUGGIFY by the callers. Also covers
the FuzzApiCorrectness/RyowCorrectness ground: the model implements
selector resolution and atomic folds locally, so any divergence in the
distributed pipeline (proxy batching, tlog replication, storage MVCC,
shard moves) surfaces as an assertion with the op trace attached.

Retried commits are resolved exactly: every transaction writes a
sequence key, and a commit_unknown_result is settled by reading it
back — the model then applies or discards the staged effects, never
guesses (ref: the reference workloads' use of idempotent markers for
commit_unknown_result).
"""

from __future__ import annotations

import time as _time
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..client.transaction import _ATOMIC_APPLY, run_transaction
from .types import (ADD_VALUE, AND_V2, APPEND_IF_FITS, BYTE_MAX, BYTE_MIN,
                    COMPARE_AND_CLEAR, KeySelector, MAX, MIN_V2, OR,
                    SET_VERSIONSTAMPED_VALUE, XOR)

_ATOMIC_CHOICES = (ADD_VALUE, AND_V2, OR, XOR, MAX, MIN_V2, BYTE_MIN,
                   BYTE_MAX, APPEND_IF_FITS, COMPARE_AND_CLEAR)

RETRYABLE = {"not_committed", "transaction_too_old", "future_version",
             "commit_unknown_result", "broken_promise", "timed_out",
             "tlog_stopped", "coordinators_changed",
             "proxy_memory_limit_exceeded", "process_behind",
             "wrong_shard_server", "transaction_timed_out"}

# commit outcomes the client cannot know: the seq key decides
UNKNOWN_OUTCOME = {"commit_unknown_result", "timed_out",
                   "broken_promise", "tlog_stopped"}


def model_select(keys: List[bytes], sel: KeySelector) -> bytes:
    """KeySelector resolution against a sorted key list (the model's
    findKey — mirrors storage resolve_selector + the client's cross-
    shard walk + user-space clamps, storage.py resolve_selector)."""
    anchor = sel.key + b"\x00" if sel.or_equal else sel.key
    if sel.offset >= 1:
        i = bisect_left(keys, anchor) + sel.offset - 1
        return keys[i] if i < len(keys) else b"\xff"
    i = bisect_left(keys, anchor) - (1 - sel.offset)
    return keys[i] if i >= 0 else b""


def model_range(staged: Dict[bytes, bytes], begin: bytes, end: bytes,
                limit: int, reverse: bool) -> List[Tuple[bytes, bytes]]:
    rows = sorted((k, v) for k, v in staged.items() if begin <= k < end)
    if reverse:
        rows.reverse()
    return rows[:limit] if limit else rows


class WriteDuringRead:
    """One seeded run: `await WriteDuringRead(db, rng).run(rounds)`.
    Raises AssertionError (with the failing op) on any divergence."""

    def __init__(self, db, rng, prefix: bytes = b"wdr/",
                 keyspace: int = 24, max_ops: int = 8,
                 check_watches: bool = True):
        self.db = db
        self.rng = rng
        self.prefix = prefix
        self.keyspace = keyspace
        self.max_ops = max_ops
        self.check_watches = check_watches
        self.seq_key = prefix + b"\xfeseq"
        self.model: Dict[bytes, bytes] = {}
        # armed watches: (key, value at arm time, future, seq armed at)
        self.watches: list = []
        self.stats = {"txns": 0, "retries": 0, "unknown_resolved": 0,
                      "ops": 0, "watches_fired": 0}

    # -- op generation ---------------------------------------------------
    def _key(self) -> bytes:
        return self.prefix + b"k%02d" % self.rng.random_int(
            0, self.keyspace - 1)

    def _gen_ops(self) -> list:
        ops = []
        for _ in range(self.rng.random_int(1, self.max_ops)):
            kind = self.rng.random_int(0, 9)
            k = self._key()
            if kind == 0:
                ops.append(("set", k, b"v%d" % self.rng.random_int(0, 999)))
            elif kind == 1:
                ops.append(("clear", k))
            elif kind == 2:
                e = self._key()
                ops.append(("clear_range", min(k, e), max(k, e)))
            elif kind == 3:
                op_type = _ATOMIC_CHOICES[self.rng.random_int(
                    0, len(_ATOMIC_CHOICES) - 1)]
                width = self.rng.random_int(1, 8)
                param = bytes(self.rng.random_int(0, 255)
                              for _ in range(width))
                ops.append(("atomic", k, param, op_type))
            elif kind == 4:
                ops.append(("get", k))
            elif kind in (5, 6):
                e = self._key()
                ops.append(("get_range", min(k, e), max(k, e) + b"\xfe",
                            self.rng.random_int(0, 6),
                            bool(self.rng.random_int(0, 1))))
            elif kind == 7:
                ops.append(("get_key", k,
                            bool(self.rng.random_int(0, 1)),
                            self.rng.random_int(-3, 3)))
            elif kind == 8 and self.check_watches:
                ops.append(("watch", k))
            else:
                ops.append(("get", k))
        return ops

    # -- one transaction -------------------------------------------------
    async def _apply_ops(self, tr, ops, staged: Dict[bytes, bytes],
                         armed: list) -> None:
        for op in ops:
            self.stats["ops"] += 1
            kind = op[0]
            if kind == "set":
                _g, k, v = op
                tr.set(k, v)
                staged[k] = v
            elif kind == "clear":
                tr.clear(op[1])
                staged.pop(op[1], None)
            elif kind == "clear_range":
                _g, b, e = op
                tr.clear_range(b, e)
                for kk in [kk for kk in staged if b <= kk < e]:
                    del staged[kk]
            elif kind == "atomic":
                _g, k, param, op_type = op
                tr.atomic_op(k, param, op_type)
                folded = _ATOMIC_APPLY[op_type](staged.get(k), param)
                if folded is None:
                    staged.pop(k, None)
                else:
                    staged[k] = folded
            elif kind == "get":
                got = await tr.get(op[1])
                want = staged.get(op[1])
                assert got == want, ("get diverged", op, got, want)
            elif kind == "get_range":
                _g, b, e, limit, rev = op
                got = await tr.get_range(b, e, limit=limit or 10 ** 9,
                                         reverse=rev)
                want = model_range(staged, b, e, limit, rev)
                assert got == want, ("get_range diverged", op, got, want)
            elif kind == "get_key":
                _g, k, or_eq, off = op
                sel = KeySelector(k, or_eq, off)
                got = await tr.get_key(sel)
                want = model_select(sorted(staged), sel)
                assert got == want, ("get_key diverged", op, got, want)
            elif kind == "watch":
                # the compare value is resolved at COMMIT version, so
                # the model value is taken at end of txn (run() fixes
                # it up from the final staged dict)
                armed.append((op[1], tr.watch(op[1])))

    async def _resolve_unknown(self, want_seq: bytes) -> bool:
        """After commit_unknown_result: did the transaction land? The
        seq key answers exactly (every txn writes a unique value)."""
        async def body(tr):
            return await tr.get(self.seq_key)
        got = await run_transaction(self.db, body, max_retries=200)
        return got == want_seq

    async def run(self, rounds: int = 50) -> dict:
        for seq in range(rounds):
            ops = self._gen_ops()
            seq_val = b"s%06d" % seq
            while True:
                tr = self.db.create_transaction()
                staged = dict(self.model)
                armed: list = []
                try:
                    await self._apply_ops(tr, ops, staged, armed)
                    tr.set(self.seq_key, seq_val)
                    staged[self.seq_key] = seq_val
                    await tr.commit()
                    self.model = staged
                    self.watches.extend(
                        (k, staged.get(k), f) for k, f in armed)
                    break
                except flow.FdbError as e:
                    if e.name in UNKNOWN_OUTCOME:
                        if await self._resolve_unknown(seq_val):
                            flow.cover("workload.wdr.unknown_committed")
                            self.stats["unknown_resolved"] += 1
                            self.model = staged
                            self.watches.extend(
                                (k, staged.get(k), f) for k, f in armed)
                            break
                    if e.name not in RETRYABLE:
                        raise
                    self.stats["retries"] += 1
                    await flow.delay(
                        flow.SERVER_KNOBS.workload_retry_delay_min
                        + self.rng.random01()
                        * flow.SERVER_KNOBS.workload_retry_delay_span)
            self.stats["txns"] += 1
        if self.check_watches:
            await self._check_watches()
        return self.stats

    async def _check_watches(self) -> None:
        """Every watch armed on a value that LATER changed must fire;
        errors (shard moved, replica died) count as fired — the client
        contract is 'wake up and re-read' either way."""
        for key, val_at_arm, fut in self.watches:
            if self.model.get(key) == val_at_arm:
                continue  # may legitimately stay parked
            try:
                await flow.timeout_error(
                    fut, flow.SERVER_KNOBS.workload_watch_timeout)
                self.stats["watches_fired"] += 1
            except flow.FdbError as e:
                if e.name in ("timed_out",):
                    raise AssertionError(
                        ("watch never fired", key, val_at_arm,
                         self.model.get(key))) from e
                self.stats["watches_fired"] += 1  # woke with an error


class Serializability:
    """External-consistency checker (ref: Serializability.actor.cpp):
    concurrent clients run random read-then-write transactions; every
    committed attempt records its observed reads, its writes, and its
    10-byte versionstamp (commit version + intra-batch index — a TOTAL
    commit order). Afterwards the attempts are replayed in stamp order
    against a model: every recorded read must equal the model state at
    that point, or the history was not serializable in commit order.

    Attempts whose outcome the client could not learn
    (commit_unknown_result and friends) are settled exactly: each
    attempt writes a unique marker key with a VERSIONSTAMPED value, so
    a final scan of the marker subspace decides both whether the
    attempt landed and where it sits in the commit order — the checker
    never guesses (every committed attempt is its own transaction as
    far as serializability is concerned, including double-landings
    from retried unknowns)."""

    def __init__(self, dbs, rng, prefix: bytes = b"ser/",
                 keyspace: int = 16):
        import struct as _struct
        self.dbs = dbs
        self.rng = rng
        self.prefix = prefix
        self.keyspace = keyspace
        self._struct = _struct
        #: (marker_key, reads [(k, v)], writes [(kind, ...)],
        #:  stamp or None — None means "resolve via the marker")
        self.attempts: list = []
        self.stats = {"committed": 0, "aborted": 0, "unknown": 0}

    def _key(self) -> bytes:
        return self.prefix + b"k%02d" % self.rng.random_int(
            0, self.keyspace - 1)

    async def _one_txn(self, db, marker: bytes) -> None:
        while True:
            tr = db.create_transaction()
            reads = []
            writes = []
            try:
                for _ in range(self.rng.random_int(1, 3)):
                    k = self._key()
                    reads.append((k, await tr.get(k)))
                for _ in range(self.rng.random_int(1, 2)):
                    k = self._key()
                    kind = self.rng.random_int(0, 2)
                    if kind == 0:
                        v = b"v%d" % self.rng.random_int(0, 9999)
                        tr.set(k, v)
                        writes.append(("set", k, v))
                    elif kind == 1:
                        tr.clear(k)
                        writes.append(("clear", k))
                    else:
                        p = self._struct.Struct("<q").pack(
                            self.rng.random_int(1, 100))
                        tr.atomic_op(k, p, ADD_VALUE)
                        writes.append(("add", k, p))
                # the attempt's identity + commit-order witness
                val = b"\x00" * 10 + self._struct.Struct("<I").pack(0)
                tr.atomic_op(marker, val, SET_VERSIONSTAMPED_VALUE)
                await tr.commit()
            except flow.FdbError as e:
                if e.name in UNKNOWN_OUTCOME:
                    self.stats["unknown"] += 1
                    self.attempts.append((marker, reads, writes, None))
                    marker = marker + b"r"   # next attempt: fresh marker
                    continue
                if e.name in RETRYABLE:
                    self.stats["aborted"] += 1
                    continue
                raise
            self.stats["committed"] += 1
            self.attempts.append(
                (marker, reads, writes, tr.get_versionstamp()))
            return

    async def run(self, txns_per_client: int = 20) -> dict:
        async def client(db, ci):
            for i in range(txns_per_client):
                await self._one_txn(
                    db, self.prefix + b"\xfem/%d/%d/" % (ci, i))

        await flow.wait_for_all([
            flow.spawn(client(db, ci), name=f"ser-client-{ci}")
            for ci, db in enumerate(self.dbs)])

        # settle unknown-outcome attempts from their markers
        async def read_markers(tr):
            return dict(await tr.get_range(
                self.prefix + b"\xfem/", self.prefix + b"\xfem0",
                limit=1 << 20))
        markers = await run_transaction(self.dbs[0], read_markers,
                                        max_retries=500)
        ordered = []
        for marker, reads, writes, stamp in self.attempts:
            if stamp is None:
                got = markers.get(marker)
                if got is None:
                    continue           # provably never landed
                stamp = got
            ordered.append((stamp, marker, reads, writes))
        assert len({s for s, *_ in ordered}) == len(ordered), \
            "versionstamps must totally order committed attempts"
        ordered.sort()

        # replay in commit order: every observed read must match
        model: Dict[bytes, bytes] = {}
        for stamp, marker, reads, writes in ordered:
            for k, v in reads:
                assert model.get(k) == v, (
                    "serializability violation", marker, k, v, model.get(k))
            for w in writes:
                if w[0] == "set":
                    model[w[1]] = w[2]
                elif w[0] == "clear":
                    model.pop(w[1], None)
                else:
                    folded = _ATOMIC_APPLY[ADD_VALUE](model.get(w[1]), w[2])
                    model[w[1]] = folded
        self.stats["replayed"] = len(ordered)
        return self.stats


def _find_net(dbs):
    """The SimNetwork behind a pool of client handles (for message
    accounting in storm reports); None when unreachable."""
    for db in dbs:
        ref = getattr(db, "cluster_ref", None)
        if ref is not None:
            try:
                return ref.endpoint.process.net
            except AttributeError:
                pass
    return None


def sim_perf_report(wall_t0: float, sim_t0: float, tasks0: int,
                    net=None, top_k: Optional[int] = None) -> dict:
    """The wall-vs-sim budget every storm report carries (ROADMAP item
    6: the binding constraint on 10^6-client storms is simulator
    wall-clock, so every storm measures what its sim-seconds COST):
    sim seconds, real wall seconds, their ratio, run-loop steps and
    step rate — plus, when the SIM_TASK_STATS plane is armed, the
    top-K task types and top-K message types burning that wall time.

    Wall readings feed reports only, never sim decisions, so seeded
    replay determinism is untouched."""
    sched = flow.g()
    if top_k is None:
        top_k = int(flow.SERVER_KNOBS.sim_task_stats_top_k)
    wall = max(_time.monotonic() - wall_t0, 1e-9)
    sim = flow.now() - sim_t0
    tasks = sched.tasks_run - tasks0
    out = {
        "sim_seconds": round(sim, 3),
        "wall_seconds": round(wall, 4),
        "sim_per_wall": round(sim / wall, 3),
        "tasks_run": tasks,
        "tasks_per_wall_sec": round(tasks / wall, 1),
    }
    if sched.task_stats_armed:
        rep = sched.task_stats_report(top_k=top_k)
        out["top_tasks"] = rep["tasks"]
        out["priority_bands"] = rep["bands"]
    if net is not None and net.msg_stats is not None:
        mrep = net.message_stats_report(top_k=top_k)
        out["top_messages"] = mrep["types"]
        out["timers_now"] = mrep["timers_now"]
        out["messages_sent"] = mrep["messages_sent"]
    return out


def make_zipf_cdf(keyspace: int, s: float) -> list:
    """Zipfian CDF over key ranks (weight 1/rank^s), shared by the
    storm workloads; sampling is one random01 + binary search."""
    weights = [1.0 / (r ** s) for r in range(1, keyspace + 1)]
    total = sum(weights)
    acc, cdf = 0.0, []
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def zipf_rank(cdf: list, u: float) -> int:
    lo, hi = 0, len(cdf) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if cdf[mid] < u:
            lo = mid + 1
        else:
            hi = mid
    return lo


# -- vectorized storm schedules (ISSUE 12) -------------------------------
# The storms used to draw every per-arrival decision (inter-arrival
# gap, Zipf key, priority/tag/repair flags, client id) one random01()
# at a time from the SHARED flow RNG, interleaved with the simulator's
# own draws — tens of thousands of python-side bisects and RNG calls on
# the measured hot path. Each storm's draw_schedule() now draws its
# ENTIRE randomized schedule up front in bulk numpy passes on an
# independent stream seeded by ONE draw from the storm's flow RNG:
#
#   - replay determinism is preserved (same seed -> one identical fork
#     draw -> identical schedule arrays -> identical arrival sequence),
#     pinned by tests/test_storm_vectorized.py;
#   - the Zipf CDF inverts via vectorized searchsorted (identical rank
#     for identical u as the per-txn zipf_rank bisect);
#   - key bytes render once per RANK, not once per transaction.
#
# Re-baselining note: the one-time schedule change moves each storm's
# sim timeline relative to the pre-vectorization code (the old path's
# draws interleaved with network-latency draws on one shared stream, so
# matching it arrival-for-arrival is impossible by construction). The
# same-seed replay oracle — the contract PR 7 enforces and the nightly
# matrix pins — holds unchanged on the new path.

def _fork_np_rng(rng):
    """An independent numpy stream seeded by ONE draw from the storm's
    flow RNG — the schedule's only footprint on the shared stream."""
    import numpy as np
    return np.random.Generator(np.random.PCG64(rng.random_int(0, 1 << 63)))


def _arrival_offsets(g, duration: float, rate_fn, est_rate: float) -> list:
    """Open-loop exponential arrival offsets in [0, duration) under a
    piecewise rate (same inverse-CDF formula the per-arrival loop
    used), from bulk uniform passes on `g`."""
    import math
    times: list = []
    t = 0.0
    n = max(64, int(est_rate * duration * 5 // 4) + 16)
    u = g.random(n).tolist()
    i = 0
    ln = math.log
    while True:
        if i >= n:
            u = g.random(n).tolist()   # top-up pass (rarely needed)
            i = 0
        r = rate_fn(t)
        t += -ln(max(1e-12, 1.0 - u[i])) / max(r, 1e-9)
        i += 1
        if t >= duration:
            return times
        times.append(t)


def _zipf_ranks(g, cdf: list, n: int) -> list:
    """n Zipf ranks via one uniform pass + vectorized searchsorted
    (identical rank per u as zipf_rank's bisect)."""
    import numpy as np
    if n == 0:
        return []
    ranks = np.searchsorted(np.asarray(cdf), g.random(n), side="left")
    # the float-summed CDF tail sits just below 1.0, so a draw beyond
    # cdf[-1] would index one past the key table — clamp exactly like
    # zipf_rank's hi bound
    return np.minimum(ranks, len(cdf) - 1).tolist()


def _flag_array(g, n: int, fraction: float) -> list:
    """n booleans at `fraction` (a zero fraction draws nothing, so
    arrays drawn before it are unaffected either way)."""
    if fraction <= 0.0 or n == 0:
        return [False] * n
    return (g.random(n) < fraction).tolist()


_POOL_DONE = object()


class ClientActorPool:
    """Bounded, reusable client-actor pool (ISSUE 12).

    Storms used to spawn one `storm-txn-<i>` task PER ARRIVAL: a fresh
    coroutine, Task object and name string each, with every dead
    one-shot name folding through the sim-task table. The pool spawns
    at most `limit` long-lived workers — lazily, on first concurrent
    demand — and reuses them across arrivals, so the task-name set is
    FIXED and small (`<label>-0..k`, k <= peak concurrency): PR 11's
    trailing-digit folding still attributes every arrival to the same
    `<label>-*` family, and SIM_TASK_STATS_MAX_NAMES slots stop
    leaking to one-shot names.

    `dispatch(job)` hands the job to an idle worker (LIFO — the
    warmest worker runs next) or returns False when all `limit`
    workers are busy: the open-loop shed decision stays at arrival
    time, exactly like the old `_inflight >= max_inflight` cap."""

    def __init__(self, run_job, limit: int, label: str = "storm-txn"):
        self._run_job = run_job
        self._limit = max(1, limit)
        self._label = label
        self._idle: list = []      # parked workers' next-job futures
        self._tasks: list = []
        self._closing = False
        self._error = None         # first job failure, re-raised by drain

    @property
    def size(self) -> int:
        """Workers ever spawned (== peak concurrency)."""
        return len(self._tasks)

    @property
    def busy(self) -> int:
        return len(self._tasks) - len(self._idle)

    def dispatch(self, job: tuple) -> bool:
        """Run `job` now on a pooled worker; False = saturated (shed)."""
        if self._idle:
            self._idle.pop().send(job)
            return True
        if len(self._tasks) < self._limit:
            self._tasks.append(flow.spawn(
                self._worker(job),
                name=f"{self._label}-{len(self._tasks)}"))
            return True
        return False

    async def _worker(self, job: tuple) -> None:
        while True:
            try:
                await self._run_job(*job)
            except flow.ActorCancelled:
                raise
            except BaseException as e:  # noqa: BLE001
                # a dying job must not leak this worker's pool slot
                # (the old per-arrival code's finally-based inflight
                # decrement had the same guarantee): record the first
                # failure for drain() and keep serving
                if self._error is None:
                    self._error = e
            if self._closing:
                return
            f = flow.Future()
            self._idle.append(f)
            job = await f
            if job is _POOL_DONE:
                return

    async def drain(self) -> None:
        """No further dispatches: release idle workers, wait for busy
        ones. Re-raises the first job failure — the same contract as
        the old wait_for_all over per-arrival tasks."""
        self._closing = True
        idle, self._idle = self._idle, []
        for f in idle:
            f.send(_POOL_DONE)
        await flow.wait_for_all(self._tasks)
        if self._error is not None:
            raise self._error


class OpenLoopStorm:
    """Open-loop Zipfian burst workload (ref: the reference's stress
    workloads + ROADMAP item 3's admission-control storm): transaction
    arrivals follow a SEEDED exponential process whose rate is
    independent of completions — closed-loop clients self-throttle when
    the cluster slows, an open-loop storm keeps pushing, which is the
    load shape that exposes saturation and exercises the Ratekeeper.
    Keys are Zipfian (hot keys → real conflicts and hot shards); a
    configurable slice of traffic runs at batch priority and every
    simulated client carries a transaction tag, so one storm drives
    the whole QoS accounting plane (per-role signals, tag/priority
    counts, RkUpdate limiting reasons).

    `dbs` is the pool of client handles standing in for the client
    population; arrivals round-robin across it. In-flight transactions
    are capped at `max_inflight` (arrivals past the cap are counted as
    `shed`, not silently dropped), bounding sim memory while keeping
    the arrival process open-loop."""

    def __init__(self, dbs, rng, duration: float = 4.0,
                 rate: float = 150.0, burst_rate: float = 800.0,
                 burst_start: float = 1.0, burst_len: float = 1.5,
                 keyspace: int = 64, zipf_s: float = 1.2,
                 prefix: bytes = b"storm/", batch_fraction: float = 0.2,
                 tags: tuple = (b"web", b"batchjob", b"mobile"),
                 max_inflight: int = 512,
                 repairable_fraction: float = 0.0):
        self.dbs = list(dbs)
        self.rng = rng
        self.duration = duration
        # fraction of transactions declaring the automatic_repair
        # contract (their get+blind-set shape is value-independent, so
        # the declaration is honest); inert while TXN_REPAIR is off —
        # the chaos storms arm it so BUGGIFY-randomized nightlies run
        # the repair paths under faults. 0 draws no RNG at all, so the
        # default arrival schedule is bit-identical to pre-subsystem.
        self.repairable_fraction = repairable_fraction
        self.rate = rate
        self.burst_rate = burst_rate
        self.burst_start = burst_start
        self.burst_len = burst_len
        self.keyspace = keyspace
        self.prefix = prefix
        self.batch_fraction = batch_fraction
        self.tags = tuple(tags)
        self.max_inflight = max_inflight
        self._zipf_cdf = make_zipf_cdf(keyspace, zipf_s)
        from ..flow.latency import LatencySample
        self.grv_latency = LatencySample("storm_grv", size=4096)
        self.commit_latency = LatencySample("storm_commit", size=4096)
        # admitted vs shed vs completed are counted SEPARATELY: the
        # max_inflight cap exists to bound sim memory, but every
        # arrival it sheds is an arrival the cluster never saw — at
        # saturation that silently turns the storm closed-loop, so the
        # report must say how much of the offered load actually
        # reached the cluster (the `attainment` fraction) for any
        # open-loop assert to be honest about what it measured
        self.stats = {"issued": 0, "admitted": 0, "completed": 0,
                      "conflicted": 0, "shed": 0, "errors": {}}

    def draw_schedule(self):
        """The whole storm schedule in one vectorized pass: arrival
        offsets (burst-windowed piecewise rate), per-arrival key bytes
        (Zipf rank -> prerendered key table), batch-priority flags and
        automatic_repair flags. Deterministic per seed; the shared
        flow RNG pays exactly one fork draw."""
        g = _fork_np_rng(self.rng)
        bs, be = self.burst_start, self.burst_start + self.burst_len
        times = _arrival_offsets(
            g, self.duration,
            lambda t: self.burst_rate if bs <= t < be else self.rate,
            max(self.rate, self.burst_rate))
        n = len(times)
        key_table = [self.prefix + b"k%04d" % r
                     for r in range(self.keyspace)]
        keys = [key_table[r] for r in _zipf_ranks(g, self._zipf_cdf, n)]
        batch = _flag_array(g, n, self.batch_fraction)
        # drawn LAST (and not at all when 0), so arming repair leaves
        # the arrival/key/priority schedule untouched
        repair = _flag_array(g, n, self.repairable_fraction)
        return times, keys, batch, repair

    async def _one_txn(self, i: int, key: bytes, batch: bool,
                       repairable: bool) -> None:
        db = self.dbs[i % len(self.dbs)]
        tr = db.create_transaction()
        try:
            tr.set_option("transaction_tag", self.tags[i % len(self.tags)])
            if batch:
                tr.set_option("priority_batch")
            if repairable:
                tr.set_option("automatic_repair")
            t0 = flow.now()
            await tr.get_read_version()
            self.grv_latency.record(flow.now() - t0)
            await tr.get(key)
            tr.set(key, b"s%06d" % i)
            t1 = flow.now()
            await tr.commit()
            self.commit_latency.record(flow.now() - t1)
            self.stats["completed"] += 1
        except flow.FdbError as e:
            # open-loop: one attempt per arrival, no retry — a conflict
            # or throttle-timeout is an OUTCOME the storm measures, not
            # something to hide inside a retry loop
            if e.name == "not_committed":
                self.stats["conflicted"] += 1
            else:
                errs = self.stats["errors"]
                errs[e.name] = errs.get(e.name, 0) + 1

    async def run(self) -> dict:
        start = flow.now()
        wall0, tasks0 = _time.monotonic(), flow.g().tasks_run
        times, keys, batch, repair = self.draw_schedule()
        pool = ClientActorPool(self._one_txn, self.max_inflight)
        now = flow.now
        for i, t in enumerate(times):
            at = start + t
            if at > now():
                await flow.delay(at - now())
            self.stats["issued"] += 1
            if pool.dispatch((i, keys[i], batch[i], repair[i])):
                self.stats["admitted"] += 1
            else:
                self.stats["shed"] += 1
        await pool.drain()
        out = dict(self.stats)
        out["grv"] = self.grv_latency.snapshot()
        out["commit"] = self.commit_latency.snapshot()
        out["wall_seconds"] = round(flow.now() - start, 3)
        # offered-load attainment: the fraction of the open-loop
        # arrival process that actually reached the cluster (1.0 =
        # genuinely open-loop end to end; below that, the inflight cap
        # was converting offered load into shed load)
        out["attainment"] = round(
            out["admitted"] / max(out["issued"], 1), 4)
        out["sim_perf"] = sim_perf_report(wall0, start, tasks0,
                                          net=_find_net(self.dbs))
        return out


class OverloadStorm:
    """The enforced-admission-control proof storm (ROADMAP item 3 /
    ISSUE 10): a large simulated open-loop client population —
    `n_clients` logical tenants multiplexed over the `dbs` handle pool
    — offering Zipfian-keyed traffic well past the cluster's budget,
    with ONE abusive tenant tag generating a disproportionate share.
    Same seed, knobs off vs on, is the collapse-vs-degrade comparison:

    - disarmed, the GRV queue grows without bound, waits walk toward
      the client timeout, and every tenant's latency collapses
      together;
    - armed (GRV_ADMISSION_CONTROL + TAG_THROTTLING +
      AUTO_TAG_THROTTLING), admission settles at the ratekeeper's
      budget with BOUNDED admitted-GRV latency, the abusive tag gets
      an auto row in \\xff\\x02/throttledTags/ (enforced at every
      proxy, honored by the clients' local backoff), and the other
      tenants' latency recovers.

    Each arrival belongs to a LOGICAL CLIENT drawn from the
    `n_clients` population (the abusive tenant owns the first tenth of
    the ids): the client id picks the handle the arrival multiplexes
    over — so GRV batching groups, the client-honored backoff caches,
    and the tenant tag all follow the population structure rather than
    the arrival order — and the report counts the distinct clients
    actually seen.

    Latency is tracked per tenant group (abusive vs others) so the
    recovery is a measured assert, not a narrative. One attempt per
    arrival, no retries: a rejection (`proxy_memory_limit_exceeded` /
    `tag_throttled`) is a designed OUTCOME the storm counts, exactly
    like the OpenLoopStorm's honesty contract — shed, admitted, and
    completed are reported separately with offered-load attainment.

    `clients_per_arrival > 1` is the 10^6-client scale path (ISSUE
    12): each arrival represents a BLOCK of that many distinct logical
    clients walking the tenant pool behind one wire transaction whose
    GRV carries the whole block's `transaction_count` — admission
    control and the ratekeeper see the full offered load, the report's
    `distinct_clients` counts every logical client (cursor coverage,
    O(1) memory), and the simulator pays one transaction per block."""

    def __init__(self, dbs, rng, duration: float = 4.0,
                 fair_rate: float = 60.0, abusive_rate: float = 240.0,
                 n_clients: int = 100_000, keyspace: int = 64,
                 zipf_s: float = 1.2, prefix: bytes = b"ovl/",
                 abusive_tag: bytes = b"tenant-abuse",
                 tenant_tags: tuple = (b"tenant-web", b"tenant-mobile",
                                       b"tenant-api"),
                 batch_fraction: float = 0.2,
                 max_inflight: int = 4096,
                 clients_per_arrival: int = 1):
        self.dbs = list(dbs)
        self.rng = rng
        self.duration = duration
        self.fair_rate = fair_rate
        self.abusive_rate = abusive_rate
        self.n_clients = n_clients
        self.prefix = prefix
        self.abusive_tag = abusive_tag
        self.tenant_tags = tuple(tenant_tags)
        self.batch_fraction = batch_fraction
        self.max_inflight = max_inflight
        # client multiplexing (ISSUE 12's 10^6-client path): each
        # arrival stands in for a BLOCK of `clients_per_arrival`
        # distinct logical clients walking the tenant pool — the block
        # leader runs the wire transaction with a GRV weight of the
        # whole block, so admission control and the ratekeeper are
        # charged for the true offered load while the sim pays one
        # transaction per block. 1 = the classic one-client-per-arrival
        # storm (cid drawn randomly from the population).
        self.clients_per_arrival = max(1, int(clients_per_arrival))
        self._zipf_cdf = make_zipf_cdf(keyspace, zipf_s)
        from ..flow.latency import LatencySample
        #: per tenant group: admitted-GRV latency and whole-txn latency
        self.grv_latency = {"abusive": LatencySample("ovl_grv_ab", 4096),
                            "others": LatencySample("ovl_grv_ot", 4096)}
        self.txn_latency = {"abusive": LatencySample("ovl_txn_ab", 4096),
                            "others": LatencySample("ovl_txn_ot", 4096)}
        self.stats = {"issued": 0, "admitted": 0, "shed": 0,
                      "completed": 0, "conflicted": 0,
                      "grv_rejected": 0, "tag_rejected": 0,
                      "abusive_issued": 0, "abusive_completed": 0,
                      "others_issued": 0, "others_completed": 0,
                      # the settle window: arrivals from the second
                      # half of the storm, past the initial
                      # unthrottled burst — what "the cluster settled
                      # at the budget" is measured over
                      "late_issued": 0, "late_completed": 0,
                      "errors": {}}

    def draw_schedule(self):
        """Vectorized arrival schedule: offsets at the combined rate,
        per-arrival abusive/fair group flags at the rate share, Zipf
        key bytes, batch-priority flags (applied to fair traffic only,
        as before), and — for the classic 1-client-per-arrival shape —
        the logical client id draws. One fork draw on the shared RNG."""
        g = _fork_np_rng(self.rng)
        total = self.fair_rate + self.abusive_rate
        times = _arrival_offsets(g, self.duration, lambda t: total, total)
        n = len(times)
        abusive_frac = self.abusive_rate / max(total, 1e-9)
        abusive = _flag_array(g, n, abusive_frac)
        key_table = [self.prefix + b"k%04d" % r
                     for r in range(len(self._zipf_cdf))]
        keys = [key_table[r] for r in _zipf_ranks(g, self._zipf_cdf, n)]
        batch = _flag_array(g, n, self.batch_fraction)
        # the abusive tenant owns the first tenth of the client ids;
        # the fair tenants split the rest
        n_abusive = max(1, self.n_clients // 10)
        fair_pool = max(1, self.n_clients - n_abusive)
        if self.clients_per_arrival <= 1:
            u = g.random(n) if n else []
            cids = [(min(int(u[i] * n_abusive), n_abusive - 1)
                     if abusive[i]
                     else n_abusive + min(int(u[i] * fair_pool),
                                          fair_pool - 1))
                    for i in range(n)]
        else:
            # multiplexed blocks walk the pools with cursors instead of
            # random draws: coverage of the population is exact, and
            # distinct-client accounting is O(1) instead of a
            # 10^6-entry set
            cids = None
        return times, abusive, keys, batch, cids

    async def _one_txn(self, i: int, cid: int, tag: bytes, group: str,
                       late: bool, key: bytes, batch: bool) -> None:
        db = self.dbs[cid % len(self.dbs)]
        tr = db.create_transaction()
        t0 = flow.now()
        try:
            tr.set_option("transaction_tag", tag)
            if batch and group == "others":
                tr.set_option("priority_batch")
            if self.clients_per_arrival > 1:
                # the block leader's GRV is charged for the whole block
                tr.set_option("grv_batch_weight", self.clients_per_arrival)
            await tr.get_read_version()
            self.grv_latency[group].record(flow.now() - t0)
            await tr.get(key)
            tr.set(key, b"o%06d" % i)
            await tr.commit()
            self.txn_latency[group].record(flow.now() - t0)
            self.stats["completed"] += 1
            self.stats[group + "_completed"] += 1
            if late:
                self.stats["late_completed"] += 1
        except flow.FdbError as e:
            # one attempt per arrival: throttle rejections and
            # timeouts are outcomes the storm measures, never hidden
            # in a retry loop
            if e.name == "not_committed":
                self.stats["conflicted"] += 1
            elif e.name == "proxy_memory_limit_exceeded":
                self.stats["grv_rejected"] += 1
            elif e.name == "tag_throttled":
                self.stats["tag_rejected"] += 1
            else:
                errs = self.stats["errors"]
                errs[e.name] = errs.get(e.name, 0) + 1

    async def run(self) -> dict:
        start = flow.now()
        wall0, tasks0 = _time.monotonic(), flow.g().tasks_run
        times, abusive, keys, batch, cids = self.draw_schedule()
        n_abusive = max(1, self.n_clients // 10)
        fair_pool = max(1, self.n_clients - n_abusive)
        B = self.clients_per_arrival
        pool = ClientActorPool(self._one_txn, self.max_inflight,
                               label="ovl-txn")
        clients_seen: set = set()
        tags_seen: set = set()   # bounded by the tag vocabulary
        # multiplexed mode: per-group block cursors + draw totals
        cursors = {"abusive": 0, "others": 0}
        draws = {"abusive": 0, "others": 0}
        half = self.duration / 2
        now = flow.now
        for i, t in enumerate(times):
            at = start + t
            if at > now():
                await flow.delay(at - now())
            if abusive[i]:
                group = "abusive"
            else:
                group = "others"
            if cids is not None:
                cid = cids[i]
                clients_seen.add(cid)
            else:
                # next block of B distinct ids from the group's pool.
                # The leader is a ROTATING member of the block (offset
                # i % B), not always the first id: a fixed stride of B
                # would alias with len(tenant_tags)/len(dbs) whenever B
                # shares a factor with them, pinning every arrival to
                # one tag and one handle (found in review — B=600 sent
                # all fair traffic to a single tenant)
                psize = n_abusive if group == "abusive" else fair_pool
                base = 0 if group == "abusive" else n_abusive
                cid = base + ((cursors[group] + (i % B)) % psize)
                cursors[group] = (cursors[group] + B) % psize
                draws[group] += B
            tag = (self.abusive_tag if group == "abusive"
                   else self.tenant_tags[cid % len(self.tenant_tags)])
            tags_seen.add(tag)
            late = t >= half
            self.stats["issued"] += 1
            self.stats[group + "_issued"] += 1
            if late:
                self.stats["late_issued"] += 1
            if pool.dispatch((i, cid, tag, group, late, keys[i],
                              batch[i])):
                self.stats["admitted"] += 1
            else:
                self.stats["shed"] += 1
        await pool.drain()
        out = dict(self.stats)
        if cids is not None:
            out["distinct_clients"] = len(clients_seen)
        else:
            # cursor walks cover the pool exactly: distinct ids per
            # group = min(ids drawn, pool size) — O(1), no 10^6 set
            out["distinct_clients"] = (
                min(draws["abusive"], n_abusive)
                + min(draws["others"], fair_pool))
        out["clients_per_arrival"] = B
        out["logical_clients_offered"] = self.stats["issued"] * B
        # which tags actually carried traffic — a multiplexing stride
        # that aliased the tag modulus would show up as a single fair
        # tag here (test-pinned)
        out["tags_seen"] = sorted(tag.decode("latin-1")
                                  for tag in tags_seen)
        wall = flow.now() - start
        out["wall_seconds"] = round(wall, 3)
        out["attainment"] = round(
            out["admitted"] / max(out["issued"], 1), 4)
        out["committed_per_sec"] = round(
            out["completed"] / max(wall, 1e-9), 2)
        out["late_window_seconds"] = round(self.duration / 2, 3)
        out["late_committed_per_sec"] = round(
            out["late_completed"] / max(self.duration / 2, 1e-9), 2)
        out["grv"] = {g: s.snapshot() for g, s in self.grv_latency.items()}
        out["txn"] = {g: s.snapshot() for g, s in self.txn_latency.items()}
        out["n_clients"] = self.n_clients
        out["sim_perf"] = sim_perf_report(wall0, start, tasks0,
                                          net=_find_net(self.dbs))
        return out


class HotShardStorm:
    """Storage-heat proof storm (ISSUE 13): seeded open-loop READ
    arrivals where one tenant tag concentrates Zipfian point reads on a
    narrow key range at the head of the keyspace (the "hot shard")
    while background tenants read uniformly across all of it. The
    storage heat plane must NAME the hot sub-range (read-hot density
    detection) and the hot tenant (per-SS busiest read tag) — and,
    same seed, must name them bit-identically on replay.

    Every arrival is read-only: the storm heats the read side without
    perturbing the keyspace, so the armed-vs-off digest comparison is
    exact. Every 4th arrival is a short range read (index-determined,
    no extra RNG) so both read paths feed the sample. One attempt per
    arrival, the OpenLoopStorm honesty contract: shed and errored
    arrivals are counted, never hidden."""

    def __init__(self, dbs, rng, duration: float = 3.0,
                 hot_rate: float = 200.0, background_rate: float = 40.0,
                 keyspace: int = 192, hot_keys: int = 8,
                 zipf_s: float = 1.2, prefix: bytes = b"heat/",
                 hot_tag: bytes = b"tenant-hot",
                 background_tags: tuple = (b"tenant-a", b"tenant-b"),
                 value_bytes: int = 96, max_inflight: int = 512):
        self.dbs = list(dbs)
        self.rng = rng
        self.duration = duration
        self.hot_rate = hot_rate
        self.background_rate = background_rate
        self.keyspace = keyspace
        self.hot_keys = max(1, min(hot_keys, keyspace))
        self.prefix = prefix
        self.hot_tag = hot_tag
        self.background_tags = tuple(background_tags)
        self.value_bytes = value_bytes
        self.max_inflight = max_inflight
        self._hot_cdf = make_zipf_cdf(self.hot_keys, zipf_s)
        self.stats = {"issued": 0, "admitted": 0, "completed": 0,
                      "shed": 0, "hot_issued": 0, "background_issued": 0,
                      "rows_read": 0, "errors": {}}

    def key(self, rank: int) -> bytes:
        return self.prefix + b"k%04d" % rank

    @property
    def hot_range(self):
        """The range the hot tag hammers — what the detector must name
        (begin inclusive, end exclusive)."""
        return self.key(0), self.key(self.hot_keys - 1) + b"\x00"

    async def seed(self, db) -> None:
        """Materialize the keyspace (uniform value sizes, so the byte
        sample is flat and any density skew is genuinely READ skew)."""
        val = b"V" * self.value_bytes
        async def body(tr):
            for r in range(self.keyspace):
                tr.set(self.key(r), val)
        await run_transaction(db, body, max_retries=200)

    def draw_schedule(self):
        """Vectorized arrival schedule: offsets at the combined rate,
        hot/background group flags at the rate share, Zipf ranks inside
        the hot range for hot arrivals and uniform ranks for the rest.
        One fork draw on the shared flow RNG (the PR 12 idiom)."""
        g = _fork_np_rng(self.rng)
        total = self.hot_rate + self.background_rate
        times = _arrival_offsets(g, self.duration, lambda t: total, total)
        n = len(times)
        hot = _flag_array(g, n, self.hot_rate / max(total, 1e-9))
        hot_ranks = _zipf_ranks(g, self._hot_cdf, n)
        u = g.random(n).tolist() if n else []
        keys = [self.key(hot_ranks[i] if hot[i]
                         else min(int(u[i] * self.keyspace),
                                  self.keyspace - 1))
                for i in range(n)]
        return times, hot, keys

    async def _one_txn(self, i: int, key: bytes, hot: bool) -> None:
        db = self.dbs[i % len(self.dbs)]
        tr = db.create_transaction()
        try:
            tr.set_option(
                "transaction_tag",
                self.hot_tag if hot
                else self.background_tags[i % len(self.background_tags)])
            if i % 4 == 0:
                # short scan: the range-read path feeds the sample too
                rows = await tr.get_range(key, self.prefix + b"\xff",
                                          limit=4)
                self.stats["rows_read"] += len(rows)
            else:
                v = await tr.get(key)
                if v is not None:
                    self.stats["rows_read"] += 1
            self.stats["completed"] += 1
        except flow.FdbError as e:
            errs = self.stats["errors"]
            errs[e.name] = errs.get(e.name, 0) + 1

    async def run(self) -> dict:
        start = flow.now()
        wall0, tasks0 = _time.monotonic(), flow.g().tasks_run
        times, hot, keys = self.draw_schedule()
        pool = ClientActorPool(self._one_txn, self.max_inflight,
                               label="heat-txn")
        now = flow.now
        for i, t in enumerate(times):
            at = start + t
            if at > now():
                await flow.delay(at - now())
            self.stats["issued"] += 1
            self.stats["hot_issued" if hot[i]
                       else "background_issued"] += 1
            if pool.dispatch((i, keys[i], bool(hot[i]))):
                self.stats["admitted"] += 1
            else:
                self.stats["shed"] += 1
        await pool.drain()
        out = dict(self.stats)
        out["wall_seconds"] = round(flow.now() - start, 3)
        out["attainment"] = round(
            out["admitted"] / max(out["issued"], 1), 4)
        hb, he = self.hot_range
        out["hot_range"] = [hb.hex(), he.hex()]
        out["hot_tag"] = self.hot_tag.hex()
        out["sim_perf"] = sim_perf_report(wall0, start, tasks0,
                                          net=_find_net(self.dbs))
        return out


class SplitStorm:
    """Seeded skewed workload proving a LOAD-DRIVEN resolver split
    (ISSUE 15; driven by `tools/smoke.py --splits`): every key lives
    under a handful of first-byte prefixes owned by ONE resolver of a
    multi-resolver cluster, so the balance loop sees hard skew and —
    armed — must split the donor's hottest bucket and hand its state
    to the recipient live.

    Three oracles ride along: (1) exactness — a slice of the traffic
    is read-modify-write increments through ordinary retry loops, and
    the final counter values must equal the increment counts exactly
    (a lost or phantom conflict across the handoff window would break
    the sums); (2) load share — the donor's share of resolved
    transactions is sampled per window BEFORE and AFTER the first
    split, and must measurably drop; (3) the report carries committed/
    conflicted totals and a keyspace digest for same-seed comparisons."""

    def __init__(self, cluster, dbs, rng, duration: float = 8.0,
                 rate: float = 120.0, hot_prefixes: bytes = b"\x10\x18",
                 counters: int = 3, max_inflight: int = 256,
                 arm_at: "float | None" = None):
        self.cluster = cluster
        self.dbs = list(dbs)
        self.rng = rng
        self.duration = duration
        # drop in the one-shot FORCE mid-storm (sim-seconds from
        # start) so the donor's load share is sampled both BEFORE and
        # AFTER the first split; None = caller manages the knobs
        self.arm_at = arm_at
        self.rate = rate
        self.hot_prefixes = hot_prefixes
        self.counters = counters
        self.max_inflight = max_inflight
        self.stats = {"issued": 0, "admitted": 0, "completed": 0,
                      "conflicted": 0, "shed": 0, "increments": 0}

    def _resolver_roles(self):
        from .resolver_role import Resolver
        info = self.cluster.cc.dbinfo.get()
        from .cluster_controller import epoch_roles
        return sorted(epoch_roles(self.cluster.cc.workers, info.epoch,
                                  Resolver), key=lambda p: p[0])

    def _resolved_counts(self) -> list:
        return [r.stats.snapshot().get("transactions_resolved", 0)
                for _n, r in self._resolver_roles()]

    async def _one(self, i: int, key: bytes, incr_key) -> None:
        from ..client import run_transaction
        db = self.dbs[i % len(self.dbs)]
        try:
            if incr_key is not None:
                async def body(tr):
                    cur = await tr.get(incr_key)
                    tr.set(incr_key, b"%d" % (int(cur or b"0") + 1))
                await run_transaction(db, body, max_retries=500)
                self.stats["increments"] += 1
            else:
                async def body(tr):
                    tr.set(key, b"v%06d" % i)
                await run_transaction(db, body, max_retries=50)
            self.stats["completed"] += 1
        except flow.FdbError as e:
            if e.name == "operation_cancelled":
                raise
            self.stats["conflicted"] += 1
        finally:
            self._inflight -= 1

    async def run(self) -> dict:
        from .chaos import database_digest
        from .consistency import check_consistency
        g = self.rng.fork()
        self._inflight = 0
        incr_keys = [bytes([self.hot_prefixes[0]]) + b"ctr%d" % c
                     for c in range(self.counters)]
        expected = [0] * self.counters
        bal0 = dict(self.cluster.cc.balance_stats.snapshot())
        share_samples: list = []   # (splits_so_far, donor_share)
        last = self._resolved_counts()
        t_end = flow.now() + self.duration
        arm_t = flow.now() + self.arm_at if self.arm_at is not None \
            else None
        next_sample = flow.now() + 1.0
        i = 0
        while flow.now() < t_end:
            if arm_t is not None and flow.now() >= arm_t:
                # the loop itself must already be spawned (the cluster
                # booted with RESOLVER_BALANCE=1 and an unreachable
                # MIN_WORK); dropping in the one-shot FORCE here makes
                # the first split land mid-storm, with load-share
                # samples on both sides of it
                arm_t = None
                flow.SERVER_KNOBS.set("resolver_balance_force", 1)
            self.stats["issued"] += 1
            if self._inflight < self.max_inflight:
                self.stats["admitted"] += 1
                self._inflight += 1
                if g.random01() < 0.25:
                    c = g.random_int(0, self.counters)
                    expected[c] += 1
                    flow.spawn(self._one(i, b"", incr_keys[c]))
                else:
                    pfx = self.hot_prefixes[
                        g.random_int(0, len(self.hot_prefixes))]
                    key = bytes([pfx]) + b"k%06d" % i
                    flow.spawn(self._one(i, key, None))
            else:
                self.stats["shed"] += 1
            i += 1
            await flow.delay(g.random_exp(1.0 / self.rate))
            if flow.now() >= next_sample:
                next_sample = flow.now() + 1.0
                cur = self._resolved_counts()
                delta = [c - l for c, l in zip(cur, last)]
                last = cur
                tot = sum(delta)
                if tot > 0 and delta:
                    splits = self.cluster.cc.balance_stats.snapshot() \
                        .get("splits", 0) - bal0.get("splits", 0)
                    share_samples.append(
                        (splits, round(max(delta) / tot, 4)))
        # drain UNCONDITIONALLY before reading the oracle: a deadline
        # cutoff here would race in-flight increments against the
        # counter read and fail `exact` spuriously (the harness's
        # run(timeout_time=) bounds a genuine wedge)
        while self._inflight > 0:
            await flow.delay(0.1)
        # oracle 1: exact sums
        vals = []
        from ..client import run_transaction
        async def read_all(tr):
            vals.clear()
            for k in incr_keys:
                vals.append(int(await tr.get(k) or b"0"))
        await run_transaction(self.dbs[0], read_all)
        exact = vals == expected
        await check_consistency(self.cluster)
        digest = await database_digest(self.dbs[0])
        bal = self.cluster.cc.balance_stats.snapshot()
        # oracle 2: donor load share before vs after the first split
        before = [s for n, s in share_samples if n == 0]
        after = [s for n, s in share_samples if n > 0]
        report = {
            "stats": dict(self.stats),
            "expected": expected, "observed": vals, "exact": exact,
            "balance": {k: bal.get(k, 0) - bal0.get(k, 0)
                        for k in ("splits", "merges", "releases",
                                  "handoff_timeouts")},
            "share_before": round(sum(before) / len(before), 4)
            if before else None,
            "share_after": round(sum(after) / len(after), 4)
            if after else None,
            "consistency": "ok",
            "digest": digest,
        }
        return report


class ChaosStorm:
    """One named chaos scenario applied mid-flight under open-loop
    traffic, healed, quiesced, and VERIFIED (ref: the reference's
    stacked simulation tests — workload + attrition + clogging — with
    ConsistencyCheck as the closing oracle; ROADMAP item 5).

    Shape of a storm: start an OpenLoopStorm (PR 6's seeded Zipfian
    arrivals) against the cluster, wait `lead_in`, run the scenario
    (server/chaos.py — it applies its faults and heals before
    returning), wait out the traffic, then assert the three oracles:

    - `check_consistency` over the surviving database (the primary, or
      the promoted region when the scenario moved it) is clean;
    - shadow validation reported ZERO mismatches (when a device
      backend with the PR 5 shadow is present);
    - recovery was BOUNDED: scenario-end → quiesced within
      CHAOS_RECOVERY_BOUND sim-seconds.

    The returned report carries the network's full chaos event log and
    a SHA-256 digest of the final keyspace: two runs with the same seed
    must produce identical logs and digests (test-pinned replay)."""

    def __init__(self, cluster, dbs, rng, scenario: str,
                 duration: float = 5.0, rate: float = 40.0,
                 lead_in: float = 1.0, recovery_bound: float = None,
                 keyspace: int = 32):
        from .chaos import get_scenario
        self.cluster = cluster
        self.dbs = list(dbs)
        self.rng = rng
        self.scenario = get_scenario(scenario)
        self.lead_in = lead_in
        if recovery_bound is None:
            recovery_bound = float(flow.SERVER_KNOBS.chaos_recovery_bound)
        self.recovery_bound = recovery_bound
        # steady open-loop pressure, no burst: the scenario IS the storm.
        # A quarter of the traffic declares automatic_repair — inert
        # unless a BUGGIFY-randomized nightly cell armed TXN_REPAIR, in
        # which case the repair paths run under the scenario's faults
        # with the same consistency/shadow/digest oracles watching
        self.storm = OpenLoopStorm(
            self.dbs, rng, duration=duration, rate=rate, burst_rate=rate,
            burst_start=duration, keyspace=keyspace, prefix=b"chaos/",
            max_inflight=256, repairable_fraction=0.25)

    async def run(self) -> dict:
        from .chaos import chaos_status, database_digest, record_scenario
        from .consistency import check_consistency
        net = self.cluster.net
        sim0 = flow.now()
        wall0, tasks0 = _time.monotonic(), flow.g().tasks_run
        record_scenario(net, self.scenario.name)
        traffic = flow.spawn(self.storm.run(),
                             name=f"chaos-traffic-{self.scenario.name}")
        await flow.delay(self.lead_in)
        result = await self.scenario.run(self.cluster, self.rng)
        healed_at = flow.now()
        storm_stats = await traffic

        check_db = result.pop("check_db", None)
        if check_db is None:
            # heal → quiesce within the bound, then sweep every replica
            await self.cluster.quiet_database(max_wait=self.recovery_bound)
            # the bound covers scenario-end → QUIESCED; the consistency
            # sweep below is verification time, not recovery time
            recovery_seconds = flow.now() - healed_at
            consistency = await check_consistency(self.cluster,
                                                  quiesce=False)
            digest_db = self.cluster.client("chaos-digest")
        else:
            # the scenario moved the database (region failover): the
            # promoted epoch already accepts commits, so recovery ended
            # when the scenario returned; verify through the promoted
            # side's own client surface
            recovery_seconds = flow.now() - healed_at
            consistency = await flow.timeout_error(
                flow.spawn(check_consistency(check_db),
                           name="chaos-region-consistency"),
                self.recovery_bound)
            digest_db = check_db
        assert recovery_seconds <= self.recovery_bound, (
            f"{self.scenario.name}: recovery took {recovery_seconds:.1f}s "
            f"(bound {self.recovery_bound}s)")
        digest = await database_digest(digest_db)

        # shadow-validation cleanliness (PR 5's oracle, when present)
        status = await digest_db.get_status()
        cl = status["cluster"]
        for r in cl.get("resolvers", ()):
            sh = (r.get("failover") or {}).get("shadow") or {}
            assert not sh.get("mismatches"), (self.scenario.name, r)
        assert not any(m["name"] == "shadow_resolve_mismatch"
                       for m in cl.get("messages", ())), cl
        chaos = chaos_status(net)
        assert chaos["scenarios"].get(self.scenario.name), chaos

        return {
            "scenario": self.scenario.name,
            "result": result,
            "storm": storm_stats,
            "consistency": consistency,
            "digest": digest,
            "recovery_seconds": round(recovery_seconds, 3),
            "chaos": chaos,
            "events": list(net.chaos_log),
            # wall-vs-sim budget over the WHOLE storm (traffic +
            # scenario + quiesce + verification), message accounting
            # included when the plane is armed
            "sim_perf": sim_perf_report(wall0, sim0, tasks0, net=net),
            # the post-storm status doc, read through the SURVIVING
            # database (after region_failover the primary CC is gone —
            # callers must not have to query it for chaos accounting)
            "status": status,
        }


class ContentionStorm:
    """High-contention goodput workload (ISSUE 8's measurement plane):
    seeded open-loop arrivals at a FIXED offered load, every arrival a
    read-modify-write on one of a few hot keys driven through a
    bounded client retry loop. The measure is COMMITTED GOODPUT —
    transactions that actually committed per second — not verdicts/s:
    under contention the abort-only baseline burns its capacity on
    retries and exhausted attempts, which is exactly the tax the
    scheduler/repair subsystem exists to convert into commits. Two
    runs with the same seed offer the identical arrival schedule, so
    `off vs on` is an apples-to-apples goodput comparison.

    Each transaction: read the hot key (records the read conflict),
    ADD 1 to it atomically, blind-set a unique payload row — a
    value-independent shape, honestly `automatic_repair`-declarable.
    The hot counters double as a bit-exactness oracle: their sum must
    equal the committed count exactly (a repair that double-applied or
    lost a mutation cannot hide), modulo unknown-outcome attempts
    which are counted, not retried."""

    def __init__(self, dbs, rng, duration: float = 4.0,
                 rate: float = 150.0, hot_keys: int = 2,
                 prefix: bytes = b"cont/", max_retries: int = 4,
                 repairable: bool = True, max_inflight: int = 512):
        self.dbs = list(dbs)
        self.rng = rng
        self.duration = duration
        self.rate = rate
        self.hot_keys = hot_keys
        self.prefix = prefix
        self.max_retries = max_retries
        self.repairable = repairable
        self.max_inflight = max_inflight
        from ..flow.latency import LatencySample
        self.txn_latency = LatencySample("contention_txn", size=4096)
        self.stats = {"issued": 0, "committed": 0, "conflicts": 0,
                      "failed": 0, "unknown": 0, "shed": 0,
                      "attempts": 0}

    def _hot_key(self, i: int) -> bytes:
        return self.prefix + b"hot%02d" % (i % self.hot_keys)

    async def _one_txn(self, i: int) -> None:
        import struct
        db = self.dbs[i % len(self.dbs)]
        k = self._hot_key(i)
        t0 = flow.now()
        tr = db.create_transaction()
        attempts = 0
        while True:
            attempts += 1
            self.stats["attempts"] += 1
            try:
                if self.repairable:
                    tr.set_option("automatic_repair")
                await tr.get(k)
                tr.atomic_op(k, struct.pack("<q", 1), ADD_VALUE)
                tr.set(self.prefix + b"r%07d" % i, b"x")
                await tr.commit()
                self.stats["committed"] += 1
                self.txn_latency.record(flow.now() - t0)
                return
            except flow.FdbError as e:
                if e.name in UNKNOWN_OUTCOME:
                    # never retried: the goodput oracle (hot-key
                    # sum == committed) must stay exact, and a
                    # retried unknown could double-apply the ADD
                    self.stats["unknown"] += 1
                    return
                if e.name == "not_committed":
                    self.stats["conflicts"] += 1
                if attempts > self.max_retries or \
                        e.name not in RETRYABLE:
                    self.stats["failed"] += 1
                    return
                try:
                    await tr.on_error(e)
                except flow.FdbError:
                    self.stats["failed"] += 1
                    return

    def draw_schedule(self) -> list:
        """Arrival offsets in one vectorized pass (key and handle per
        arrival are index-deterministic — no other randomness)."""
        g = _fork_np_rng(self.rng)
        return _arrival_offsets(g, self.duration, lambda t: self.rate,
                                self.rate)

    async def run(self) -> dict:
        start = flow.now()
        wall0, tasks0 = _time.monotonic(), flow.g().tasks_run
        times = self.draw_schedule()
        pool = ClientActorPool(self._one_txn, self.max_inflight,
                               label="cont-txn")
        now = flow.now
        for i, t in enumerate(times):
            at = start + t
            if at > now():
                await flow.delay(at - now())
            self.stats["issued"] += 1
            if not pool.dispatch((i,)):
                self.stats["shed"] += 1
        await pool.drain()
        out = dict(self.stats)
        wall = flow.now() - start
        out["wall_seconds"] = round(wall, 3)
        out["goodput_per_sec"] = round(out["committed"] / max(wall, 1e-9),
                                       2)
        out["attempts_per_commit"] = round(
            out["attempts"] / max(out["committed"], 1), 3)
        out["latency"] = self.txn_latency.snapshot()
        out["sim_perf"] = sim_perf_report(wall0, start, tasks0,
                                          net=_find_net(self.dbs))
        return out

    async def read_hot_total(self, db) -> int:
        """Sum of the hot ADD counters — must equal committed (plus at
        most `unknown`, whose outcomes the storm deliberately did not
        settle). The bit-exactness oracle for repaired commits."""
        import struct

        async def body(tr):
            total = 0
            for j in range(self.hot_keys):
                v = await tr.get(self.prefix + b"hot%02d" % j)
                if v is not None:
                    total += struct.unpack("<q", v)[0]
            return total
        return await run_transaction(db, body, max_retries=200)


class FuzzApiCorrectness:
    """API-misuse fuzz (ref: FuzzApiCorrectness.actor.cpp): drive the
    client surface with invalid inputs — oversized keys/values,
    oversized transactions, system-keyspace access without the option,
    extreme selector offsets — and assert the EXACT error every time,
    interleaved with valid operations proving the transaction object
    stays usable afterwards (an invalid argument raises; it must not
    poison the transaction or the process)."""

    def __init__(self, db, rng, prefix: bytes = b"fuzz/"):
        self.db = db
        self.rng = rng
        self.prefix = prefix
        self.stats = {"invalid_ops": 0, "valid_commits": 0}

    def _expect(self, name: str, fn) -> None:
        try:
            fn()
        except flow.FdbError as e:
            assert e.name == name, (e.name, name)
            self.stats["invalid_ops"] += 1
            return
        raise AssertionError(f"expected {name}, got success")

    async def _expect_async(self, name: str, coro) -> None:
        try:
            await coro
        except flow.FdbError as e:
            assert e.name == name, (e.name, name)
            self.stats["invalid_ops"] += 1
            return
        raise AssertionError(f"expected {name}, got success")

    async def run(self, rounds: int = 30) -> dict:
        key_limit = int(flow.SERVER_KNOBS.key_size_limit)
        value_limit = int(flow.SERVER_KNOBS.value_size_limit)
        for i in range(rounds):
            tr = self.db.create_transaction()
            kind = self.rng.random_int(0, 5)
            k = self.prefix + b"k%d" % self.rng.random_int(0, 9)
            if kind == 0:
                big = b"K" * (key_limit + 1 + self.rng.random_int(0, 64))
                self._expect("key_too_large", lambda: tr.set(big, b"v"))
            elif kind == 1:
                big = b"V" * (value_limit + 1 + self.rng.random_int(0, 64))
                self._expect("value_too_large", lambda: tr.set(k, big))
            elif kind == 2:
                # overflow the per-transaction byte budget with legal
                # individual writes
                chunk = b"C" * value_limit
                def overflow():
                    for j in range(
                            2 + int(flow.SERVER_KNOBS.transaction_size_limit)
                            // value_limit):
                        tr.set(self.prefix + b"big%d" % j, chunk)
                self._expect("transaction_too_large", overflow)
            elif kind == 3:
                self._expect("key_outside_legal_range",
                             lambda: tr.set(b"\xff/illegal", b"v"))
            elif kind == 4:
                await self._expect_async(
                    "key_outside_legal_range", tr.get(b"\xff/conf/x"))
            else:
                # extreme selector offsets resolve to the keyspace
                # bounds, never crash or escape the legal range
                sel = KeySelector(k, bool(self.rng.random_int(0, 1)),
                                  self.rng.random_int(500, 4000)
                                  * (1 if self.rng.random_int(0, 1) else -1))
                got = await tr.get_key(sel)
                assert got == b"" or got <= b"\xff", got
                self.stats["invalid_ops"] += 1
            # the transaction (or a fresh one, if the failed op poisoned
            # the byte budget) still works end-to-end
            tr2 = self.db.create_transaction()
            tr2.set(k, b"ok%d" % i)
            await tr2.commit()
            self.stats["valid_commits"] += 1
        return self.stats
