"""Model-checked client workloads.

Reference: fdbserver/workloads/WriteDuringRead.actor.cpp:29-143 — a
random operation mix (sets, clears, range clears, atomics, gets,
selector/limit/reverse range reads, watches) driven through the full
client surface and replayed against an in-memory model database, with
every read asserted against the model mid-transaction (read-your-writes
included); stacked with attrition/BUGGIFY by the callers. Also covers
the FuzzApiCorrectness/RyowCorrectness ground: the model implements
selector resolution and atomic folds locally, so any divergence in the
distributed pipeline (proxy batching, tlog replication, storage MVCC,
shard moves) surfaces as an assertion with the op trace attached.

Retried commits are resolved exactly: every transaction writes a
sequence key, and a commit_unknown_result is settled by reading it
back — the model then applies or discards the staged effects, never
guesses (ref: the reference workloads' use of idempotent markers for
commit_unknown_result).
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

from .. import flow
from ..client.transaction import _ATOMIC_APPLY, run_transaction
from .types import (ADD_VALUE, AND_V2, APPEND_IF_FITS, BYTE_MAX, BYTE_MIN,
                    COMPARE_AND_CLEAR, KeySelector, MAX, MIN_V2, OR, XOR)

_ATOMIC_CHOICES = (ADD_VALUE, AND_V2, OR, XOR, MAX, MIN_V2, BYTE_MIN,
                   BYTE_MAX, APPEND_IF_FITS, COMPARE_AND_CLEAR)

RETRYABLE = {"not_committed", "transaction_too_old", "future_version",
             "commit_unknown_result", "broken_promise", "timed_out",
             "tlog_stopped", "coordinators_changed",
             "proxy_memory_limit_exceeded", "process_behind",
             "wrong_shard_server", "transaction_timed_out"}

# commit outcomes the client cannot know: the seq key decides
UNKNOWN_OUTCOME = {"commit_unknown_result", "timed_out",
                   "broken_promise", "tlog_stopped"}


def model_select(keys: List[bytes], sel: KeySelector) -> bytes:
    """KeySelector resolution against a sorted key list (the model's
    findKey — mirrors storage resolve_selector + the client's cross-
    shard walk + user-space clamps, storage.py resolve_selector)."""
    anchor = sel.key + b"\x00" if sel.or_equal else sel.key
    if sel.offset >= 1:
        i = bisect_left(keys, anchor) + sel.offset - 1
        return keys[i] if i < len(keys) else b"\xff"
    i = bisect_left(keys, anchor) - (1 - sel.offset)
    return keys[i] if i >= 0 else b""


def model_range(staged: Dict[bytes, bytes], begin: bytes, end: bytes,
                limit: int, reverse: bool) -> List[Tuple[bytes, bytes]]:
    rows = sorted((k, v) for k, v in staged.items() if begin <= k < end)
    if reverse:
        rows.reverse()
    return rows[:limit] if limit else rows


class WriteDuringRead:
    """One seeded run: `await WriteDuringRead(db, rng).run(rounds)`.
    Raises AssertionError (with the failing op) on any divergence."""

    def __init__(self, db, rng, prefix: bytes = b"wdr/",
                 keyspace: int = 24, max_ops: int = 8,
                 check_watches: bool = True):
        self.db = db
        self.rng = rng
        self.prefix = prefix
        self.keyspace = keyspace
        self.max_ops = max_ops
        self.check_watches = check_watches
        self.seq_key = prefix + b"\xfeseq"
        self.model: Dict[bytes, bytes] = {}
        # armed watches: (key, value at arm time, future, seq armed at)
        self.watches: list = []
        self.stats = {"txns": 0, "retries": 0, "unknown_resolved": 0,
                      "ops": 0, "watches_fired": 0}

    # -- op generation ---------------------------------------------------
    def _key(self) -> bytes:
        return self.prefix + b"k%02d" % self.rng.random_int(
            0, self.keyspace - 1)

    def _gen_ops(self) -> list:
        ops = []
        for _ in range(self.rng.random_int(1, self.max_ops)):
            kind = self.rng.random_int(0, 9)
            k = self._key()
            if kind == 0:
                ops.append(("set", k, b"v%d" % self.rng.random_int(0, 999)))
            elif kind == 1:
                ops.append(("clear", k))
            elif kind == 2:
                e = self._key()
                ops.append(("clear_range", min(k, e), max(k, e)))
            elif kind == 3:
                op_type = _ATOMIC_CHOICES[self.rng.random_int(
                    0, len(_ATOMIC_CHOICES) - 1)]
                width = self.rng.random_int(1, 8)
                param = bytes(self.rng.random_int(0, 255)
                              for _ in range(width))
                ops.append(("atomic", k, param, op_type))
            elif kind == 4:
                ops.append(("get", k))
            elif kind in (5, 6):
                e = self._key()
                ops.append(("get_range", min(k, e), max(k, e) + b"\xfe",
                            self.rng.random_int(0, 6),
                            bool(self.rng.random_int(0, 1))))
            elif kind == 7:
                ops.append(("get_key", k,
                            bool(self.rng.random_int(0, 1)),
                            self.rng.random_int(-3, 3)))
            elif kind == 8 and self.check_watches:
                ops.append(("watch", k))
            else:
                ops.append(("get", k))
        return ops

    # -- one transaction -------------------------------------------------
    async def _apply_ops(self, tr, ops, staged: Dict[bytes, bytes],
                         armed: list) -> None:
        for op in ops:
            self.stats["ops"] += 1
            kind = op[0]
            if kind == "set":
                _g, k, v = op
                tr.set(k, v)
                staged[k] = v
            elif kind == "clear":
                tr.clear(op[1])
                staged.pop(op[1], None)
            elif kind == "clear_range":
                _g, b, e = op
                tr.clear_range(b, e)
                for kk in [kk for kk in staged if b <= kk < e]:
                    del staged[kk]
            elif kind == "atomic":
                _g, k, param, op_type = op
                tr.atomic_op(k, param, op_type)
                folded = _ATOMIC_APPLY[op_type](staged.get(k), param)
                if folded is None:
                    staged.pop(k, None)
                else:
                    staged[k] = folded
            elif kind == "get":
                got = await tr.get(op[1])
                want = staged.get(op[1])
                assert got == want, ("get diverged", op, got, want)
            elif kind == "get_range":
                _g, b, e, limit, rev = op
                got = await tr.get_range(b, e, limit=limit or 10 ** 9,
                                         reverse=rev)
                want = model_range(staged, b, e, limit, rev)
                assert got == want, ("get_range diverged", op, got, want)
            elif kind == "get_key":
                _g, k, or_eq, off = op
                sel = KeySelector(k, or_eq, off)
                got = await tr.get_key(sel)
                want = model_select(sorted(staged), sel)
                assert got == want, ("get_key diverged", op, got, want)
            elif kind == "watch":
                # the compare value is resolved at COMMIT version, so
                # the model value is taken at end of txn (run() fixes
                # it up from the final staged dict)
                armed.append((op[1], tr.watch(op[1])))

    async def _resolve_unknown(self, want_seq: bytes) -> bool:
        """After commit_unknown_result: did the transaction land? The
        seq key answers exactly (every txn writes a unique value)."""
        async def body(tr):
            return await tr.get(self.seq_key)
        got = await run_transaction(self.db, body, max_retries=200)
        return got == want_seq

    async def run(self, rounds: int = 50) -> dict:
        for seq in range(rounds):
            ops = self._gen_ops()
            seq_val = b"s%06d" % seq
            while True:
                tr = self.db.create_transaction()
                staged = dict(self.model)
                armed: list = []
                try:
                    await self._apply_ops(tr, ops, staged, armed)
                    tr.set(self.seq_key, seq_val)
                    staged[self.seq_key] = seq_val
                    await tr.commit()
                    self.model = staged
                    self.watches.extend(
                        (k, staged.get(k), f) for k, f in armed)
                    break
                except flow.FdbError as e:
                    if e.name in UNKNOWN_OUTCOME:
                        if await self._resolve_unknown(seq_val):
                            flow.cover("workload.wdr.unknown_committed")
                            self.stats["unknown_resolved"] += 1
                            self.model = staged
                            self.watches.extend(
                                (k, staged.get(k), f) for k, f in armed)
                            break
                    if e.name not in RETRYABLE:
                        raise
                    self.stats["retries"] += 1
                    await flow.delay(0.05 + self.rng.random01() * 0.2)
            self.stats["txns"] += 1
        if self.check_watches:
            await self._check_watches()
        return self.stats

    async def _check_watches(self) -> None:
        """Every watch armed on a value that LATER changed must fire;
        errors (shard moved, replica died) count as fired — the client
        contract is 'wake up and re-read' either way."""
        for key, val_at_arm, fut in self.watches:
            if self.model.get(key) == val_at_arm:
                continue  # may legitimately stay parked
            try:
                await flow.timeout_error(fut, 30.0)
                self.stats["watches_fired"] += 1
            except flow.FdbError as e:
                if e.name in ("timed_out",):
                    raise AssertionError(
                        ("watch never fired", key, val_at_arm,
                         self.model.get(key))) from e
                self.stats["watches_fired"] += 1  # woke with an error
