"""Simulated disks: machine-scoped files with power-loss semantics.

Reference behaviors re-implemented (not ported):
  - async file API with explicit sync barriers (fdbrpc/IAsyncFile.h)
  - simulated IO latency drawn from the deterministic RNG
    (fdbrpc/sim2.actor.cpp SimDiskSpace / file ops)
  - NONDURABLE kill semantics: writes issued since the last sync have
    no durability guarantee — on an untimely process death each one is
    independently kept or dropped, so recovery code must tolerate any
    prefix/subset surviving (fdbrpc/AsyncFileNonDurable.actor.h — the
    heart of FDB's power-loss testing)

Files belong to a MACHINE, not a process: a restarted process opens the
same file set and sees whatever survived (ref: simulator.h machine
folders; restartSimulatedSystem).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow import TaskPriority, error


class SimFile:
    """One simulated file: durable bytes + an unsynced write buffer."""

    __slots__ = ("disk", "name", "owner", "_durable", "_pending", "_open")

    def __init__(self, disk: "SimDisk", name: str, owner=None):
        self.disk = disk
        self.name = name
        self.owner = owner  # the SimProcess whose death power-fails this file
        self._durable = bytearray()
        self._pending: List[Tuple[int, bytes]] = []  # (offset, data)
        self._open = True

    # -- async API (ref: IAsyncFile) ------------------------------------
    async def write(self, offset: int, data: bytes) -> None:
        """Buffered write; durable only after sync()."""
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        self._pending.append((offset, bytes(data)))

    async def sync(self) -> None:
        """Barrier: all previously written data becomes durable
        (ref: IAsyncFile::sync / fsync)."""
        self._check_open()
        await self.disk._io_latency(sync=True)
        self._check_open()
        for offset, data in self._pending:
            self._apply(offset, data)
        self._pending.clear()

    async def read(self, offset: int, length: int) -> bytes:
        """Read through the OS view (durable + buffered writes) — a live
        process sees its own unsynced writes."""
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        view = bytearray(self._durable)
        for off, data in self._pending:
            self._apply_to(view, off, data)
        return bytes(view[offset:offset + length])

    async def truncate(self, size: int) -> None:
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        self._pending.append((size, None))  # type: ignore[arg-type]

    async def size(self) -> int:
        self._check_open()
        view_len = len(self._durable)
        for off, data in self._pending:
            if data is None:
                view_len = off
            else:
                view_len = max(view_len, off + len(data))
        return view_len

    # -- internals ------------------------------------------------------
    def _check_open(self) -> None:
        if not self._open:
            raise error("io_error")

    def _apply(self, offset: int, data: Optional[bytes]) -> None:
        self._apply_to(self._durable, offset, data)

    @staticmethod
    def _apply_to(buf: bytearray, offset: int, data: Optional[bytes]) -> None:
        if data is None:  # truncate record
            del buf[offset:]
            return
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data

    def _power_loss(self, rng) -> None:
        """Each unsynced write independently survives or vanishes — the
        OS may or may not have flushed it (ref: AsyncFileNonDurable
        KILLED mode). Ordering of survivors is preserved. The LAST
        surviving write — the one in flight when the power failed — may
        additionally be TORN: only a seeded prefix of it lands
        (SIM_TORN_WRITE_PROB; ref: AsyncFileNonDurable's partial-write
        mode), so recovery code is exercised against genuinely
        half-written records, not just whole-write drops."""
        from ..flow import SERVER_KNOBS
        survivors = [(offset, data) for offset, data in self._pending
                     if rng.random01() >= SERVER_KNOBS.sim_power_loss_drop_prob]
        for i, (offset, data) in enumerate(survivors):
            if (data is not None and len(data) > 1
                    and i == len(survivors) - 1
                    and rng.random01() < SERVER_KNOBS.sim_torn_write_prob):
                from ..flow import cover
                cover("disk.torn_write")
                data = data[:rng.random_int(1, len(data))]
                if self.disk.net is not None:
                    self.disk.net.chaos_note("torn_write", file=self.name,
                                             machine=self.disk.machine)
            self._apply(offset, data)
        self._pending.clear()
        self._open = False

    def corrupt(self, rng, n_bytes: int = None) -> list:
        """Seeded sector rot: flip bytes in the DURABLE image (the
        bytes a recovery will read). Returns [(offset, old, new)].
        Detection is the reader's job, and depends on where the flip
        lands: a payload hit in a checksummed format (DiskQueue)
        surfaces as checksum_failed at recovery, while a header hit is
        indistinguishable from a torn tail and gets CRC-cut — acked
        data past it must then be re-healed from replication. Tests
        that need a GUARANTEED-detectable (or guaranteed-undetectable)
        flip use the format-aware server/chaos.py helpers instead."""
        from ..flow import SERVER_KNOBS
        if n_bytes is None:
            n_bytes = int(SERVER_KNOBS.chaos_corrupt_bytes)
        if not self._durable:
            return []
        flips = []
        for _ in range(n_bytes):
            off = rng.random_int(0, len(self._durable))
            old = self._durable[off]
            new = old ^ rng.random_int(1, 256)   # guaranteed to differ
            self._durable[off] = new
            flips.append((off, old, new))
        if self.disk.net is not None:
            self.disk.net.chaos_note("disk_corruption", file=self.name,
                                     machine=self.disk.machine,
                                     bytes=len(flips))
        return flips

    def _close(self) -> None:
        self._open = False


class SimDisk:
    """A machine's file namespace + IO model (survives process kills)."""

    def __init__(self, net, machine: str):
        self.net = net
        self.machine = machine
        self.files: Dict[str, SimFile] = {}

    def open(self, name: str, owner=None) -> SimFile:
        """Open-or-create. Reopening after a kill hands back a fresh
        handle onto whatever bytes survived."""
        f = self.files.get(name)
        if f is None or not f._open:
            nf = SimFile(self, name, owner)
            if f is not None:
                nf._durable = f._durable  # survives the crash
            self.files[name] = nf
            f = nf
        elif owner is not None:
            f.owner = owner
        return f

    def exists(self, name: str) -> bool:
        return name in self.files

    def corrupt_file(self, name: str, rng, n_bytes: int = None) -> list:
        """Sector-rot a named file's durable bytes (see SimFile.corrupt)."""
        f = self.files.get(name)
        if f is None:
            return []
        return f.corrupt(rng, n_bytes)

    def remove(self, name: str) -> None:
        """Destroy a file (store retirement)."""
        f = self.files.pop(name, None)
        if f is not None:
            f._close()

    async def _io_latency(self, sync: bool = False):
        from .. import flow
        k = flow.SERVER_KNOBS
        base = k.sim_disk_write_latency if not sync else \
            k.sim_disk_sync_latency
        jitter = flow.g_random.random01() * (
            k.sim_disk_write_jitter if not sync else k.sim_disk_sync_jitter)
        await flow.delay(base + jitter, TaskPriority.DISK_IO_LATENCY)

    def power_loss(self, rng, owner=None) -> None:
        """Crash semantics: with `owner`, only that process's files lose
        their unsynced writes (process crash); without, the whole
        machine does (power failure)."""
        for f in self.files.values():
            if f._open and (owner is None or f.owner is owner):
                f._power_loss(rng)


def _fsync_handle(fh) -> None:
    """Pool-side fsync via the handle (fileno() on a closed file raises
    ValueError, never returns a stale — possibly reused — fd)."""
    import os
    os.fsync(fh.fileno())


class RealFile:
    """One ON-DISK file behind the SimFile async interface (ref:
    AsyncFileKAIO/AsyncFileCached — the production IAsyncFile). Writes
    go to the OS immediately; sync() is a real fsync, so acknowledged
    durability survives an actual process restart."""

    __slots__ = ("path", "name", "owner", "_fh", "_open", "pool")

    def __init__(self, path: str, name: str, owner=None, pool=None):
        import os
        self.path = path
        self.name = name
        self.owner = owner
        # IThreadPool for the blocking fsync (ref: AsyncFileEIO —
        # the reference never lets a blocking syscall run on the
        # event loop); None = inline (sim tests, tiny tools)
        self.pool = pool
        mode = "r+b" if os.path.exists(path) else "w+b"
        # unbuffered: writes reach the OS immediately, so a finalizer
        # flush can never resurrect stale bytes after a successor
        # process has recovered from the same file
        self._fh = open(path, mode, buffering=0)
        self._open = True

    async def write(self, offset: int, data: bytes) -> None:
        self._check_open()
        self._fh.seek(offset)
        self._fh.write(data)

    async def sync(self) -> None:
        import os
        self._check_open()
        if self.pool is not None:
            # a real fsync takes ms to tens of ms: on the pool it
            # stalls one worker thread, not every actor in the process.
            # The worker resolves the fd AT EXECUTION TIME from the
            # handle: a file closed while the fsync was queued raises
            # (io_error) instead of fsyncing a reused fd number
            await self.pool.run(_fsync_handle, self._fh)
            self._check_open()   # may have closed while waiting
        else:
            os.fsync(self._fh.fileno())

    async def read(self, offset: int, length: int) -> bytes:
        self._check_open()
        self._fh.seek(offset)
        return self._fh.read(length)

    async def truncate(self, size: int) -> None:
        self._check_open()
        self._fh.truncate(size)

    async def size(self) -> int:
        import os
        self._check_open()
        return os.fstat(self._fh.fileno()).st_size

    def _check_open(self) -> None:
        if not self._open:
            raise error("io_error")

    def _power_loss(self, rng) -> None:
        # a real process crash: the OS keeps whatever it has; only the
        # handle dies (unsynced page-cache fate is the kernel's call)
        self._close()

    def _close(self) -> None:
        if self._open:
            self._open = False
            try:
                self._fh.close()
            except OSError:
                pass


class RealDisk:
    """A directory as a machine's file namespace — the production disk
    behind the same seam the simulator serves (ref: the platform layer
    under IAsyncFile). `tools/server --data-dir` uses this so a host
    process's durable state survives ACTUAL restarts."""

    LOCKFILE = ".fdbtpu-lock"

    def __init__(self, root: str, machine: str = "", pool=None):
        import fcntl
        import os
        self.root = root
        self.machine = machine
        self.pool = pool   # shared IThreadPool for blocking file IO
        os.makedirs(root, exist_ok=True)
        # exclusive directory lock (ref: fdbserver flocking its data
        # dir): two processes interleaving writes into the same stores
        # would corrupt acknowledged durable state
        self._lock_fh = open(os.path.join(root, self.LOCKFILE), "w")
        try:
            fcntl.flock(self._lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_fh.close()
            raise error("io_error") from None
        self.files: Dict[str, RealFile] = {}
        for name in sorted(os.listdir(root)):
            if name != self.LOCKFILE:
                self.files[name] = RealFile(os.path.join(root, name),
                                            name, pool=self.pool)

    def _path(self, name: str) -> str:
        import os
        assert "/" not in name and name not in (".", ".."), name
        return os.path.join(self.root, name)

    def open(self, name: str, owner=None) -> RealFile:
        f = self.files.get(name)
        if f is None or not f._open:
            f = RealFile(self._path(name), name, owner, pool=self.pool)
            self.files[name] = f
        elif owner is not None:
            f.owner = owner
        return f

    def exists(self, name: str) -> bool:
        return name in self.files

    def power_loss(self, rng, owner=None) -> None:
        for f in self.files.values():
            if f._open and (owner is None or f.owner is owner):
                f._power_loss(rng)

    def remove(self, name: str) -> None:
        """Destroy a file ON DISK (store retirement must not resurrect
        on the next boot scan)."""
        import os
        f = self.files.pop(name, None)
        if f is not None:
            f._close()
            try:
                os.unlink(f.path)
            except OSError:
                pass

    def close_all(self) -> None:
        """Release every handle and the directory lock (shutdown)."""
        for f in self.files.values():
            f._close()
        try:
            self._lock_fh.close()   # drops the flock
        except OSError:
            pass
