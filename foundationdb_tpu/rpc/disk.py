"""Simulated disks: machine-scoped files with power-loss semantics.

Reference behaviors re-implemented (not ported):
  - async file API with explicit sync barriers (fdbrpc/IAsyncFile.h)
  - simulated IO latency drawn from the deterministic RNG
    (fdbrpc/sim2.actor.cpp SimDiskSpace / file ops)
  - NONDURABLE kill semantics: writes issued since the last sync have
    no durability guarantee — on an untimely process death each one is
    independently kept or dropped, so recovery code must tolerate any
    prefix/subset surviving (fdbrpc/AsyncFileNonDurable.actor.h — the
    heart of FDB's power-loss testing)

Files belong to a MACHINE, not a process: a restarted process opens the
same file set and sees whatever survived (ref: simulator.h machine
folders; restartSimulatedSystem).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow import TaskPriority, error


class SimFile:
    """One simulated file: durable bytes + an unsynced write buffer."""

    __slots__ = ("disk", "name", "owner", "_durable", "_pending", "_open")

    def __init__(self, disk: "SimDisk", name: str, owner=None):
        self.disk = disk
        self.name = name
        self.owner = owner  # the SimProcess whose death power-fails this file
        self._durable = bytearray()
        self._pending: List[Tuple[int, bytes]] = []  # (offset, data)
        self._open = True

    # -- async API (ref: IAsyncFile) ------------------------------------
    async def write(self, offset: int, data: bytes) -> None:
        """Buffered write; durable only after sync()."""
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        self._pending.append((offset, bytes(data)))

    async def sync(self) -> None:
        """Barrier: all previously written data becomes durable
        (ref: IAsyncFile::sync / fsync)."""
        self._check_open()
        await self.disk._io_latency(sync=True)
        self._check_open()
        for offset, data in self._pending:
            self._apply(offset, data)
        self._pending.clear()

    async def read(self, offset: int, length: int) -> bytes:
        """Read through the OS view (durable + buffered writes) — a live
        process sees its own unsynced writes."""
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        view = bytearray(self._durable)
        for off, data in self._pending:
            self._apply_to(view, off, data)
        return bytes(view[offset:offset + length])

    async def truncate(self, size: int) -> None:
        self._check_open()
        await self.disk._io_latency()
        self._check_open()
        self._pending.append((size, None))  # type: ignore[arg-type]

    async def size(self) -> int:
        self._check_open()
        view_len = len(self._durable)
        for off, data in self._pending:
            if data is None:
                view_len = off
            else:
                view_len = max(view_len, off + len(data))
        return view_len

    # -- internals ------------------------------------------------------
    def _check_open(self) -> None:
        if not self._open:
            raise error("io_error")

    def _apply(self, offset: int, data: Optional[bytes]) -> None:
        self._apply_to(self._durable, offset, data)

    @staticmethod
    def _apply_to(buf: bytearray, offset: int, data: Optional[bytes]) -> None:
        if data is None:  # truncate record
            del buf[offset:]
            return
        end = offset + len(data)
        if len(buf) < end:
            buf.extend(b"\x00" * (end - len(buf)))
        buf[offset:end] = data

    def _power_loss(self, rng) -> None:
        """Each unsynced write independently survives or vanishes — the
        OS may or may not have flushed it (ref: AsyncFileNonDurable
        KILLED mode). Ordering of survivors is preserved."""
        for offset, data in self._pending:
            if rng.random01() < 0.5:
                self._apply(offset, data)
        self._pending.clear()
        self._open = False

    def _close(self) -> None:
        self._open = False


class SimDisk:
    """A machine's file namespace + IO model (survives process kills)."""

    def __init__(self, net, machine: str):
        self.net = net
        self.machine = machine
        self.files: Dict[str, SimFile] = {}

    def open(self, name: str, owner=None) -> SimFile:
        """Open-or-create. Reopening after a kill hands back a fresh
        handle onto whatever bytes survived."""
        f = self.files.get(name)
        if f is None or not f._open:
            nf = SimFile(self, name, owner)
            if f is not None:
                nf._durable = f._durable  # survives the crash
            self.files[name] = nf
            f = nf
        elif owner is not None:
            f.owner = owner
        return f

    def exists(self, name: str) -> bool:
        return name in self.files

    async def _io_latency(self, sync: bool = False):
        from .. import flow
        base = 0.0001 if not sync else 0.0005
        jitter = flow.g_random.random01() * (0.0002 if not sync else 0.002)
        await flow.delay(base + jitter, TaskPriority.DISK_IO_LATENCY)

    def power_loss(self, rng, owner=None) -> None:
        """Crash semantics: with `owner`, only that process's files lose
        their unsynced writes (process crash); without, the whole
        machine does (power failure)."""
        for f in self.files.values():
            if f._open and (owner is None or f.owner is owner):
                f._power_loss(rng)
