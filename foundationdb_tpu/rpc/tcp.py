"""Real TCP transport: token-addressed request/reply over sockets,
speaking the same wire format the simulator round-trips.

Reference: fdbrpc/FlowTransport.actor.cpp — a ConnectPacket handshake
(:200), token-addressed delivery to an EndpointMap (:517), one
connection per peer pair with a connectionReader/Writer pair per
socket (:646/:397). Frames: [u32 len][u8 kind][u64 req_id][u64 token]
[wire payload]; kind 0 request, 1 reply, 2 error reply.

The flow scheduler is single-threaded and (in wall-clock mode) has no
socket reactor, so ALL socket IO — connect, read, write — runs on OS
threads; the scheduler side only enqueues outbound frames and drains an
inbox of completions via a reactor actor (a miniature of Net2's
asio-reactor seam, flow/Net2.actor.cpp:123). A dying connection fails
its in-flight requests with broken_promise exactly like the simulated
transport's closed-connection semantics.
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
from collections import deque
from typing import Dict, NamedTuple, Optional

from .. import flow
from ..flow import TaskPriority, error
from ..flow.actors import PromiseStream
from ..flow.future import Future, Promise
from . import wire

_HDR = struct.Struct("<IBQQ")   # len, kind, req_id, token
PROTOCOL_VERSION = b"fdbtpu01"
K_REQUEST, K_REPLY, K_ERROR = 0, 1, 2
# traced variants (ISSUE 16, gated on the TRACE_PROPAGATION knob):
# kind 3 wraps a request as [trace_ctx, request] — the sender's process
# identity, its open parent span per debug id, and the send timestamp
# t0; kind 4 wraps the reply as [hop, value] with the server identity
# and its recv/send timestamps t1/t2. With the knob off (the default)
# kinds 3/4 never hit a socket and kinds 0/1/2 frames are byte-
# identical to the pre-knob transport (pinned in
# tests/test_distributed_trace.py)
K_TRACED, K_TRACED_REPLY = 3, 4


def _trace_armed() -> bool:
    from ..flow import SERVER_KNOBS
    return bool(SERVER_KNOBS.trace_propagation)


def _trace_ctx(request):
    """The trace context a TRACED request frame carries: the sending
    process identity, (debug_id, open parent span id) pairs for every
    debug id the request ships, and the local send timestamp t0 (the
    first of the four NTP-style hop timestamps tracemerge's clock-
    offset estimator consumes). None when the request samples nothing —
    an unsampled request rides a plain K_REQUEST frame even while the
    knob is armed."""
    ids = getattr(request, "debug_ids", None)
    if not ids:
        d = getattr(request, "debug_id", None)
        ids = (d,) if d is not None else ()
    ids = tuple(d for d in ids if d is not None)
    if not ids:
        return None
    from ..flow import trace as _trace
    return {"process": _trace.process_name(),
            "spans": [[d, _trace.g_trace_batch.open_span_id(d)]
                      for d in ids],
            "t0": flow.now()}
def HANDSHAKE_TIMEOUT():
    from ..flow import SERVER_KNOBS
    return SERVER_KNOBS.tcp_handshake_timeout


def CONNECT_TIMEOUT():
    from ..flow import SERVER_KNOBS
    return SERVER_KNOBS.tcp_connect_timeout


class TlsConfig(NamedTuple):
    """Mutual-TLS configuration for a transport (ref: FDBLibTLS — both
    sides present certificates and verify the peer's chain; the
    reference plugs this in under FlowTransport the same way)."""

    certfile: str
    keyfile: str
    cafile: str
    verify_peer: bool = True

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        if self.verify_peer:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(self.cafile)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        # peers authenticate by certificate chain, not hostname — the
        # reference's TLS verifies subject/issuer fields, not DNS names
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(self.cafile)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        return ctx


class TcpReply:
    """Reply handle handed to server actors; send() enqueues the framed
    value on the originating connection's writer thread. A request that
    arrived on a TRACED frame remembers its receive timestamp here and
    answers with a TRACED reply carrying this process's identity and
    the t1/t2 hop timestamps (errors stay plain: the offset estimator
    only wants clean request/reply pairs)."""

    __slots__ = ("conn", "req_id", "t_recv")

    def __init__(self, conn: "_Conn", req_id: int,
                 t_recv: Optional[float] = None):
        self.conn = conn
        self.req_id = req_id
        self.t_recv = t_recv

    def send(self, value=None) -> None:
        if self.t_recv is not None:
            from ..flow import trace as _trace
            hop = {"process": _trace.process_name(),
                   "t1": self.t_recv, "t2": flow.now()}
            self.conn.enqueue(K_TRACED_REPLY, self.req_id, 0,
                              wire.to_bytes([hop, value]))
            return
        self.conn.enqueue(K_REPLY, self.req_id, 0, wire.to_bytes(value))

    def send_error(self, err) -> None:
        name = getattr(err, "name", "unknown_error")
        self.conn.enqueue(K_ERROR, self.req_id, 0, wire.to_bytes(name))


class TcpRequestStream:
    """Server side of a TCP endpoint (mirror of rpc.network
    RequestStream)."""

    def __init__(self, transport: "TcpTransport"):
        self.stream = PromiseStream()
        self.token = transport._register(self)
        self.transport = transport

    def pop(self) -> Future:
        return self.stream.stream.pop()


class TcpRef:
    """Client handle to a remote TCP endpoint."""

    __slots__ = ("transport", "addr", "token")

    def __init__(self, transport: "TcpTransport", addr, token: int):
        self.transport = transport
        self.addr = addr
        self.token = token

    def get_reply(self, request, _src=None) -> Future:
        return self.transport._request(self.addr, self.token, request)

    def send(self, request, _src=None) -> None:
        """Fire-and-forget (the NetworkRef.send mirror): the frame rides
        a normal request id, but no promise is registered — a reply (or
        the connection dying) is silently dropped, matching the sim
        transport's best-effort datagram semantics."""
        self.transport._request(self.addr, self.token, request,
                                oneway=True)


class RetryingTcpRef:
    """A TcpRef that re-issues a request when the underlying connection
    dies mid-flight (broken_promise), with exponential backoff up to the
    ROLE_RETRY_DEADLINE wall-clock budget.

    This is the client half of role-process fault tolerance: an
    externally-hosted resolver/tlog killed with SIGKILL respawns on the
    SAME addr:port (SO_REUSEADDR) and recovers from its checkpoint +
    journal, so a retried request lands on a role whose reply cache /
    version chain make the re-delivery idempotent (the reference's
    model: endpoint tokens survive process restart only through
    recruitment, but OUR role hosts pin their token layout, so the
    ref stays valid across the respawn). Requests that fail with any
    error OTHER than broken_promise propagate immediately — retry is
    for dead transport, not for application verdicts."""

    __slots__ = ("ref",)

    def __init__(self, ref: TcpRef):
        self.ref = ref

    @property
    def addr(self):
        return self.ref.addr

    @property
    def token(self):
        return self.ref.token

    def get_reply(self, request, _src=None) -> Future:
        p = Promise()
        flow.spawn(self._drive(request, _src, p), TaskPriority.READ_SOCKET,
                   name="tcp.retry")
        return p.future

    def send(self, request, _src=None) -> None:
        self.ref.send(request, _src)

    async def _drive(self, request, src, p: Promise):
        from ..flow import SERVER_KNOBS
        deadline = flow.now() + float(SERVER_KNOBS.role_retry_deadline)
        backoff = 0.05
        while True:
            try:
                value = await self.ref.get_reply(request, src)
            except flow.FdbError as e:
                name = e.name
                if name != "broken_promise" or flow.now() >= deadline:
                    if not p.is_set:
                        p.send_error(e)
                    return
                await flow.delay(
                    min(backoff, max(0.0, deadline - flow.now())),
                    TaskPriority.READ_SOCKET)
                backoff = min(backoff * 2.0, 1.0)
                continue
            if not p.is_set:
                p.send(value)
            return


class _Conn:
    """One socket + its reader/writer threads (ref: connectionReader /
    connectionWriter). Outbound frames queue through the writer so the
    scheduler thread never blocks on the kernel; death notifies the
    transport exactly once."""

    def __init__(self, transport: "TcpTransport", sock: Optional[socket.socket],
                 addr=None, handshake_in: bool = False):
        self.transport = transport
        self.sock = sock              # None: connect lazily (client side)
        self.addr = addr
        self.handshake_in = handshake_in
        self.dead = False
        self._wq: deque = deque()
        self._wq_event = threading.Event()
        self._lock = threading.Lock()
        self.pending: set = set()     # req_ids in flight on this conn

    def start(self) -> None:
        threading.Thread(target=self._writer, daemon=True).start()

    def enqueue(self, kind, req_id, token, payload: bytes) -> None:
        with self._lock:
            if self.dead:
                return
            self._wq.append(_HDR.pack(len(payload), kind, req_id, token)
                            + payload)
        self._wq_event.set()

    # -- threads ---------------------------------------------------------
    def _writer(self) -> None:
        try:
            if self.sock is None:
                self.sock = socket.create_connection(
                    self.addr, timeout=CONNECT_TIMEOUT())
                ctx = self.transport.tls_client_ctx()
                if ctx is not None:
                    # TLS handshake before the protocol tag, exactly
                    # where the reference's TLS sits: beneath the
                    # ConnectPacket (FDBLibTLS under FlowTransport)
                    self.sock = ctx.wrap_socket(self.sock)
                self.sock.settimeout(None)
                self.sock.sendall(self.transport.protocol)
            elif self.handshake_in:
                self.sock.settimeout(HANDSHAKE_TIMEOUT())
                ctx = self.transport.tls_server_ctx()
                if ctx is not None:
                    self.sock = ctx.wrap_socket(self.sock,
                                                server_side=True)
                got = _read_exact(self.sock, len(PROTOCOL_VERSION))
                if got != self.transport.protocol:
                    if got is not None and \
                            got[:6] == PROTOCOL_VERSION[:6]:
                        # a versioned peer we don't speak: answer with
                        # OUR tag so a MultiVersion client can pick the
                        # matching library (ref: getServerProtocol)
                        try:
                            self.sock.sendall(self.transport.protocol)
                        except OSError:
                            pass
                    raise OSError("bad handshake")
                self.sock.settimeout(None)
            threading.Thread(target=self._reader, daemon=True).start()
            while True:
                self._wq_event.wait()
                with self._lock:
                    if self.dead:
                        return
                    frame = self._wq.popleft() if self._wq else None
                    if frame is None:
                        self._wq_event.clear()
                        continue
                self.sock.sendall(frame)
        except OSError:
            self._die()

    def _reader(self) -> None:
        try:
            while True:
                hdr = _read_exact(self.sock, _HDR.size)
                if hdr is None:
                    break
                ln, kind, req_id, token = _HDR.unpack(hdr)
                payload = _read_exact(self.sock, ln)
                if payload is None:
                    break
                self.transport._post(("frame", self, kind, req_id, token,
                                      payload))
        finally:
            self._die()

    def _die(self) -> None:
        with self._lock:
            if self.dead:
                return
            self.dead = True
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self._wq_event.set()
        self.transport._post(("dead", self))


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpTransport:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 tls: Optional[TlsConfig] = None,
                 protocol: bytes = None):
        self.host = host
        self.tls = tls
        # the 8-byte protocol tag this transport speaks (ref: the
        # ConnectPacket's protocolVersion). A server answers a
        # mismatched-but-recognizable tag with ITS OWN tag before
        # closing, so a MultiVersion client can discover the cluster's
        # protocol and select the matching versioned library
        # (ref: MultiVersionApi / getServerProtocol)
        self.protocol = protocol or PROTOCOL_VERSION
        assert len(self.protocol) == len(PROTOCOL_VERSION)
        # contexts built once and shared by every connection (cert files
        # are read at transport creation, not per reconnect)
        self._tls_server_ctx = tls.server_context() if tls else None
        self._tls_client_ctx = tls.client_context() if tls else None
        self._streams: Dict[int, TcpRequestStream] = {}
        self._next_token = 1
        self._next_req = 1
        self._pending: Dict[int, Promise] = {}
        #: req_id -> (t0, debug ids) for in-flight TRACED requests: the
        #: traced reply joins them with the server's t1/t2 into one
        #: client-side WireHop event (all four timestamps, both
        #: identities — everything the offset estimator needs)
        self._pending_trace: Dict[int, tuple] = {}
        self._conns: Dict[object, _Conn] = {}   # addr -> client conn
        self._lock = threading.Lock()
        self._inbox: deque = deque()
        self._closing = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]

    def tls_server_ctx(self):
        return self._tls_server_ctx

    def tls_client_ctx(self):
        return self._tls_client_ctx

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()
        flow.spawn(self._reactor(), TaskPriority.READ_SOCKET,
                   name=f"tcp:{self.port}.reactor")

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c._die()

    # -- registration ----------------------------------------------------
    def _register(self, stream: TcpRequestStream) -> int:
        token = self._next_token
        self._next_token += 1
        self._streams[token] = stream
        return token

    def ref(self, host: str, port: int, token: int) -> TcpRef:
        return TcpRef(self, (host, port), token)

    # -- accept thread ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            # handshake + IO happen on the connection's own threads so a
            # stalled peer can never freeze other accepts
            conn = _Conn(self, sock, handshake_in=True)
            conn.start()

    # -- inbox bridging ---------------------------------------------------
    def _post(self, item) -> None:
        with self._lock:
            self._inbox.append(item)

    async def _reactor(self):
        """Drain socket completions into the flow loop (the Net2
        reactor seam in miniature). A malformed frame fails its own
        request — never the reactor."""
        while not self._closing:
            while True:
                with self._lock:
                    item = self._inbox.popleft() if self._inbox else None
                if item is None:
                    break
                try:
                    self._handle(item)
                except Exception as e:  # noqa: BLE001 — isolate frames
                    flow.TraceEvent(
                        "TcpDispatchError", f"tcp:{self.port}",
                        severity=flow.trace.SevWarnAlways).detail(
                        Error=repr(e)).log()
            await flow.delay(flow.SERVER_KNOBS.tcp_reactor_poll_delay,
                             TaskPriority.READ_SOCKET)

    def _handle(self, item) -> None:
        if item[0] == "dead":
            _tag, conn = item
            with self._lock:
                if self._conns.get(conn.addr) is conn:
                    del self._conns[conn.addr]
            for req_id in list(conn.pending):
                p = self._pending.pop(req_id, None)
                self._pending_trace.pop(req_id, None)
                if p is not None and not p.is_set:
                    p.send_error(error("broken_promise"))
            conn.pending.clear()
            return
        _tag, conn, kind, req_id, token, payload = item
        if kind in (K_REQUEST, K_TRACED):
            t_recv = flow.now() if kind == K_TRACED else None
            reply = TcpReply(conn, req_id, t_recv)
            stream = self._streams.get(token)
            if stream is None:
                reply.send_error(error("broken_promise"))
                return
            try:
                request = wire.from_bytes(payload, None)
            except wire.WireError as e:
                reply.send_error(error("unknown_error"))
                raise e
            if kind == K_TRACED:
                # note the sender's open spans BEFORE dispatch, so the
                # role's begin_span for these ids sees its remote parent
                ctx, request = request
                from ..flow import trace as _trace
                for d, sid in ctx.get("spans", ()):
                    if sid is not None:
                        _trace.g_trace_batch.note_remote_parent(
                            d, ctx.get("process", ""), sid)
            stream.stream.send((request, reply))
        else:
            p = self._pending.pop(req_id, None)
            tr = self._pending_trace.pop(req_id, None)
            conn.pending.discard(req_id)
            if p is None or p.is_set:
                return
            try:
                value = wire.from_bytes(payload, None)
            except wire.WireError:
                p.send_error(error("unknown_error"))
                return
            if kind == K_TRACED_REPLY:
                hop, value = value
                if tr is not None:
                    self._emit_wire_hop(tr, hop)
                p.send(value)
            elif kind == K_REPLY:
                p.send(value)
            else:
                p.send_error(error(value))

    @staticmethod
    def _emit_wire_hop(tr, hop) -> None:
        """One client-side WireHop event per traced request/reply pair:
        both process identities plus the four timestamps
        (t0 client-send, t1 server-recv, t2 server-send, t3
        client-recv) — tracemerge estimates the per-process-pair clock
        offset as the median of ((t1-t0)+(t2-t3))/2 over these events
        (the NTP local-clock-offset formula; no trusted wall clock)."""
        t0, ids = tr
        from ..flow import trace as _trace
        flow.TraceEvent("WireHop", str(ids[0])).detail(
            DebugIDs=[str(d) for d in ids],
            Client=_trace.process_name(),
            Server=hop.get("process", ""),
            T0=t0, T1=hop.get("t1"), T2=hop.get("t2"),
            T3=flow.now()).log()

    # -- client side -------------------------------------------------------
    def _request(self, addr, token: int, request,
                 oneway: bool = False) -> Optional[Future]:
        p = Promise()
        # traced envelope only when the knob is armed AND the request
        # samples at least one debug id — everything else keeps the
        # exact pre-knob K_REQUEST bytes
        ctx = _trace_ctx(request) if _trace_armed() else None
        try:
            payload = (wire.to_bytes(request) if ctx is None
                       else wire.to_bytes([ctx, request]))
        except wire.WireError:
            return flow.error_future(error("unknown_error"))
        with self._lock:
            conn = self._conns.get(addr)
            if conn is None or conn.dead:
                conn = _Conn(self, None, addr=addr)
                self._conns[addr] = conn
                fresh = True
            else:
                fresh = False
            req_id = self._next_req
            self._next_req += 1
            if not oneway:
                self._pending[req_id] = p
                if ctx is not None:
                    self._pending_trace[req_id] = (
                        ctx["t0"], tuple(d for d, _sid in ctx["spans"]))
                conn.pending.add(req_id)
        if fresh:
            conn.start()     # connect happens on the writer thread
        conn.enqueue(K_REQUEST if ctx is None else K_TRACED,
                     req_id, token, payload)
        return None if oneway else p.future
