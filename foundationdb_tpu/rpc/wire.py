"""RPC wire format: every message that crosses the (simulated or real)
network serializes to bytes and back.

Reference: flow/serialize.h — the `serializer(ar, ...)` templates give
every RPC struct a byte encoding, and because the real FlowTransport
runs over simulated connections in sim, serialization bugs are caught
by ordinary simulation runs (SURVEY §4: "There is no mock-RPC layer").
This module plays both parts: a compact tagged encoding for the
framework's message vocabulary (NamedTuples over primitives), with
endpoints serialized as (process name, token) the way the reference
ships (address, token) pairs, and a round-trip hook the simulated
network applies to every delivery so nothing unserializable can sneak
into an interface.

Messages that are deliberately NOT wire-safe (the worker registration
carrying the recruitment seam object) opt out via ``__no_wire__``.
"""

from __future__ import annotations

import struct
from typing import Dict, Type

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

# type tags
_NONE, _FALSE, _TRUE, _INT, _BIGINT, _FLOAT, _BYTES, _STR, _TUPLE, \
    _LIST, _NT, _REF, _DICT = range(13)

_REGISTRY: Dict[str, Type] = {}

# encode hot path: the wire round-trip runs on EVERY simulated
# delivery, so the codec is dispatch-table-driven instead of an
# isinstance chain — type(obj) keys straight to its encoder, and each
# registered NamedTuple class precomputes its constant header bytes
# (tag + name + arity) once at registration. Byte format unchanged.
_ENCODERS: Dict[type, object] = {}
_NT_HEADER: Dict[type, bytes] = {}


def _nt_header(cls: Type) -> bytes:
    nb = cls.__name__.encode()
    return (bytes([_NT]) + _U32.pack(len(nb)) + nb
            + _U32.pack(len(cls._fields)))


def _encode_nt(obj, out) -> None:
    out.append(_NT_HEADER[type(obj)])
    for f in obj:
        encode(f, out)


def register_message(cls: Type) -> Type:
    """Register a NamedTuple message type for the wire (decorator)."""
    _REGISTRY[cls.__name__] = cls
    _NT_HEADER[cls] = _nt_header(cls)
    _ENCODERS[cls] = _encode_nt
    return cls


def register_all(module) -> None:
    """Register every NamedTuple class defined in a module."""
    for name in dir(module):
        obj = getattr(module, name)
        if isinstance(obj, type) and issubclass(obj, tuple) and \
                hasattr(obj, "_fields") and obj.__module__ == module.__name__:
            register_message(obj)


def register_module(module_name: str) -> None:
    """One-line footer for RPC-vocabulary modules:
    ``wire.register_module(__name__)``."""
    import sys
    register_all(sys.modules[module_name])


class WireError(TypeError):
    pass


_B_NONE = bytes([_NONE])
_B_FALSE = bytes([_FALSE])
_B_TRUE = bytes([_TRUE])
_B_INT = bytes([_INT])
_B_BIGINT = bytes([_BIGINT])
_B_FLOAT = bytes([_FLOAT])
_B_BYTES = bytes([_BYTES])
_B_STR = bytes([_STR])
_B_TUPLE = bytes([_TUPLE])
_B_LIST = bytes([_LIST])
_B_REF = bytes([_REF])
_B_DICT = bytes([_DICT])


def _encode_none(obj, out):
    out.append(_B_NONE)


def _encode_bool(obj, out):
    out.append(_B_TRUE if obj else _B_FALSE)


def _encode_int(obj, out):
    if -(1 << 63) <= obj < (1 << 63):
        out.append(_B_INT)
        out.append(_I64.pack(obj))
    else:
        b = obj.to_bytes((obj.bit_length() + 15) // 8, "big", signed=True)
        out.append(_B_BIGINT)
        out.append(_U32.pack(len(b)))
        out.append(b)


def _encode_float(obj, out):
    out.append(_B_FLOAT)
    out.append(_F64.pack(obj))


def _encode_bytes(obj, out):
    out.append(_B_BYTES)
    out.append(_U32.pack(len(obj)))
    out.append(bytes(obj))


def _encode_str(obj, out):
    b = obj.encode()
    out.append(_B_STR)
    out.append(_U32.pack(len(b)))
    out.append(b)


def _encode_tuple(obj, out):
    out.append(_B_TUPLE)
    out.append(_U32.pack(len(obj)))
    for f in obj:
        encode(f, out)


def _encode_list(obj, out):
    out.append(_B_LIST)
    out.append(_U32.pack(len(obj)))
    for f in obj:
        encode(f, out)


def _encode_dict(obj, out):
    out.append(_B_DICT)
    out.append(_U32.pack(len(obj)))
    for k, v in obj.items():
        encode(k, out)
        encode(v, out)


_ENCODERS.update({
    type(None): _encode_none,
    bool: _encode_bool,
    int: _encode_int,
    float: _encode_float,
    bytes: _encode_bytes,
    bytearray: _encode_bytes,
    str: _encode_str,
    tuple: _encode_tuple,
    list: _encode_list,
    dict: _encode_dict,
})


def _encode_ref(obj, out):
    ep = obj.endpoint
    nb = ep.process.name.encode()
    out.append(_B_REF)
    out.append(_U32.pack(len(nb)))
    out.append(nb)
    out.append(_I64.pack(ep.token))


def encode(obj, out: list) -> None:
    f = _ENCODERS.get(type(obj))
    if f is not None:
        f(obj, out)
    else:
        _encode_slow(obj, out)


def _encode_slow(obj, out: list) -> None:
    """Types outside the dispatch table: subclasses of the primitives,
    NamedTuples that never registered, NetworkRefs."""
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        if type(obj).__name__ not in _REGISTRY:
            raise WireError(
                f"unregistered message type {type(obj).__name__}")
        # a registered class reaching here was registered under another
        # class object of the same name (module reload): encode by name
        nb = type(obj).__name__.encode()
        out.append(bytes([_NT]))
        out.append(_U32.pack(len(nb)))
        out.append(nb)
        out.append(_U32.pack(len(obj)))
        for f in obj:
            encode(f, out)
    elif isinstance(obj, bool):
        _encode_bool(obj, out)
    elif isinstance(obj, int):
        _encode_int(obj, out)
    elif isinstance(obj, float):
        _encode_float(obj, out)
    elif isinstance(obj, (bytes, bytearray)):
        _encode_bytes(obj, out)
    elif isinstance(obj, str):
        _encode_str(obj, out)
    elif isinstance(obj, tuple):
        _encode_tuple(obj, out)
    elif isinstance(obj, list):
        _encode_list(obj, out)
    elif isinstance(obj, dict):
        _encode_dict(obj, out)
    elif type(obj).__name__ == "NetworkRef":
        # self-installs into the dispatch table on first sight (wire.py
        # cannot import rpc.network at load time — module cycle)
        _ENCODERS[type(obj)] = _encode_ref
        _encode_ref(obj, out)
    else:
        raise WireError(
            f"type {type(obj).__name__} has no wire encoding — register "
            f"the message or mark the request __no_wire__")


def _decode_none(buf, off, net):
    return None, off


def _decode_false(buf, off, net):
    return False, off


def _decode_true(buf, off, net):
    return True, off


def _decode_int(buf, off, net):
    return _I64.unpack_from(buf, off)[0], off + 8


def _decode_bigint(buf, off, net):
    (ln,) = _U32.unpack_from(buf, off)
    off += 4
    return int.from_bytes(buf[off:off + ln], "big", signed=True), off + ln


def _decode_float(buf, off, net):
    return _F64.unpack_from(buf, off)[0], off + 8


def _decode_bytes(buf, off, net):
    (ln,) = _U32.unpack_from(buf, off)
    off += 4
    return bytes(buf[off:off + ln]), off + ln


def _decode_str(buf, off, net):
    (ln,) = _U32.unpack_from(buf, off)
    off += 4
    return buf[off:off + ln].decode(), off + ln


def _decode_tuple(buf, off, net):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    items = []
    for _ in range(n):
        v, off = decode(buf, off, net)
        items.append(v)
    return tuple(items), off


def _decode_list(buf, off, net):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    items = []
    for _ in range(n):
        v, off = decode(buf, off, net)
        items.append(v)
    return items, off


def _decode_dict(buf, off, net):
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    d = {}
    for _ in range(n):
        k, off = decode(buf, off, net)
        v, off = decode(buf, off, net)
        d[k] = v
    return d, off


def _decode_nt(buf, off, net):
    (ln,) = _U32.unpack_from(buf, off)
    off += 4
    name = buf[off:off + ln].decode()
    off += ln
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    fields = []
    for _ in range(n):
        v, off = decode(buf, off, net)
        fields.append(v)
    cls = _REGISTRY.get(name)
    if cls is None:
        raise WireError(f"unregistered message type {name} in decode")
    return cls(*fields), off


def _decode_ref(buf, off, net):
    (ln,) = _U32.unpack_from(buf, off)
    off += 4
    name = buf[off:off + ln].decode()
    off += ln
    (token,) = _I64.unpack_from(buf, off)
    return net.resolve_ref(name, token), off + 8


_DECODERS = [None] * 13
_DECODERS[_NONE] = _decode_none
_DECODERS[_FALSE] = _decode_false
_DECODERS[_TRUE] = _decode_true
_DECODERS[_INT] = _decode_int
_DECODERS[_BIGINT] = _decode_bigint
_DECODERS[_FLOAT] = _decode_float
_DECODERS[_BYTES] = _decode_bytes
_DECODERS[_STR] = _decode_str
_DECODERS[_TUPLE] = _decode_tuple
_DECODERS[_LIST] = _decode_list
_DECODERS[_NT] = _decode_nt
_DECODERS[_REF] = _decode_ref
_DECODERS[_DICT] = _decode_dict


def decode(buf: bytes, off: int, net):
    tag = buf[off]
    if tag > 12:
        raise WireError(f"bad wire tag {tag}")
    return _DECODERS[tag](buf, off + 1, net)


def to_bytes(obj) -> bytes:
    out: list = []
    encode(obj, out)
    return b"".join(out)


def from_bytes(buf: bytes, net):
    v, _off = decode(buf, 0, net)
    return v


def roundtrip(obj, net):
    """encode+decode — the simulated delivery hook."""
    return from_bytes(to_bytes(obj), net)


def wire_safe(obj) -> bool:
    return not getattr(obj, "__no_wire__", False)
