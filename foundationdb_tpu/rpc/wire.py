"""RPC wire format: every message that crosses the (simulated or real)
network serializes to bytes and back.

Reference: flow/serialize.h — the `serializer(ar, ...)` templates give
every RPC struct a byte encoding, and because the real FlowTransport
runs over simulated connections in sim, serialization bugs are caught
by ordinary simulation runs (SURVEY §4: "There is no mock-RPC layer").
This module plays both parts: a compact tagged encoding for the
framework's message vocabulary (NamedTuples over primitives), with
endpoints serialized as (process name, token) the way the reference
ships (address, token) pairs, and a round-trip hook the simulated
network applies to every delivery so nothing unserializable can sneak
into an interface.

Messages that are deliberately NOT wire-safe (the worker registration
carrying the recruitment seam object) opt out via ``__no_wire__``.
"""

from __future__ import annotations

import struct
from typing import Dict, Type

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

# type tags
_NONE, _FALSE, _TRUE, _INT, _BIGINT, _FLOAT, _BYTES, _STR, _TUPLE, \
    _LIST, _NT, _REF, _DICT = range(13)

_REGISTRY: Dict[str, Type] = {}


def register_message(cls: Type) -> Type:
    """Register a NamedTuple message type for the wire (decorator)."""
    _REGISTRY[cls.__name__] = cls
    return cls


def register_all(module) -> None:
    """Register every NamedTuple class defined in a module."""
    for name in dir(module):
        obj = getattr(module, name)
        if isinstance(obj, type) and issubclass(obj, tuple) and \
                hasattr(obj, "_fields") and obj.__module__ == module.__name__:
            _REGISTRY[obj.__name__] = obj


def register_module(module_name: str) -> None:
    """One-line footer for RPC-vocabulary modules:
    ``wire.register_module(__name__)``."""
    import sys
    register_all(sys.modules[module_name])


class WireError(TypeError):
    pass


def encode(obj, out: list) -> None:
    if obj is None:
        out.append(bytes([_NONE]))
    elif obj is False:
        out.append(bytes([_FALSE]))
    elif obj is True:
        out.append(bytes([_TRUE]))
    elif isinstance(obj, int):
        if -(1 << 63) <= obj < (1 << 63):
            out.append(bytes([_INT]))
            out.append(_I64.pack(obj))
        else:
            b = obj.to_bytes((obj.bit_length() + 15) // 8, "big",
                             signed=True)
            out.append(bytes([_BIGINT]))
            out.append(_U32.pack(len(b)))
            out.append(b)
    elif isinstance(obj, float):
        out.append(bytes([_FLOAT]))
        out.append(_F64.pack(obj))
    elif isinstance(obj, (bytes, bytearray)):
        out.append(bytes([_BYTES]))
        out.append(_U32.pack(len(obj)))
        out.append(bytes(obj))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(bytes([_STR]))
        out.append(_U32.pack(len(b)))
        out.append(b)
    elif isinstance(obj, tuple) and hasattr(obj, "_fields"):
        name = type(obj).__name__
        if name not in _REGISTRY:
            raise WireError(f"unregistered message type {name}")
        nb = name.encode()
        out.append(bytes([_NT]))
        out.append(_U32.pack(len(nb)))
        out.append(nb)
        out.append(_U32.pack(len(obj)))
        for f in obj:
            encode(f, out)
    elif isinstance(obj, tuple):
        out.append(bytes([_TUPLE]))
        out.append(_U32.pack(len(obj)))
        for f in obj:
            encode(f, out)
    elif isinstance(obj, list):
        out.append(bytes([_LIST]))
        out.append(_U32.pack(len(obj)))
        for f in obj:
            encode(f, out)
    elif isinstance(obj, dict):
        out.append(bytes([_DICT]))
        out.append(_U32.pack(len(obj)))
        for k, v in obj.items():
            encode(k, out)
            encode(v, out)
    elif type(obj).__name__ == "NetworkRef":
        ep = obj.endpoint
        nb = ep.process.name.encode()
        out.append(bytes([_REF]))
        out.append(_U32.pack(len(nb)))
        out.append(nb)
        out.append(_I64.pack(ep.token))
    else:
        raise WireError(
            f"type {type(obj).__name__} has no wire encoding — register "
            f"the message or mark the request __no_wire__")


def decode(buf: bytes, off: int, net):
    tag = buf[off]
    off += 1
    if tag == _NONE:
        return None, off
    if tag == _FALSE:
        return False, off
    if tag == _TRUE:
        return True, off
    if tag == _INT:
        (v,) = _I64.unpack_from(buf, off)
        return v, off + 8
    if tag == _BIGINT:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        return int.from_bytes(buf[off:off + ln], "big", signed=True), \
            off + ln
    if tag == _FLOAT:
        (v,) = _F64.unpack_from(buf, off)
        return v, off + 8
    if tag == _BYTES:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        return bytes(buf[off:off + ln]), off + ln
    if tag == _STR:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        return buf[off:off + ln].decode(), off + ln
    if tag in (_TUPLE, _LIST):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        items = []
        for _ in range(n):
            v, off = decode(buf, off, net)
            items.append(v)
        return (tuple(items) if tag == _TUPLE else items), off
    if tag == _DICT:
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        d = {}
        for _ in range(n):
            k, off = decode(buf, off, net)
            v, off = decode(buf, off, net)
            d[k] = v
        return d, off
    if tag == _NT:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        name = buf[off:off + ln].decode()
        off += ln
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        fields = []
        for _ in range(n):
            v, off = decode(buf, off, net)
            fields.append(v)
        cls = _REGISTRY.get(name)
        if cls is None:
            raise WireError(f"unregistered message type {name} in decode")
        return cls(*fields), off
    if tag == _REF:
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        name = buf[off:off + ln].decode()
        off += ln
        (token,) = _I64.unpack_from(buf, off)
        off += 8
        return net.resolve_ref(name, token), off + 0
    raise WireError(f"bad wire tag {tag}")


def to_bytes(obj) -> bytes:
    out: list = []
    encode(obj, out)
    return b"".join(out)


def from_bytes(buf: bytes, net):
    v, _off = decode(buf, 0, net)
    return v


def roundtrip(obj, net):
    """encode+decode — the simulated delivery hook."""
    return from_bytes(to_bytes(obj), net)


def wire_safe(obj) -> bool:
    return not getattr(obj, "__no_wire__", False)
