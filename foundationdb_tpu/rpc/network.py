"""Deterministic simulated network: processes, endpoints, kills, clogs.

Reference behaviors re-implemented (not ported):
  - token-addressed delivery to typed request streams
    (fdbrpc/FlowTransport.actor.cpp:48-113 EndpointMap, :517 deliver)
  - request/reply as paired endpoints: the reply rides back through the
    network with its own latency (fdbrpc/fdbrpc.h ReplyPromise /
    networksender.actor.h)
  - simulated latency per message and clogged links
    (fdbrpc/sim2.actor.cpp:127-160 SimClogging, :176 Sim2Conn)
  - process kill semantics: in-flight requests and replies owned by the
    dead process break; new sends to it hang until failure detection or
    break immediately, per knob (fdbrpc/sim2.actor.cpp:1222
    killProcess_internal; broken_promise surfaces to callers the way a
    closed connection does)
  - machine model grouping processes (fdbrpc/simulator.h:47-147)

Everything randomized draws from the flow deterministic RNG, so a seed
replays the identical message schedule.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..flow import error
from ..flow.actors import PromiseStream
from ..flow.future import Future, Promise
from ..flow.rng import buggify
from ..flow.scheduler import Scheduler, TaskPriority


class Endpoint:
    """A delivery token: (process, stream id)."""

    __slots__ = ("process", "token")

    def __init__(self, process: "SimProcess", token: int):
        self.process = process
        self.token = token

    def __repr__(self):
        return f"Endpoint({self.process.name}:{self.token})"


class SimProcess:
    """A simulated process hosting request streams (ref: simulator.h
    ProcessInfo). Kill breaks everything it owns."""

    def __init__(self, net: "SimNetwork", name: str, machine: str = "",
                 zone: str = "", dc: str = ""):
        self.net = net
        self.name = name
        self.machine = machine or name
        # failure-domain locality (ref: flow/Locality.h LocalityData —
        # machineid ⊂ zoneid ⊂ dcid). Defaults collapse to the legacy
        # one-process-per-machine model: zone == machine, one dc.
        self.zone = zone or self.machine
        self.dc = dc or "dc0"
        self.alive = True
        self._streams: Dict[int, PromiseStream] = {}
        self._pending_replies: list[Promise] = []
        self._on_kill: list[Callable[[], None]] = []

    def register(self, stream: PromiseStream) -> Endpoint:
        token = self.net._next_token()
        self._streams[token] = stream
        return Endpoint(self, token)

    def on_kill(self, fn: Callable[[], None]) -> None:
        self._on_kill.append(fn)

    def _track_reply(self, p: Promise) -> None:
        self._pending_replies.append(p)
        if len(self._pending_replies) > 64:  # drop settled entries
            self._pending_replies = [
                q for q in self._pending_replies if not q.is_set]

    def __repr__(self):
        return f"SimProcess({self.name}, alive={self.alive})"


class RequestStream:
    """Server side of a typed endpoint: a PromiseStream of envelopes.

    Each received item is ``(request, reply)`` where ``reply`` is a
    Promise whose send travels back through the network."""

    def __init__(self, process: SimProcess):
        self.stream = PromiseStream()
        self.endpoint = process.register(self.stream)

    def ref(self) -> "NetworkRef":
        return NetworkRef(self.endpoint)

    def pop(self) -> Future:
        """Future of the next (request, reply) pair (ref: waitNext)."""
        return self.stream.stream.pop()

    def close(self) -> None:
        """Deregister the endpoint: later requests break with
        broken_promise, exactly like a closed connection, and requests
        already queued but never popped break too (ref: endpoint removal
        from the EndpointMap when a role's actors die)."""
        self.endpoint.process._streams.pop(self.endpoint.token, None)
        q = self.stream.stream._queue
        while q:
            item = q.popleft()
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[1] is not None:
                item[1].send_error(error("broken_promise"))


class NetworkRef:
    """Client handle to a remote RequestStream (ref: RequestStream<T> as
    carried inside interface structs)."""

    __slots__ = ("endpoint",)

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint

    def get_reply(self, request: Any, src: SimProcess) -> Future:
        """Send and return a Future of the reply (ref: getReply pattern,
        fdbrpc/fdbrpc.h)."""
        return self.endpoint.process.net.send_request(
            src, self.endpoint, request)

    def send(self, request: Any, src: SimProcess) -> None:
        """Fire-and-forget (best-effort datagram semantics)."""
        self.endpoint.process.net.send_oneway(src, self.endpoint, request)


class SimNetwork:
    """The simulated transport + fault API (ref: sim2.actor.cpp)."""

    def __init__(self, sched: Scheduler, rng,
                 min_latency: float = None,
                 max_latency: float = None, serialize: bool = True):
        from ..flow import SERVER_KNOBS
        if min_latency is None:
            min_latency = SERVER_KNOBS.sim_latency_min
        if max_latency is None:
            max_latency = SERVER_KNOBS.sim_latency_max
        self.sched = sched
        self.rng = rng
        self.min_latency = min_latency
        self.max_latency = max_latency
        # every delivered message round-trips through the wire format,
        # so serialization bugs surface in ordinary sim runs exactly as
        # the reference's real-FlowTransport-over-sim-connections does
        # (flow/serialize.h; SURVEY §4 "no mock-RPC layer")
        self.serialize = serialize
        self.processes: Dict[str, SimProcess] = {}
        self._tombstones: Dict[str, SimProcess] = {}
        self._token = 0
        #: machine -> disk namespace factory; None = in-memory SimDisk.
        #: A cluster on REAL storage installs RealDisk here.
        self.disk_factory = None
        # (src_machine, dst_machine) -> unclog time
        self._clogged: Dict[Tuple[str, str], float] = {}
        self.messages_sent = 0
        self.messages_dropped = 0
        self.disks: Dict[str, "SimDisk"] = {}

    # -- topology -------------------------------------------------------
    def new_process(self, name: str, machine: str = "", zone: str = "",
                    dc: str = "") -> SimProcess:
        p = SimProcess(self, name, machine, zone, dc)
        self.processes[name] = p
        return p

    def processes_on(self, machine: str) -> list:
        """Live processes sharing a machine (ref: simulator.h
        MachineInfo.processes — machines group processes so failures
        correlate)."""
        return [p for p in self.processes.values()
                if p.alive and p.machine == machine]

    def kill_machine(self, machine: str) -> list:
        """Correlated failure: kill every live process on the machine
        at once (ref: killMachine, sim2.actor.cpp:1717 — machine-level
        kills take out all co-located processes and their unsynced
        writes in one power-loss event). Returns the killed names."""
        victims = self.processes_on(machine)
        for p in victims:
            self.kill(p)
        return [p.name for p in victims]

    def disk(self, machine: str) -> "SimDisk":
        """The machine's persistent file namespace (survives kills).
        `disk_factory` (set by a cluster running on REAL storage)
        swaps in on-disk namespaces behind the same seam."""
        d = self.disks.get(machine)
        if d is None:
            if self.disk_factory is not None:
                d = self.disk_factory(machine)
            else:
                from .disk import SimDisk
                d = SimDisk(self, machine)
            self.disks[machine] = d
        return d

    def _next_token(self) -> int:
        self._token += 1
        return self._token

    def resolve_ref(self, process_name: str, token: int) -> "NetworkRef":
        """Rebuild a NetworkRef from its wire form (process name +
        token — ref: FlowTransport's (address, token) endpoints). A
        name that no longer exists resolves to a dead tombstone so
        sends break the same way a closed connection would."""
        p = self.processes.get(process_name)
        if p is None:
            p = self._tombstones.get(process_name)
            if p is None:
                p = SimProcess(self, process_name, process_name)
                p.alive = False
                self._tombstones[process_name] = p
        return NetworkRef(Endpoint(p, token))

    def _wire(self, obj):
        if not self.serialize:
            return obj
        from . import wire
        if not wire.wire_safe(obj):
            return obj
        return wire.roundtrip(obj, self)

    # -- faults ---------------------------------------------------------
    def kill(self, process: SimProcess) -> None:
        """Kill a process: break its owned replies; its streams stop
        receiving; its open files lose unsynced writes
        (ref: killProcess_internal, sim2.actor.cpp:1222 +
        AsyncFileNonDurable power-loss semantics)."""
        if not process.alive:
            return
        process.alive = False
        for fn in process._on_kill:
            fn()
        for p in process._pending_replies:
            if not p.is_set:
                p.send_error(error("broken_promise"))
        process._pending_replies.clear()
        d = self.disks.get(process.machine)
        if d is not None:
            d.power_loss(self.rng, owner=process)

    def reboot(self, name: str) -> SimProcess:
        """Kill (if alive) and re-create a process of the same name on
        the same machine. The caller restarts role actors on the new
        process; they recover from the machine's surviving files
        (ref: simulatedFDBDRebooter, SimulatedCluster.actor.cpp:194)."""
        old = self.processes[name]
        self.kill(old)
        return self.new_process(name, old.machine, old.zone, old.dc)

    def clog_pair(self, a: str, b: str, seconds: float) -> None:
        """Delay all messages between two machines until now+seconds
        (ref: clogPair, sim2.actor.cpp:1532)."""
        until = self.sched.now() + seconds
        for k in ((a, b), (b, a)):
            self._clogged[k] = max(self._clogged.get(k, 0.0), until)

    def _delivery_delay(self, src: SimProcess, dst: SimProcess) -> float:
        lat = self.min_latency + self.rng.random01() * (
            self.max_latency - self.min_latency)
        if buggify("net/extra_latency"):
            # occasional pathological latency: reorders far more
            # aggressively than the uniform draw (ref: sim2's BUGGIFY'd
            # connection delays)
            from ..flow import SERVER_KNOBS
            lat += self.rng.random01() * SERVER_KNOBS.sim_clog_extra_latency
        key = (src.machine, dst.machine)
        unclog = self._clogged.get(key, 0.0)
        now = self.sched.now()
        if unclog > now:
            lat += unclog - now
        return lat

    # -- delivery -------------------------------------------------------
    def send_request(self, src: SimProcess, dst: Endpoint, request) -> Future:
        reply = Promise()
        dst.process._track_reply(reply)
        self._deliver(src, dst, (self._wire(request),
                                 _NetReply(self, dst.process, src, reply)),
                      reply)
        return reply.future

    def send_oneway(self, src: SimProcess, dst: Endpoint, request) -> None:
        request = self._wire(request)
        self._deliver(src, dst, (request, None), None)
        if buggify("net/duplicate_oneway"):
            # best-effort datagrams may be delivered twice (receivers
            # must be idempotent, e.g. TLog pops)
            self._deliver(src, dst, (request, None), None)

    def _deliver(self, src: SimProcess, dst: Endpoint, item,
                 reply: Optional[Promise]) -> None:
        self.messages_sent += 1
        if not src.alive:
            return  # a dead process sends nothing
        delay = self._delivery_delay(src, dst.process)
        timer = self.sched.delay(delay, TaskPriority.DEFAULT_ENDPOINT)

        def on_time(_f):
            if not dst.process.alive:
                # connection failure surfaces as broken_promise to the
                # requester (after the latency, like a RST would)
                self.messages_dropped += 1
                if reply is not None and not reply.is_set:
                    reply.send_error(error("broken_promise"))
                return
            stream = dst.process._streams.get(dst.token)
            if stream is None:
                if reply is not None and not reply.is_set:
                    reply.send_error(error("broken_promise"))
                return
            stream.send(item)

        timer.on_ready(on_time)


class _NetReply:
    """Reply promise that routes back through the network with latency.

    Breaks (broken_promise) if the replying process dies first — tracked
    via SimProcess._pending_replies."""

    __slots__ = ("net", "owner", "dst", "promise")

    def __init__(self, net: SimNetwork, owner: SimProcess, dst: SimProcess,
                 promise: Promise):
        self.net = net
        self.owner = owner  # the serving process
        self.dst = dst      # the original requester
        self.promise = promise

    def send(self, value=None) -> None:
        if self.promise.is_set:
            return
        if not self.owner.alive:
            return  # the kill path already broke the promise
        value = self.net._wire(value)
        delay = self.net._delivery_delay(self.owner, self.dst)
        timer = self.net.sched.delay(delay, TaskPriority.DEFAULT_PROMISE_ENDPOINT)
        p = self.promise

        def on_time(_f, p=p, value=value):
            if not p.is_set:
                p.send(value)

        timer.on_ready(on_time)

    def send_error(self, err) -> None:
        if self.promise.is_set:
            return
        if not self.owner.alive:
            return
        delay = self.net._delivery_delay(self.owner, self.dst)
        timer = self.net.sched.delay(delay, TaskPriority.DEFAULT_PROMISE_ENDPOINT)
        p = self.promise

        def on_time(_f, p=p, err=err):
            if not p.is_set:
                p.send_error(err)

        timer.on_ready(on_time)
