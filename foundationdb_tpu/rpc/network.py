"""Deterministic simulated network: processes, endpoints, kills, clogs,
partitions, and swizzled links.

Reference behaviors re-implemented (not ported):
  - token-addressed delivery to typed request streams
    (fdbrpc/FlowTransport.actor.cpp:48-113 EndpointMap, :517 deliver)
  - request/reply as paired endpoints: the reply rides back through the
    network with its own latency (fdbrpc/fdbrpc.h ReplyPromise /
    networksender.actor.h)
  - simulated latency per message and clogged links
    (fdbrpc/sim2.actor.cpp:127-160 SimClogging, :176 Sim2Conn), plus
    one-sided send/recv clogs (clogSendFor/clogRecvFor) that apply to
    in-flight REPLIES too — a reply's latency is drawn at reply time,
    so clogging after the request went out still delays the answer
  - bidirectional machine-set partitions with healing: while
    partitioned, a crossing message never arrives and its reply breaks
    after the wire latency, exactly like a connection reset — failure
    detection (which pings over this network) therefore sees a
    partitioned machine as down (ref: sim2's connection-failure
    injection + the partition workloads)
  - per-link "swizzle": a window during which messages on the link draw
    pathological extra latency (aggressive reordering) and one-way
    datagrams may be delivered twice (ref: the swizzled-clogging
    workloads, sim2.actor.cpp)
  - process kill semantics: in-flight requests and replies owned by the
    dead process break; new sends to it hang until failure detection or
    break immediately, per knob (fdbrpc/sim2.actor.cpp:1222
    killProcess_internal; broken_promise surfaces to callers the way a
    closed connection does)
  - machine model grouping processes (fdbrpc/simulator.h:47-147)

Everything randomized draws from the flow deterministic RNG, so a seed
replays the identical message schedule. Every injected fault is
recorded in `chaos_log`/`chaos_counters` (see `chaos_note`): the same
seed must produce the identical fault schedule, and the chaos tests pin
that by comparing the logs of two runs.
"""

from __future__ import annotations

from collections import deque as _deque
from typing import Any, Callable, Dict, Optional, Tuple

from ..flow import error
from ..flow.actors import PromiseStream
from ..flow.future import Future, Promise
from ..flow.rng import buggify
from ..flow.scheduler import Scheduler


class Endpoint:
    """A delivery token: (process, stream id)."""

    __slots__ = ("process", "token")

    def __init__(self, process: "SimProcess", token: int):
        self.process = process
        self.token = token

    def __repr__(self):
        return f"Endpoint({self.process.name}:{self.token})"


class SimProcess:
    """A simulated process hosting request streams (ref: simulator.h
    ProcessInfo). Kill breaks everything it owns."""

    def __init__(self, net: "SimNetwork", name: str, machine: str = "",
                 zone: str = "", dc: str = ""):
        self.net = net
        self.name = name
        self.machine = machine or name
        # failure-domain locality (ref: flow/Locality.h LocalityData —
        # machineid ⊂ zoneid ⊂ dcid). Defaults collapse to the legacy
        # one-process-per-machine model: zone == machine, one dc.
        self.zone = zone or self.machine
        self.dc = dc or "dc0"
        self.alive = True
        self._streams: Dict[int, PromiseStream] = {}
        self._pending_replies: "_deque[Promise]" = _deque()
        self._on_kill: list[Callable[[], None]] = []

    def register(self, stream: PromiseStream) -> Endpoint:
        token = self.net._next_token()
        self._streams[token] = stream
        return Endpoint(self, token)

    def on_kill(self, fn: Callable[[], None]) -> None:
        self._on_kill.append(fn)

    def _track_reply(self, p: Promise) -> None:
        pr = self._pending_replies
        pr.append(p)
        # drop settled entries from the FRONT (replies settle roughly
        # in send order, so popleft is O(1) — the old periodic
        # full-list rebuild re-scanned 64 entries on every 65th send);
        # a long-pending head falls back to the bounded full sweep
        while pr and pr[0].is_set:
            pr.popleft()
        if len(pr) > 4096:
            self._pending_replies = _deque(
                q for q in pr if not q.is_set)

    def __repr__(self):
        return f"SimProcess({self.name}, alive={self.alive})"


class RequestStream:
    """Server side of a typed endpoint: a PromiseStream of envelopes.

    Each received item is ``(request, reply)`` where ``reply`` is a
    Promise whose send travels back through the network."""

    def __init__(self, process: SimProcess):
        self.stream = PromiseStream()
        self.endpoint = process.register(self.stream)

    def ref(self) -> "NetworkRef":
        return NetworkRef(self.endpoint)

    def pop(self) -> Future:
        """Future of the next (request, reply) pair (ref: waitNext)."""
        return self.stream.stream.pop()

    def close(self) -> None:
        """Deregister the endpoint: later requests break with
        broken_promise, exactly like a closed connection, and requests
        already queued but never popped break too (ref: endpoint removal
        from the EndpointMap when a role's actors die)."""
        self.endpoint.process._streams.pop(self.endpoint.token, None)
        q = self.stream.stream._queue
        while q:
            item = q.popleft()
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[1] is not None:
                item[1].send_error(error("broken_promise"))


class NetworkRef:
    """Client handle to a remote RequestStream (ref: RequestStream<T> as
    carried inside interface structs)."""

    __slots__ = ("endpoint",)

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint

    def get_reply(self, request: Any, src: SimProcess) -> Future:
        """Send and return a Future of the reply (ref: getReply pattern,
        fdbrpc/fdbrpc.h)."""
        return self.endpoint.process.net.send_request(
            src, self.endpoint, request)

    def send(self, request: Any, src: SimProcess) -> None:
        """Fire-and-forget (best-effort datagram semantics)."""
        self.endpoint.process.net.send_oneway(src, self.endpoint, request)


class SimNetwork:
    """The simulated transport + fault API (ref: sim2.actor.cpp)."""

    def __init__(self, sched: Scheduler, rng,
                 min_latency: float = None,
                 max_latency: float = None, serialize: bool = True):
        from ..flow import SERVER_KNOBS
        if min_latency is None:
            min_latency = SERVER_KNOBS.sim_latency_min
        if max_latency is None:
            max_latency = SERVER_KNOBS.sim_latency_max
        self.sched = sched
        self.rng = rng
        self.min_latency = min_latency
        self.max_latency = max_latency
        # every delivered message round-trips through the wire format,
        # so serialization bugs surface in ordinary sim runs exactly as
        # the reference's real-FlowTransport-over-sim-connections does
        # (flow/serialize.h; SURVEY §4 "no mock-RPC layer")
        self.serialize = serialize
        self.processes: Dict[str, SimProcess] = {}
        self._tombstones: Dict[str, SimProcess] = {}
        self._token = 0
        #: machine -> disk namespace factory; None = in-memory SimDisk.
        #: A cluster on REAL storage installs RealDisk here.
        self.disk_factory = None
        # (src_machine, dst_machine) -> unclog time
        self._clogged: Dict[Tuple[str, str], float] = {}
        # one-sided clogs: machine -> unclog time (ref: clogSendFor /
        # clogRecvFor, sim2.actor.cpp)
        self._clog_send: Dict[str, float] = {}
        self._clog_recv: Dict[str, float] = {}
        # (src_machine, dst_machine) -> swizzle-window end time
        self._swizzled: Dict[Tuple[str, str], float] = {}
        # partition id -> (machine set A, machine set B); messages
        # crossing any live partition never arrive
        self._partitions: Dict[int, Tuple[frozenset, frozenset]] = {}
        self._next_partition = 0
        self.messages_sent = 0
        self.messages_dropped = 0
        self.messages_duplicated = 0
        # the chaos plane's deterministic fault record: every injected
        # fault appends (sim_time, kind, detail) here and bumps a
        # counter — the seed-replay tests compare two runs' logs, and
        # status.cluster.chaos surfaces the counters (bounded so a long
        # attrition run cannot grow memory without bound)
        self.chaos_log: list = []
        self.chaos_counters: Dict[str, int] = {}
        self.chaos_scenarios: Dict[str, int] = {}
        self.chaos_log_max = 4096
        self.chaos_log_dropped = 0
        self.disks: Dict[str, "SimDisk"] = {}
        # sim-perf message accounting (the SIM_TASK_STATS plane's
        # network half — ROADMAP item 6 names per-message allocation
        # as a run-loop hot path): armed via arm_message_stats(), each
        # delivery bumps a bounded per-request-type counter. None =
        # off, zero hot-path cost; the delivery-timer / ready-backlog
        # population gauges are pull-computed from the scheduler's
        # heaps at report time, never maintained per message.
        self.msg_stats: Optional[Dict[str, int]] = None
        self._msg_stats_max = 128
        self.msg_stats_dropped = 0
        # wire-path fast paths (ISSUE 12's allocation-lean wire front):
        # the knobs object is reset in place, so binding it once is
        # safe and saves a module import per delivery; the wire cache
        # holds the canonical decoded instance per FIELD-LESS message
        # type (typed polls/pings round-trip to an equal instance)
        self._knobs = SERVER_KNOBS
        self._wire_cache: Dict[type, object] = {}

    # -- sim-perf message accounting ------------------------------------
    def arm_message_stats(self, max_types: Optional[int] = None) -> None:
        """Arm per-request-type delivery counting (bounded table)."""
        if max_types is None:
            try:
                from ..flow import SERVER_KNOBS
                max_types = int(SERVER_KNOBS.sim_msg_stats_max_types)
            except Exception:
                max_types = 128
        self._msg_stats_max = max(1, max_types)
        self.msg_stats = {}
        self.msg_stats_dropped = 0

    def _count_msg(self, type_name: str) -> None:
        # lint-style oracle, armed mode only (this method never runs
        # with the plane off): a `NoneType` row means a bare-payload
        # request went out untyped — give it a typed envelope in
        # server/types.py instead of shipping None (ISSUE 12; the row
        # also defeats per-type attribution, folding every bare poll
        # into one anonymous bucket)
        assert type_name != "NoneType", (
            "untyped (None-payload) message delivery — wrap the request "
            "in a typed wire envelope (see server/types.py PingRequest "
            "and friends)")
        ms = self.msg_stats
        if type_name in ms:
            ms[type_name] += 1
        elif len(ms) < self._msg_stats_max:
            ms[type_name] = 1
        else:
            self.msg_stats_dropped += 1
            ms["(other)"] = ms.get("(other)", 0) + 1

    def message_stats_report(self, top_k: Optional[int] = None) -> dict:
        """-> {armed, types: [{type, count}] (busiest first),
        dropped_types, messages_*, timers_now, ready_now}. The gauges
        are read live from the scheduler heaps (every in-flight
        delivery rides a timer, so the timer heap IS the delivery
        queue plus role timers)."""
        types = sorted(((t, n) for t, n in (self.msg_stats or {}).items()),
                       key=lambda kv: (-kv[1], kv[0]))
        if top_k is not None:
            types = types[:top_k]
        return {
            "armed": int(self.msg_stats is not None),
            "types": [{"type": t, "count": n} for t, n in types],
            "dropped_types": self.msg_stats_dropped,
            "messages_sent": self.messages_sent,
            "messages_dropped": self.messages_dropped,
            "messages_duplicated": self.messages_duplicated,
            "timers_now": len(self.sched._timers),
            "ready_now": len(self.sched._ready),
        }

    def chaos_note(self, kind: str, **detail) -> None:
        """Record one injected fault (the shared chaos accounting every
        primitive feeds — see server/chaos.py for the merged schema)."""
        self.chaos_counters[kind] = self.chaos_counters.get(kind, 0) + 1
        if len(self.chaos_log) < self.chaos_log_max:
            self.chaos_log.append(
                (round(self.sched.now(), 6), kind, detail))
        else:
            self.chaos_log_dropped += 1
        from ..flow import trace
        trace.TraceEvent("ChaosEvent", severity=trace.SevWarnAlways) \
            .detail(Kind=kind, **{k.capitalize(): v
                                  for k, v in detail.items()}).log()

    # -- topology -------------------------------------------------------
    def new_process(self, name: str, machine: str = "", zone: str = "",
                    dc: str = "") -> SimProcess:
        p = SimProcess(self, name, machine, zone, dc)
        self.processes[name] = p
        return p

    def processes_on(self, machine: str) -> list:
        """Live processes sharing a machine (ref: simulator.h
        MachineInfo.processes — machines group processes so failures
        correlate)."""
        return [p for p in self.processes.values()
                if p.alive and p.machine == machine]

    def kill_machine(self, machine: str) -> list:
        """Correlated failure: kill every live process on the machine
        at once (ref: killMachine, sim2.actor.cpp:1717 — machine-level
        kills take out all co-located processes and their unsynced
        writes in one power-loss event). Returns the killed names."""
        victims = self.processes_on(machine)
        if victims:
            self.chaos_note("machine_power_loss", machine=machine,
                            victims=len(victims))
        for p in victims:
            self.kill(p)
        return [p.name for p in victims]

    def disk(self, machine: str) -> "SimDisk":
        """The machine's persistent file namespace (survives kills).
        `disk_factory` (set by a cluster running on REAL storage)
        swaps in on-disk namespaces behind the same seam."""
        d = self.disks.get(machine)
        if d is None:
            if self.disk_factory is not None:
                d = self.disk_factory(machine)
            else:
                from .disk import SimDisk
                d = SimDisk(self, machine)
            self.disks[machine] = d
        return d

    def _next_token(self) -> int:
        self._token += 1
        return self._token

    def resolve_ref(self, process_name: str, token: int) -> "NetworkRef":
        """Rebuild a NetworkRef from its wire form (process name +
        token — ref: FlowTransport's (address, token) endpoints). A
        name that no longer exists resolves to a dead tombstone so
        sends break the same way a closed connection would."""
        p = self.processes.get(process_name)
        if p is None:
            p = self._tombstones.get(process_name)
            if p is None:
                p = SimProcess(self, process_name, process_name)
                p.alive = False
                self._tombstones[process_name] = p
        return NetworkRef(Endpoint(p, token))

    def _wire(self, obj):
        if not self.serialize:
            return obj
        if obj is None:
            return None   # bare reply payloads: nothing to serialize
        # field-less registered messages (typed polls/pings) round-trip
        # to an equal instance every time: prove it once per type, then
        # serve the cached decoded instance — the serialization oracle
        # still holds (an unregistered type fails the first round trip)
        cached = self._wire_cache.get(type(obj))
        if cached is not None:
            return cached
        from . import wire
        if not wire.wire_safe(obj):
            return obj
        rt = wire.roundtrip(obj, self)
        t = type(obj)
        if getattr(t, "_fields", None) == () and type(rt) is t:
            self._wire_cache[t] = rt
        return rt

    # -- faults ---------------------------------------------------------
    def kill(self, process: SimProcess) -> None:
        """Kill a process: break its owned replies; its streams stop
        receiving; its open files lose unsynced writes
        (ref: killProcess_internal, sim2.actor.cpp:1222 +
        AsyncFileNonDurable power-loss semantics)."""
        if not process.alive:
            return
        self.chaos_note("kill", process=process.name,
                        machine=process.machine)
        process.alive = False
        for fn in process._on_kill:
            fn()
        for p in process._pending_replies:
            if not p.is_set:
                p.send_error(error("broken_promise"))
        process._pending_replies.clear()
        d = self.disks.get(process.machine)
        if d is not None:
            d.power_loss(self.rng, owner=process)

    def reboot(self, name: str) -> SimProcess:
        """Kill (if alive) and re-create a process of the same name on
        the same machine. The caller restarts role actors on the new
        process; they recover from the machine's surviving files
        (ref: simulatedFDBDRebooter, SimulatedCluster.actor.cpp:194)."""
        old = self.processes[name]
        self.kill(old)
        self.chaos_note("reboot", process=name, machine=old.machine)
        return self.new_process(name, old.machine, old.zone, old.dc)

    def clog_pair(self, a: str, b: str, seconds: float) -> None:
        """Delay all messages between two machines until now+seconds
        (ref: clogPair, sim2.actor.cpp:1532)."""
        until = self.sched.now() + seconds
        for k in ((a, b), (b, a)):
            self._clogged[k] = max(self._clogged.get(k, 0.0), until)
        self.chaos_note("clog_pair", a=a, b=b, seconds=round(seconds, 6))

    def clog_send(self, machine: str, seconds: float) -> None:
        """Delay everything the machine SENDS until now+seconds,
        replies included — a reply's latency is drawn at reply time, so
        an in-flight request's answer honors a clog installed after the
        request went out (ref: clogSendFor, sim2.actor.cpp)."""
        until = self.sched.now() + seconds
        self._clog_send[machine] = max(
            self._clog_send.get(machine, 0.0), until)
        self.chaos_note("clog_send", machine=machine,
                        seconds=round(seconds, 6))

    def clog_recv(self, machine: str, seconds: float) -> None:
        """Delay everything the machine RECEIVES until now+seconds
        (ref: clogRecvFor, sim2.actor.cpp)."""
        until = self.sched.now() + seconds
        self._clog_recv[machine] = max(
            self._clog_recv.get(machine, 0.0), until)
        self.chaos_note("clog_recv", machine=machine,
                        seconds=round(seconds, 6))

    def partition(self, machines, others=None) -> int:
        """Bidirectional partition: no message crosses between the two
        machine sets until heal(). `others` defaults to every machine
        not in `machines` — including coordinators, the CC, and
        clients, so isolating a minority really isolates it. Crossing
        requests break (broken_promise) after the wire latency, like a
        reset connection, which is what failure detection keys on.
        Returns a partition id for heal()."""
        a = frozenset(machines)
        if others is None:
            others = {p.machine for p in self.processes.values()} - a
        b = frozenset(others) - a
        pid = self._next_partition
        self._next_partition += 1
        self._partitions[pid] = (a, b)
        self.chaos_note("partition", id=pid, minority=sorted(a),
                        majority_size=len(b))
        return pid

    def heal(self, pid: Optional[int] = None) -> None:
        """Remove one partition (or all of them)."""
        if pid is None:
            healed = sorted(self._partitions)
            self._partitions.clear()
        else:
            healed = [pid] if self._partitions.pop(pid, None) else []
        for h in healed:
            self.chaos_note("heal", id=h)

    def partitioned(self, m1: str, m2: str) -> bool:
        for a, b in self._partitions.values():
            if (m1 in a and m2 in b) or (m1 in b and m2 in a):
                return True
        return False

    def swizzle(self, a: str, b: str, seconds: float = None) -> None:
        """Open a swizzle window on the link: messages draw extra
        reorder latency (CHAOS_SWIZZLE_LATENCY spread) and one-way
        datagrams may deliver twice, until the window expires."""
        from ..flow import SERVER_KNOBS
        if seconds is None:
            seconds = SERVER_KNOBS.chaos_swizzle_seconds
        until = self.sched.now() + seconds
        for k in ((a, b), (b, a)):
            self._swizzled[k] = max(self._swizzled.get(k, 0.0), until)
        self.chaos_note("swizzle", a=a, b=b, seconds=round(seconds, 6))

    def _swizzled_now(self, src: SimProcess, dst: SimProcess) -> bool:
        until = self._swizzled.get((src.machine, dst.machine), 0.0)
        return until > self.sched.now()

    def _delivery_delay(self, src: SimProcess, dst: SimProcess) -> float:
        lat = self.min_latency + self.rng.random01() * (
            self.max_latency - self.min_latency)
        if buggify("net/extra_latency"):
            # occasional pathological latency: reorders far more
            # aggressively than the uniform draw (ref: sim2's BUGGIFY'd
            # connection delays)
            lat += self.rng.random01() * self._knobs.sim_clog_extra_latency
        if self._swizzled_now(src, dst):
            # swizzled link: a wide uniform draw scrambles delivery
            # order far beyond the base latency jitter
            lat += self.rng.random01() * self._knobs.chaos_swizzle_latency
        now = self.sched.now()
        unclog = max(self._clogged.get((src.machine, dst.machine), 0.0),
                     self._clog_send.get(src.machine, 0.0),
                     self._clog_recv.get(dst.machine, 0.0))
        if unclog > now:
            lat += unclog - now
        return lat

    # -- delivery -------------------------------------------------------
    def send_request(self, src: SimProcess, dst: Endpoint, request) -> Future:
        reply = Promise()
        dst.process._track_reply(reply)
        self._deliver(src, dst, (self._wire(request),
                                 _NetReply(self, dst.process, src, reply,
                                           type(request).__name__)),
                      reply)
        return reply.future

    def send_oneway(self, src: SimProcess, dst: Endpoint, request) -> None:
        request = self._wire(request)
        self._deliver(src, dst, (request, None), None)
        if buggify("net/duplicate_oneway"):
            # best-effort datagrams may be delivered twice (receivers
            # must be idempotent, e.g. TLog pops)
            self._deliver(src, dst, (request, None), None)
        elif self._swizzled_now(src, dst.process) and \
                self.rng.random01() < self._knobs.chaos_swizzle_dup_prob:
            # a swizzled link duplicates datagrams too — each copy
            # draws its own (scrambled) latency, so the duplicate may
            # arrive FIRST
            self.messages_duplicated += 1
            self._deliver(src, dst, (request, None), None)

    def _deliver(self, src: SimProcess, dst: Endpoint, item,
                 reply: Optional[Promise]) -> None:
        self.messages_sent += 1
        if self.msg_stats is not None:
            self._count_msg(type(item[0]).__name__)
        if not src.alive:
            return  # a dead process sends nothing
        delay = self._delivery_delay(src, dst.process)
        # delivery deadlines ride Scheduler.call_at: a plain (time,
        # seq, callback) heap entry instead of a _TimerFuture + closure
        # + on_ready chain per message (ISSUE 12's wire-path diet —
        # same shared seq counter, so delivery order is unchanged)
        if self.partitioned(src.machine, dst.process.machine):
            # the message never crosses; the requester sees a reset
            # after the wire latency (ref: sim2 failing the connection —
            # NOT an instant error, or partitions would be cheaper than
            # real ones and failure detection would look too good)
            self.messages_dropped += 1
            if reply is not None:
                self.sched.call_at(delay, _break_reply, reply)
            return
        self.sched.call_at(delay, self._deliver_now, dst, item, reply)

    def _deliver_now(self, dst: Endpoint, item, reply) -> None:
        """The delivery deadline fired (runs from the timer pump)."""
        if not dst.process.alive:
            # connection failure surfaces as broken_promise to the
            # requester (after the latency, like a RST would)
            self.messages_dropped += 1
            if reply is not None and not reply.is_set:
                reply.send_error(error("broken_promise"))
            return
        stream = dst.process._streams.get(dst.token)
        if stream is None:
            if reply is not None and not reply.is_set:
                reply.send_error(error("broken_promise"))
            return
        stream.send(item)


class _NetReply:
    """Reply promise that routes back through the network with latency.

    Breaks (broken_promise) if the replying process dies first — tracked
    via SimProcess._pending_replies."""

    __slots__ = ("net", "owner", "dst", "promise", "rtype")

    def __init__(self, net: SimNetwork, owner: SimProcess, dst: SimProcess,
                 promise: Promise, rtype: str = "?"):
        self.net = net
        self.owner = owner  # the serving process
        self.dst = dst      # the original requester
        self.promise = promise
        self.rtype = rtype  # request type name (message accounting)

    def _partitioned(self) -> bool:
        """A reply crossing a live partition never lands: break the
        requester's promise after the wire latency instead (the same
        reset a dropped request sees — in-flight replies honor
        partitions and clogs installed after the request went out)."""
        return self.net.partitioned(self.owner.machine, self.dst.machine)

    def send(self, value=None) -> None:
        if self.promise.is_set:
            return
        if not self.owner.alive:
            return  # the kill path already broke the promise
        if self.net.msg_stats is not None:
            self.net._count_msg(self.rtype + ".reply")
        value = self.net._wire(value)
        delay = self.net._delivery_delay(self.owner, self.dst)
        if self._partitioned():
            self.net.messages_dropped += 1
            value = _PARTITION_RESET
        self.net.sched.call_at(delay, _reply_value, self.promise, value)

    def send_error(self, err) -> None:
        if self.promise.is_set:
            return
        if not self.owner.alive:
            return
        if self.net.msg_stats is not None:
            self.net._count_msg(self.rtype + ".reply")
        if self._partitioned():
            self.net.messages_dropped += 1
            err = error("broken_promise")
        delay = self.net._delivery_delay(self.owner, self.dst)
        self.net.sched.call_at(delay, _reply_error, self.promise, err)


_PARTITION_RESET = object()


# call_at callbacks for the reply wire path — module-level so a reply
# in flight costs one heap entry, not a closure per message
def _reply_value(p, value) -> None:
    if p.is_set:
        return
    if value is _PARTITION_RESET:
        p.send_error(error("broken_promise"))
    else:
        p.send(value)


def _reply_error(p, err) -> None:
    if not p.is_set:
        p.send_error(err)


def _break_reply(reply) -> None:
    if not reply.is_set:
        reply.send_error(error("broken_promise"))
