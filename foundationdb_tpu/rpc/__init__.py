"""RPC + deterministic network simulation.

Reference: fdbrpc/ — token-addressed typed endpoints over a swappable
transport (fdbrpc/FlowTransport.actor.cpp:48-113 EndpointMap, :517
deliver), with the simulator implementing the same interface
(fdbrpc/sim2.actor.cpp) so the whole cluster runs single-threaded on
virtual time. Here the simulated transport is the primary runtime; a
real TCP transport can slot in behind the same NetworkRef seam.
"""

from .disk import SimDisk, SimFile
from .network import (
    Endpoint,
    NetworkRef,
    RequestStream,
    SimNetwork,
    SimProcess,
)

__all__ = ["Endpoint", "NetworkRef", "RequestStream", "SimNetwork",
           "SimProcess", "SimDisk", "SimFile"]
