"""TCP gateway: the cluster's client-facing endpoints on real sockets.

Reference: in the reference every role endpoint is served directly by
FlowTransport on the process's listen address, and out-of-process
clients (the C binding linking NativeAPI) reach it by token
(fdbrpc/FlowTransport.actor.cpp:517 deliver; bindings/c/fdb_c.cpp is a
thin ABI over that client). Here the cluster's role endpoints live on
the in-process flow scheduler, so the gateway plays the listen-address
seam: each client-visible endpoint (proxy GRV/commit, storage
get/range/get_key) is assigned a real TCP token whose frames are
forwarded into the role's RequestStream and whose replies travel back
over the same wire format the simulator round-trips.

The describe endpoint (fixed token 1) plays MonitorLeader +
openDatabase: it serves a token-translated ServerDBInfo (proxy and
shard maps), long-polling the ClusterController through the attached
Database when the client's picture went stale — exactly the client
recovery path (fdbclient/MonitorLeader.actor.cpp, NativeAPI
getClientInfo), so an out-of-process client rides epoch recoveries the
same way in-process ones do.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import flow
from ..flow import error
from .tcp import TcpRequestStream, TcpTransport

DESCRIBE_TOKEN = 1


class TcpGateway:
    """Serve a cluster (via its client `Database` handle) over TCP."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 tls=None, protocol: bytes = None):
        self.db = db
        self.transport = TcpTransport(host, port, tls=tls,
                                      protocol=protocol)
        self._describe = TcpRequestStream(self.transport)
        assert self._describe.token == DESCRIBE_TOKEN, \
            "describe must be the transport's first registered endpoint"
        #: (process name, sim token) -> tcp token
        self._exposed: Dict[Tuple[str, int], int] = {}
        self._actors: List[object] = []

    @property
    def port(self) -> int:
        return self.transport.port

    def start(self) -> None:
        self.transport.start()
        self._actors.append(flow.spawn(
            self._describe_loop(), name=f"gateway:{self.port}.describe"))

    def close(self) -> None:
        self.transport.close()
        for a in self._actors:
            a.cancel()
        self._actors.clear()

    # -- endpoint exposure ----------------------------------------------
    def _expose(self, ref) -> int:
        """TCP token for a sim NetworkRef, forwarding frames to it.

        Tokens are cached by (process, sim-token) identity: after a
        recovery the same describe tokens keep working for surviving
        roles, while new-epoch roles get fresh tokens in the next
        describe — dead tokens answer broken_promise, which the client
        treats as a stale-picture refresh signal.
        """
        ep = ref.endpoint
        key = (ep.process.name, ep.token)
        token = self._exposed.get(key)
        if token is None:
            stream = TcpRequestStream(self.transport)
            token = stream.token
            self._exposed[key] = token
            self._actors.append(flow.spawn(
                self._forward_loop(stream, ref),
                name=f"gateway:{self.port}.fwd.{ep.process.name}"))
        return token

    async def _forward_loop(self, stream: TcpRequestStream, ref) -> None:
        while True:
            req, reply = await stream.pop()
            flow.spawn(self._forward_one(ref, req, reply))

    async def _forward_one(self, ref, req, reply) -> None:
        try:
            reply.send(await ref.get_reply(req, self.db.process))
        except flow.FdbError as e:
            reply.send_error(e)
        except Exception:  # noqa: BLE001 — a bad frame fails only itself
            reply.send_error(error("internal_error"))

    # -- describe --------------------------------------------------------
    async def _describe_loop(self) -> None:
        while True:
            req, reply = await self._describe.pop()
            flow.spawn(self._describe_one(req, reply))

    async def _describe_one(self, min_seq, reply) -> None:
        """Request payload: the newest dbinfo seq the client has seen
        (-1 for "whatever is current"). A non-negative seq long-polls
        the CC until the broadcast picture moves past it (the client's
        post-failure refresh), mirroring Database.refresh_past."""
        try:
            if isinstance(min_seq, int) and min_seq >= 0:
                await self.db.refresh_past(min_seq)
            info = await self.db.info()
            reply.send(self._translate(info))
        except flow.FdbError as e:
            reply.send_error(e)
        except Exception:  # noqa: BLE001
            reply.send_error(error("internal_error"))

    def _translate(self, info) -> dict:
        """ServerDBInfo with every NetworkRef replaced by a TCP token
        (refs themselves cannot cross this wire: their encoding names a
        sim process, meaningless to an out-of-process peer)."""
        return {
            "seq": info.seq,
            "epoch": info.epoch,
            "recovery_state": info.recovery_state,
            "failed": list(info.failed),
            # control plane (ref: StatusClient / ManagementAPI reach the
            # CC the same way data ops reach the roles)
            "status": (self._expose(self.db.status_ref)
                       if self.db.status_ref is not None else 0),
            "management": (self._expose(self.db.management_ref)
                           if self.db.management_ref is not None else 0),
            "proxies": [
                {"name": p.name,
                 "grvs": self._expose(p.grvs),
                 "commits": self._expose(p.commits)}
                for p in info.proxies],
            "shards": [
                {"begin": s.begin,
                 "end": s.end if s.end is not None else b"",
                 "has_end": s.end is not None,
                 "replicas": [
                     {"name": r.name,
                      "gets": self._expose(r.gets),
                      "ranges": self._expose(r.ranges),
                      "get_keys": self._expose(r.get_keys),
                      "watches": self._expose(r.watches)}
                     for r in s.replicas]}
                for s in info.storages],
        }
